"""Event-log aggregation — the history-server analogue.

``python -m matrel_tpu history [--last N] [--summary] [--log PATH]``
replays a JSONL event log (obs/events.py) into per-query and
per-strategy tables, the way the reference's Spark history server
replays an event log into the UI. Plain text out; no state kept.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from matrel_tpu.obs.events import read_events, resolve_path
from matrel_tpu.obs import metrics as metrics_lib


def _fmt(v, nd=2) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render_queries(events: List[dict], last: Optional[int] = None) -> str:
    """Per-query table (most recent last), one row per query record."""
    qs = [e for e in events if e.get("kind") == "query"]
    if last is not None:
        # qs[-0:] would be the WHOLE list — 0 must mean "none"
        qs = qs[-last:] if last > 0 else []
    if not qs:
        return "no query events"
    header = (f"{'query_id':<18}{'src':<5}{'cache':<6}{'opt_ms':>8}"
              f"{'exec_ms':>9}  {'strategies':<22}{'out_shape'}")
    lines = [header, "-" * len(header)]
    for e in qs:
        strats = ",".join(sorted({d.get("strategy", "?")
                                  for d in e.get("matmuls", [])})) or "-"
        shape = "x".join(str(s) for s in e.get("out_shape", [])) or "-"
        lines.append(
            f"{e.get('query_id', '?'):<18}{e.get('source', '?'):<5}"
            f"{e.get('cache', '?'):<6}{_fmt(e.get('optimize_ms')):>8}"
            f"{_fmt(e.get('execute_ms')):>9}  {strats:<22}{shape}")
    return "\n".join(lines)


def summarize(events: List[dict]) -> dict:
    """Aggregate a log into the per-query / per-strategy roll-up the
    papers' strategy-win tables come from."""
    qs = [e for e in events if e.get("kind") == "query"]
    hits = sum(1 for e in qs if e.get("cache") == "hit")
    exec_ms = [e["execute_ms"] for e in qs
               if isinstance(e.get("execute_ms"), (int, float))]
    strategies: Dict[str, dict] = {}
    rule_hits: Dict[str, int] = {}
    tiers: Dict[str, dict] = {}
    spk: Dict[str, dict] = {}
    # whole-plan fusion roll-up (round 12): region counts, member-op
    # census and the modelled dispatch/HBM savings from each query
    # record's plan-level ``fusion`` field (executor._fusion_meta) —
    # the event-log view of what the fusion pass is actually buying
    fusion: dict = {"queries": 0, "regions": 0, "census": {},
                    "est_saved_dispatches": 0,
                    "est_saved_hbm_bytes": 0.0}
    reshards: dict = {"matmuls": 0, "steps": {}, "bytes_x": 0.0,
                      "bytes_y": 0.0, "peak_bytes": 0.0}
    for e in qs:
        fus = e.get("fusion")
        if isinstance(fus, dict) and fus.get("regions"):
            fusion["queries"] += 1
            fusion["regions"] += int(fus.get("regions") or 0)
            for k, v in (fus.get("census") or {}).items():
                fusion["census"][k] = fusion["census"].get(k, 0) \
                    + int(v)
            fusion["est_saved_dispatches"] += int(
                fus.get("est_saved_dispatches") or 0)
            fusion["est_saved_hbm_bytes"] += float(
                fus.get("est_saved_hbm_bytes") or 0.0)
        for d in e.get("matmuls", []):
            # staged-reshard roll-up (round 10): step kinds, per-axis
            # bytes and the worst per-device peak across every staged
            # move in the log — the event-log view of what the reshard
            # planner is actually doing (and the regression signal
            # when a layout change starts paying a gather it didn't)
            rr = d.get("reshard")
            if isinstance(rr, dict):
                reshards["matmuls"] += 1
                for kind in rr.get("steps") or ():
                    reshards["steps"][kind] = \
                        reshards["steps"].get(kind, 0) + 1
                ba = rr.get("bytes_by_axis") or (0.0, 0.0)
                if len(ba) == 2 and all(
                        isinstance(v, (int, float)) for v in ba):
                    reshards["bytes_x"] += ba[0]
                    reshards["bytes_y"] += ba[1]
                if isinstance(rr.get("peak_bytes"), (int, float)):
                    reshards["peak_bytes"] = max(reshards["peak_bytes"],
                                                 rr["peak_bytes"])
            # precision-tier roll-up (round 8): chosen tier + the pass
            # counts the cost model billed, so a tier-selection
            # regression (an "exact" stream suddenly running bf16)
            # surfaces in `history --summary`
            t = d.get("precision_tier")
            if t:
                row = tiers.setdefault(t, {"count": 0, "passes": 0})
                row["count"] += 1
                if isinstance(d.get("est_passes"), int):
                    row["passes"] += d["est_passes"]
            # SpGEMM kernel census (round 11): which registry kernels
            # the planner stamped, over which structure classes, and
            # how often a measured winner overrode the estimate — the
            # event-log view of the specialized-kernel loop (a
            # structure whose census is all "generic" means the
            # classifier never fires; all "estimate" means the
            # autotuner never measured)
            kid = d.get("kernel_id")
            if kid:
                row = spk.setdefault(kid, {"count": 0, "measured": 0,
                                           "structures": {}})
                row["count"] += 1
                if d.get("est_vs_measured") == "measured":
                    row["measured"] += 1
                sc = d.get("structure_class")
                if sc:
                    row["structures"][sc] = \
                        row["structures"].get(sc, 0) + 1
            s = strategies.setdefault(
                d.get("strategy", "?"),
                {"count": 0, "flops": 0.0, "est_ici_bytes": 0.0})
            s["count"] += 1
            if isinstance(d.get("flops"), (int, float)):
                s["flops"] += d["flops"]
            if isinstance(d.get("est_ici_bytes"), (int, float)):
                s["est_ici_bytes"] += d["est_ici_bytes"]
            # per-axis comm bytes (planner.matmul_decisions round 7):
            # rolled up per strategy so a regression that shifts
            # traffic onto the slow DCN axis is visible in the event
            # log even when the total stays flat
            ab = d.get("est_axis_bytes")
            if (isinstance(ab, (list, tuple)) and len(ab) == 2
                    and all(isinstance(v, (int, float)) for v in ab)):
                s["est_axis_bytes_x"] = (s.get("est_axis_bytes_x", 0.0)
                                         + ab[0])
                s["est_axis_bytes_y"] = (s.get("est_axis_bytes_y", 0.0)
                                         + ab[1])
            # SpGEMM dispatch records carry estimated savings vs the
            # densify fallback (planner.matmul_decisions) — rolled up
            # so `make obs-report` shows the win per strategy
            if isinstance(d.get("est_saved_flops"), (int, float)):
                s["est_saved_flops"] = (s.get("est_saved_flops", 0.0)
                                        + d["est_saved_flops"])
            if isinstance(d.get("est_saved_hbm_bytes"), (int, float)):
                s["est_saved_hbm_bytes"] = (
                    s.get("est_saved_hbm_bytes", 0.0)
                    + d["est_saved_hbm_bytes"])
        for rule, n in (e.get("rule_hits") or {}).items():
            rule_hits[rule] = rule_hits.get(rule, 0) + int(n)
    last_cache = qs[-1].get("plan_cache", {}) if qs else {}
    return {
        "queries": len(qs),
        "cache_hits": hits,
        "cache_hit_rate": round(hits / len(qs), 3) if qs else None,
        "rc_hits": sum(1 for e in qs if e.get("cache") == "rc_hit"),
        "ivm": _summarize_ivm(events),
        "alerts": _summarize_alerts(events),
        "fleet": _summarize_fleet(events),
        "serve": _summarize_serve(events),
        "cse": _summarize_cse(events),
        "spill": _summarize_spill(events),
        "cost_model": _summarize_cost_model(events),
        "lockdep": _summarize_lockdep(events),
        "resilience": _summarize_resilience(events, len(qs)),
        "overload": _summarize_overload(events),
        "execute_ms_total": round(sum(exec_ms), 3),
        "execute_ms_mean": (round(sum(exec_ms) / len(exec_ms), 3)
                            if exec_ms else None),
        "phase_quantiles": _phase_quantiles(qs),
        "plan_cache": last_cache,
        "strategies": strategies,
        "precision_tiers": tiers,
        "spgemm_kernels": spk,
        "fusion": fusion if fusion["queries"] else None,
        "reshards": reshards if reshards["matmuls"] else None,
        "rule_hits": rule_hits,
        "bench_runs": sum(1 for e in events if e.get("kind") == "bench"),
        "bench_errors": _last_bench_errors(events),
        "soak_runs": sum(1 for e in events if e.get("kind") == "soak"),
        "span_count": sum(1 for e in events if e.get("kind") == "span"),
        "verify_runs": sum(1 for e in events
                           if e.get("kind") == "verify"),
        "verify_diagnostics": sum(
            int(e.get("count", 0)) for e in events
            if e.get("kind") == "verify"),
    }


#: Per-query phase fields the quantile roll-up covers.
_PHASE_FIELDS = ("optimize_ms", "trace_ms", "execute_ms")


def _phase_quantiles(qs: List[dict]) -> Dict[str, dict]:
    """p50/p95 of optimize/trace/execute milliseconds PER QUERY KIND
    (root_kind) — the serve roll-up's nearest-rank helper applied to
    the query phases, so a latency regression in one query shape is
    visible instead of drowning in the global mean. Cache-hit records
    repeat their plan's compile-time optimize/trace values by design
    (the numbers describe the plan that ran); execute_ms is always
    this run's own."""
    by_kind: Dict[str, Dict[str, list]] = {}
    for e in qs:
        kind = str(e.get("root_kind") or "?")
        rows = by_kind.setdefault(kind,
                                  {f: [] for f in _PHASE_FIELDS})
        for f in _PHASE_FIELDS:
            v = e.get(f)
            if isinstance(v, (int, float)):
                rows[f].append(float(v))
    out: Dict[str, dict] = {}
    for kind, rows in by_kind.items():
        entry: dict = {"count": max(len(rows[f])
                                    for f in _PHASE_FIELDS)}
        for f in _PHASE_FIELDS:
            vals = sorted(rows[f])
            entry[f] = {"p50": _pctile(vals, 0.50),
                        "p95": _pctile(vals, 0.95)}
        out[kind] = entry
    return out


def _last_bench_errors(events: List[dict]) -> Dict[str, dict]:
    """Most recent ``bench_error`` record per metric — the relay-wedge
    trail bench.py leaves when a probe fails (today that failure lives
    only in the BENCH_*.json tail string; here the roll-up surfaces
    it next to the successful runs)."""
    out: Dict[str, dict] = {}
    for e in events:
        if e.get("kind") != "bench_error":
            continue
        out[str(e.get("metric") or "?")] = {
            "ts": e.get("ts"),
            "error": str(e.get("error") or "")[:300],
            "attempts": e.get("attempts"),
            "last_known_good": e.get("last_known_good"),
        }
    return out


def _pctile(vals: List[float], q: float):
    """Quantile through the SHARED sketch definition
    (obs/metrics.percentile) — the round-15 fix: history used to
    nearest-rank over raw lists per invocation while the live plane
    reported sketch estimates, so the offline replay and `top` could
    disagree on the same data. Now both report ONE definition, pinned
    to agree with the nearest-rank oracle within the sketch's
    documented relative error (tests). None when empty."""
    return metrics_lib.percentile(vals, q)


def _summarize_serve(events: List[dict]) -> dict:
    """Roll up ``serve`` records (session.run_many / the submit
    pipeline — one per micro-batched admission) into the serving
    headline numbers: QPS over the batches' own wall clocks, the
    result-cache hit ratio, and queue-latency percentiles."""
    sv = [e for e in events if e.get("kind") == "serve"]
    queries = sum(int(e.get("batch_size") or 0) for e in sv)
    wall_ms = sum(float(e.get("wall_ms") or 0.0) for e in sv)
    waits = sorted(
        float(w) for e in sv for w in (e.get("queue_wait_ms") or ())
        if isinstance(w, (int, float)))
    # hit ratio from PER-RECORD deltas (rc_hits/batch_size), summed
    # over the whole log like every other roll-up here — the snapshot
    # counters inside "result_cache" are session-lifetime cumulative,
    # so reading only the last record's would discard every earlier
    # session's behaviour in a multi-session log (and mix in non-serve
    # sess.run() consults). The last snapshot still rides along for
    # the eviction/invalidation display.
    rc_hits = sum(int(e.get("rc_hits") or 0) for e in sv)
    rc = sv[-1].get("result_cache", {}) if sv else {}
    return {
        "batches": len(sv),
        "queries": queries,
        "qps": (round(queries / (wall_ms / 1e3), 2) if wall_ms > 0
                else None),
        "rc_hit_ratio": (round(rc_hits / queries, 3) if queries
                         else None),
        "queue_wait_p50_ms": _pctile(waits, 0.50),
        "queue_wait_p95_ms": _pctile(waits, 0.95),
        "result_cache": rc,
    }


def _summarize_cse(events: List[dict]) -> Optional[dict]:
    """Roll up the multi-query-optimization deltas (round 17:
    serve/mqo.py; docs/SERVING.md) — ``cse_hoisted``/``template_hits``
    ride each serve record only when ``config.cse_enable`` is on, and
    query events stamped ``cache="template_hit"`` prove the zero
    optimize/trace steady state. None when no record carries either
    (CSE off, or a pre-round-17 log), so historical summaries render
    byte-identically."""
    sv = [e for e in events if e.get("kind") == "serve"
          and ("cse_hoisted" in e or "template_hits" in e)]
    tpl_q = sum(1 for e in events if e.get("kind") == "query"
                and e.get("cache") == "template_hit")
    if not sv and not tpl_q:
        return None
    return {
        "batches": len(sv),
        "hoisted": sum(int(e.get("cse_hoisted") or 0) for e in sv),
        "template_hits": sum(int(e.get("template_hits") or 0)
                             for e in sv),
        "template_hit_queries": tpl_q,
    }


def _summarize_spill(events: List[dict]) -> Optional[dict]:
    """Roll up the ``spill`` records (serve/spill.py;
    docs/DURABILITY.md): demotion/promotion traffic by tier, the
    measured transfer bytes/ms per leg (the drift loop's raw feed),
    and the save_state/restore lifecycle. None when the log carries
    no spill traffic — a pre-durability (or ``spill_enable=False``)
    log renders byte-identically."""
    sp = [e for e in events if e.get("kind") == "spill"]
    if not sp:
        return None
    out = {"demoted": 0, "aged_to_disk": 0, "promoted": {},
           "legs": {}, "save_states": 0, "restores": 0,
           "restored_entries": 0}
    for e in sp:
        op = e.get("op")
        if op == "demote":
            out["demoted"] += 1
            out["aged_to_disk"] += int(e.get("aged_to_disk") or 0)
        elif op == "promote":
            t = str(e.get("tier") or "?")
            out["promoted"][t] = out["promoted"].get(t, 0) + 1
        elif op == "save_state":
            out["save_states"] += 1
        elif op == "restore":
            out["restores"] += 1
            out["restored_entries"] += int(e.get("rc_entries") or 0)
        for leg in e.get("legs") or ():
            if not isinstance(leg, dict):
                continue
            row = out["legs"].setdefault(
                str(leg.get("leg") or "?"), {"n": 0, "bytes": 0.0,
                                             "ms": 0.0})
            row["n"] += 1
            row["bytes"] += float(leg.get("bytes") or 0.0)
            row["ms"] += float(leg.get("ms") or 0.0)
    return out


def _summarize_lockdep(events: List[dict]) -> Optional[dict]:
    """Roll up runtime-lockdep diagnostics (utils/lockdep.py;
    docs/CONCURRENCY.md) — ``lockdep`` records ride the obs funnel
    only when ``config.lockdep_enable`` armed the sanitizer, so None
    (and a byte-identical summary) on every default-config log. Any
    recorded inversion/self-deadlock flips ``--summary --check`` to
    exit 1: a lock-order violation in a capture log is a latent
    deadlock, not a statistic."""
    lds = [e for e in events if e.get("kind") == "lockdep"]
    if not lds:
        return None
    by_diag: Dict[str, int] = {}
    locks: Dict[str, int] = {}
    for e in lds:
        d = str(e.get("diag") or "?")
        by_diag[d] = by_diag.get(d, 0) + 1
        for key in ("lock", "held"):
            if e.get(key):
                locks[str(e[key])] = locks.get(str(e[key]), 0) + 1
    inversions = (by_diag.get("inversion", 0)
                  + by_diag.get("self_deadlock", 0))
    return {
        "diagnostics": len(lds),
        "by_diag": by_diag,
        "inversions": inversions,
        "locks": locks,
        "last_msg": str(lds[-1].get("msg") or ""),
    }


def _summarize_resilience(events: List[dict], n_queries: int) -> dict:
    """Roll up ``fault``/``retry``/``degrade`` records (the resilience
    layer's event kinds, docs/RESILIENCE.md) into the rates the serve
    plane's health is judged by: how often queries fault, how often a
    retry saves one, and which degradation rungs are being climbed —
    a rising rung census is a cost-model/kernel regression wearing a
    recovery mechanism's clothes."""
    faults = [e for e in events if e.get("kind") == "fault"]
    retries = [e for e in events if e.get("kind") == "retry"]
    degrades = [e for e in events if e.get("kind") == "degrade"]
    rungs: Dict[str, int] = {}
    for e in degrades:
        lbl = str(e.get("rung_label") or e.get("rung") or "?")
        rungs[lbl] = rungs.get(lbl, 0) + 1
    sites: Dict[str, int] = {}
    for e in faults:
        s = str(e.get("site") or e.get("error") or "?")
        sites[s] = sites.get(s, 0) + 1
    return {
        "faults": len(faults),
        "injected": sum(1 for e in faults if e.get("injected")),
        "retries": len(retries),
        "bisects": sum(1 for e in retries
                       if e.get("scope") == "serve_bisect"),
        "degrades": len(degrades),
        "retry_rate": (round(len(retries) / n_queries, 3)
                       if n_queries else None),
        "rungs": rungs,
        "fault_sites": sites,
    }


def _summarize_cost_model(events: List[dict]) -> Optional[dict]:
    """Cost-model loop roll-up (round 19, docs/COST_MODEL.md): how many
    planner decisions ranked by measured coefficients vs the analytic
    closed forms, the coefficient epoch the log ends on, and the
    re-plan rounds the drift controller actioned. None when the log
    carries no cost-model signal at all (coeff planner off — the
    roll-up key is absent, not zeroed, so default-config reports are
    bit-identical to pre-round-19 output)."""
    counts: Dict[str, int] = {}
    epoch = None
    for e in events:
        if e.get("kind") != "query":
            continue
        if e.get("coeff_epoch"):
            epoch = e["coeff_epoch"]
        for d in e.get("matmuls") or ():
            c = d.get("cost")
            if c:
                counts[c] = counts.get(c, 0) + 1
    replans = [e for e in events if e.get("kind") == "replan"]
    if not counts and epoch is None and not replans:
        return None
    rewarmed = sum(int(e.get("replanned") or 0) for e in replans)
    out = {"measured": counts.get("measured", 0),
           "analytic": counts.get("analytic", 0),
           "epoch": epoch,
           "replans": len(replans),
           "rewarmed": rewarmed}
    if replans:
        last = replans[-1]
        out["last_replan"] = {"classes": last.get("classes"),
                              "epoch": last.get("epoch")}
    return out


def _summarize_ivm(events: List[dict]) -> Optional[dict]:
    """Roll up ``delta`` records (one per session.register_delta —
    serve/ivm.py; docs/IVM.md) into the incremental-view-maintenance
    headline: how many cached entries were patched in place vs killed
    (the historical behaviour), how often a compiled patch plan was
    REUSED with rebound leaves (the steady-state stream path), the
    per-rule census, and the modelled FLOPs the patches avoided.
    Per-record fields are per-generation deltas, so summing is correct
    across sessions (the serve roll-up's discipline). None when the
    delta plane was never used — the summary stays byte-identical for
    historical logs."""
    dv = [e for e in events if e.get("kind") == "delta"]
    if not dv:
        return None
    rules: Dict[str, int] = {}
    patched = killed = rekeyed = priced_out = reused = 0
    saved = 0.0
    names: Dict[str, int] = {}
    for e in dv:
        patched += int(e.get("patched") or 0)
        killed += int(e.get("killed") or 0)
        rekeyed += int(e.get("rekeyed") or 0)
        priced_out += int(e.get("priced_out") or 0)
        reused += int(e.get("reused_plans") or 0)
        saved += float(e.get("est_saved_flops") or 0.0)
        names[str(e.get("name") or "?")] = \
            names.get(str(e.get("name") or "?"), 0) + 1
        for r, n in (e.get("rules") or {}).items():
            rules[r] = rules.get(r, 0) + int(n)
    examined = patched + killed
    return {
        "registers": len(dv),
        "patched": patched,
        "killed": killed,
        "priced_out": priced_out,
        "rekeyed": rekeyed,
        "reused_plans": reused,
        "patch_rate": (round(patched / examined, 3) if examined
                       else None),
        "est_saved_gflops": round(saved / 1e9, 3),
        "rules": rules,
        "names": names,
    }


def _summarize_fleet(events: List[dict]) -> Optional[dict]:
    """Multi-slice fleet roll-up (docs/FLEET.md): placement census
    from the per-submission ``placement`` records, lifecycle counts
    from ``fleet`` records, and a PER-SLICE query/serve breakdown
    from the slice tags every slice session stamps on its events.
    None when the log carries no fleet traffic — the summary stays
    byte-identical for single-controller logs."""
    placements = [e for e in events if e.get("kind") == "placement"]
    fleet_evs = [e for e in events if e.get("kind") == "fleet"]
    tagged = [e for e in events
              if e.get("kind") == "query" and e.get("slice")
              is not None]
    if not placements and not fleet_evs and not tagged:
        return None
    routed: Dict[str, int] = {}
    coeff: Dict[str, int] = {}
    for e in placements:
        r = str(e.get("routed") or "?")
        routed[r] = routed.get(r, 0) + 1
        c = str(e.get("coeff_source") or "?")
        coeff[c] = coeff.get(c, 0) + 1
    slices: Dict[str, dict] = {}
    for e in tagged:
        s = slices.setdefault(str(e["slice"]),
                              {"queries": 0, "rc_hits": 0,
                               "execute_ms": 0.0})
        s["queries"] += 1
        if e.get("cache") == "rc_hit":
            s["rc_hits"] += 1
        if isinstance(e.get("execute_ms"), (int, float)):
            s["execute_ms"] += e["execute_ms"]
    lifecycle: Dict[str, int] = {}
    for e in fleet_evs:
        k = str(e.get("event") or "?")
        lifecycle[k] = lifecycle.get(k, 0) + 1
    return {
        "placements": len(placements),
        "routed": routed,
        "coeff_sources": coeff,
        "directory_hits": routed.get("directory", 0)
        + routed.get("directory_remote", 0),
        "remote_hits": routed.get("directory_remote", 0),
        "lifecycle": lifecycle,
        "slices": slices,
    }


def _summarize_alerts(events: List[dict]) -> Optional[dict]:
    """Roll up ``alert`` records (SLO burn-rate alert TRANSITIONS —
    obs/slo.py fire/clear edges) into the per-tenant SLO view: alert
    counts, last-known state per (tenant, objective), the last
    reported attainment (worst across a tenant's objectives), and the
    un-cleared set — what ``history --summary --check`` (and `make
    obs-report`) exits nonzero on. None when no alert ever fired —
    historical logs summarize byte-identically."""
    al = [e for e in events if e.get("kind") == "alert"]
    if not al:
        return None
    last: Dict[tuple, dict] = {}
    fired_by_tenant: Dict[str, int] = {}
    fired = cleared = 0
    for e in al:
        tenant = str(e.get("tenant") or "?")
        last[(tenant, str(e.get("objective") or "?"))] = e
        if e.get("state") == "firing":
            fired += 1
            fired_by_tenant[tenant] = \
                fired_by_tenant.get(tenant, 0) + 1
        elif e.get("state") == "clear":
            cleared += 1
    tenants: Dict[str, dict] = {}
    for (t, o), e in sorted(last.items()):
        row = tenants.setdefault(
            t, {"fired": fired_by_tenant.get(t, 0),
                "attainment": None, "objectives": {}})
        row["objectives"][o] = str(e.get("state") or "?")
        att = e.get("attainment")
        if isinstance(att, (int, float)):
            row["attainment"] = (att if row["attainment"] is None
                                 else min(row["attainment"], att))
    uncleared = [f"{t}:{o}" for (t, o), e in sorted(last.items())
                 if e.get("state") == "firing"]
    return {"events": len(al), "fired": fired, "cleared": cleared,
            "uncleared": uncleared, "tenants": tenants}


def _summarize_overload(events: List[dict]) -> Optional[dict]:
    """Roll up ``overload`` records (one per admission cycle while the
    control plane is active — serve/pipeline.py; docs/OVERLOAD.md)
    into the numbers saturation is judged by: per-tenant shed rate and
    p99 queue wait, the brownout rung census, and breaker
    open/half-open/close transition counts. Shed/purge/transition
    fields on each record are PER-CYCLE DELTAS (the serve roll-up's
    multi-session discipline), so summing them is correct across
    sessions; rung/depth fields are instantaneous."""
    ov = [e for e in events if e.get("kind") == "overload"]
    if not ov:
        return None
    rungs: Dict[str, int] = {}
    tenants: Dict[str, dict] = {}
    trans = {"open": 0, "half_open": 0, "close": 0}
    purged = stale = misses = 0
    for e in ov:
        rungs[str(e.get("rung", 0))] = \
            rungs.get(str(e.get("rung", 0)), 0) + 1
        purged += int(e.get("purged_expired") or 0)
        stale += int(e.get("stale_served") or 0)
        misses += int(e.get("deadline_misses") or 0)
        for t, n in (e.get("admitted") or {}).items():
            row = tenants.setdefault(
                t, {"admitted": 0, "sheds": 0, "waits": []})
            row["admitted"] += int(n)
        for t, n in (e.get("sheds") or {}).items():
            row = tenants.setdefault(
                t, {"admitted": 0, "sheds": 0, "waits": []})
            row["sheds"] += int(n)
        for t, ws in (e.get("tenant_waits_ms") or {}).items():
            row = tenants.setdefault(
                t, {"admitted": 0, "sheds": 0, "waits": []})
            row["waits"].extend(float(w) for w in ws
                                if isinstance(w, (int, float)))
        br = e.get("breakers") or {}
        for k, n in (br.get("transitions") or {}).items():
            if k in trans:
                trans[k] += int(n)
    out_tenants: Dict[str, dict] = {}
    for t, row in tenants.items():
        seen = row["admitted"] + row["sheds"]
        waits = sorted(row["waits"])
        out_tenants[t or "(default)"] = {
            "admitted": row["admitted"],
            "sheds": row["sheds"],
            "shed_rate": (round(row["sheds"] / seen, 3) if seen
                          else None),
            "queue_wait_p99_ms": _pctile(waits, 0.99),
        }
    last_br = (ov[-1].get("breakers") or {})
    return {
        "cycles": len(ov),
        "rungs": rungs,
        "max_rung": max((int(e.get("rung") or 0) for e in ov),
                        default=0),
        "tenants": out_tenants,
        "purged_expired": purged,
        "stale_served": stale,
        "deadline_misses": misses,
        "breaker_transitions": trans,
        "breakers_open_now": last_br.get("open") or [],
    }


def render_summary(events: List[dict]) -> str:
    s = summarize(events)
    lines = [
        f"queries: {s['queries']}  cache hit rate: "
        f"{_fmt(s['cache_hit_rate'], 3)}  "
        f"(evicted: {s['plan_cache'].get('evicted', 0)})",
        f"execute_ms: total {_fmt(s['execute_ms_total'])}  "
        f"mean {_fmt(s['execute_ms_mean'])}",
        f"other events: bench={s['bench_runs']} soak={s['soak_runs']} "
        f"verify={s['verify_runs']}"
        + (f" ({s['verify_diagnostics']} diagnostic(s))"
           if s["verify_diagnostics"] else "")
        + (f" spans={s['span_count']}" if s.get("span_count") else ""),
    ]
    for metric, err in sorted((s.get("bench_errors") or {}).items()):
        lkg = err.get("last_known_good") or {}
        lines.append(
            f"LAST BENCH ERROR [{metric}]: {err['error']}"
            + (f" (last known good: {lkg.get('tflops', lkg)})"
               if lkg else ""))
    pq = s.get("phase_quantiles") or {}
    if pq:
        lines.append("")
        header = (f"{'query kind':<14}{'n':>5}"
                  f"{'opt p50/p95':>16}{'trace p50/p95':>16}"
                  f"{'exec p50/p95':>16}")
        lines += [header, "-" * len(header)]
        for kind in sorted(pq):
            q = pq[kind]
            cells = "".join(
                f"{_fmt(q[f]['p50'])}/{_fmt(q[f]['p95'])}".rjust(16)
                for f in ("optimize_ms", "trace_ms", "execute_ms"))
            lines.append(f"{kind:<14}{q['count']:>5}{cells} ms")
    rs = s.get("resilience") or {}
    if rs.get("faults") or rs.get("retries") or rs.get("degrades"):
        line = (f"resilience: {rs['faults']} fault(s) "
                f"({rs['injected']} injected), {rs['retries']} "
                f"retrie(s) (rate {_fmt(rs['retry_rate'], 3)}), "
                f"{rs['degrades']} degrade(s)")
        if rs.get("bisects"):
            line += f", {rs['bisects']} serve bisection(s)"
        if rs.get("rungs"):
            line += "; rungs: " + ", ".join(
                f"{k}={v}" for k, v in sorted(rs["rungs"].items()))
        if rs.get("fault_sites"):
            line += "; sites: " + ", ".join(
                f"{k}={v}" for k, v in sorted(
                    rs["fault_sites"].items()))
        lines.append(line)
    fl = s.get("fleet")
    if fl:
        line = (f"fleet: {fl['placements']} placement(s)"
                + ("; routed: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(fl["routed"].items()))
                   if fl["routed"] else "")
                + (f"; {fl['directory_hits']} directory hit(s) "
                   f"({fl['remote_hits']} remote)"
                   if fl["directory_hits"] else ""))
        if fl.get("coeff_sources"):
            line += "; coeffs: " + ", ".join(
                f"{k}={v}"
                for k, v in sorted(fl["coeff_sources"].items()))
        if fl.get("lifecycle"):
            line += "; events: " + ", ".join(
                f"{k}={v}"
                for k, v in sorted(fl["lifecycle"].items()))
        lines.append(line)
        if fl.get("slices"):
            header = (f"{'slice':<8}{'queries':>9}{'rc hits':>9}"
                      f"{'exec ms':>11}")
            lines += [header, "-" * len(header)]
            for sid in sorted(fl["slices"]):
                d = fl["slices"][sid]
                lines.append(
                    f"{sid:<8}{d['queries']:>9}{d['rc_hits']:>9}"
                    f"{_fmt(d['execute_ms']):>11}")
    ov = s.get("overload")
    if ov:
        line = (f"overload: {ov['cycles']} cycle(s), max rung "
                f"{ov['max_rung']}; rungs: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(ov["rungs"].items()))
                + f"; purged {ov['purged_expired']} expired, "
                  f"{ov['stale_served']} stale-served, "
                  f"{ov['deadline_misses']} deadline miss(es)")
        bt = ov.get("breaker_transitions") or {}
        if any(bt.values()) or ov.get("breakers_open_now"):
            line += ("; breakers: " + ", ".join(
                f"{k}={v}" for k, v in sorted(bt.items())))
            if ov.get("breakers_open_now"):
                line += (" (open now: "
                         + ", ".join(ov["breakers_open_now"]) + ")")
        lines.append(line)
        if ov.get("tenants"):
            # SLO-attainment + alert-count columns (round 15) ride
            # the per-tenant roll-up, sourced from the `alert` events
            al = s.get("alerts") or {}
            al_t = al.get("tenants") or {}
            header = (f"{'tenant':<14}{'admitted':>9}{'sheds':>7}"
                      f"{'shed rate':>11}{'wait p99':>10}"
                      f"{'slo attain':>12}{'alerts':>8}")
            lines += [header, "-" * len(header)]
            for t in sorted(ov["tenants"]):
                d = ov["tenants"][t]
                a = al_t.get(t, {})
                lines.append(
                    f"{t:<14}{d['admitted']:>9}{d['sheds']:>7}"
                    f"{_fmt(d['shed_rate'], 3):>11}"
                    f"{_fmt(d['queue_wait_p99_ms']):>7} ms"
                    f"{_fmt(a.get('attainment'), 4):>12}"
                    f"{_fmt(a.get('fired') if a else None):>8}")
    al = s.get("alerts")
    if al:
        line = (f"slo alerts: {al['fired']} fired / {al['cleared']} "
                f"cleared")
        if al["uncleared"]:
            line += ("; UNCLEARED: " + ", ".join(al["uncleared"])
                     + " (--check exits nonzero)")
        lines.append(line)
    ivm = s.get("ivm")
    if ivm:
        lines.append(
            f"ivm: {ivm['registers']} delta(s), {ivm['patched']} "
            f"patched / {ivm['killed']} killed "
            f"({ivm['priced_out']} priced out; patch rate "
            f"{_fmt(ivm['patch_rate'], 3)}), {ivm['reused_plans']} "
            f"plan reuse(s), {ivm['rekeyed']} rekeyed, est saved "
            f"{_fmt(ivm['est_saved_gflops'])} GFLOPs"
            + ("; rules: " + ", ".join(
                f"{k}={v}" for k, v in sorted(ivm["rules"].items()))
               if ivm.get("rules") else ""))
    sv = s.get("serve") or {}
    if sv.get("batches"):
        lines.append(
            f"serve: {sv['batches']} batch(es), {sv['queries']} "
            f"queries, QPS {_fmt(sv['qps'])}, result-cache hit ratio "
            f"{_fmt(sv['rc_hit_ratio'], 3)}, queue wait p50/p95 "
            f"{_fmt(sv['queue_wait_p50_ms'])}/"
            f"{_fmt(sv['queue_wait_p95_ms'])} ms"
            + (f" (rc evicted: {sv['result_cache'].get('evicted', 0)}, "
               f"invalidated: "
               f"{sv['result_cache'].get('invalidated', 0)})"
               if sv.get("result_cache") else ""))
    cse = s.get("cse")
    if cse:
        lines.append(
            f"mqo: {cse['hoisted']} interior(s) hoisted over "
            f"{cse['batches']} batch(es), {cse['template_hits']} "
            f"template rebind(s), {cse['template_hit_queries']} "
            f"zero-optimize quer(ies)")
    sp = s.get("spill")
    if sp:
        line = (f"spill: {sp['demoted']} demotion(s) "
                f"({sp['aged_to_disk']} aged to disk)"
                + ("; promoted: " + ", ".join(
                    f"{k}={v}"
                    for k, v in sorted(sp["promoted"].items()))
                   if sp["promoted"] else ""))
        if sp.get("legs"):
            line += "; legs: " + ", ".join(
                f"{k}={v['n']}x{_fmt(v['bytes'] / (1 << 20))}MiB/"
                f"{_fmt(v['ms'])}ms"
                for k, v in sorted(sp["legs"].items()))
        if sp.get("save_states") or sp.get("restores"):
            line += (f"; durability: {sp['save_states']} "
                     f"save_state(s), {sp['restores']} restore(s)")
            if sp.get("restored_entries"):
                line += (f" ({sp['restored_entries']} entr(ies) "
                         f"rethawable)")
        lines.append(line)
    cmod = s.get("cost_model")
    if cmod:
        line = (f"cost model: {cmod['measured']} measured / "
                f"{cmod['analytic']} analytic decision(s)")
        if cmod.get("epoch"):
            line += f", epoch {cmod['epoch']}"
        if cmod.get("replans"):
            line += (f", {cmod['replans']} re-plan round(s) "
                     f"({cmod['rewarmed']} plan(s) re-warmed)")
            lr = cmod.get("last_replan") or {}
            if lr.get("classes"):
                line += ("; last: classes "
                         + ", ".join(lr["classes"])
                         + f" -> epoch {lr.get('epoch')}")
        lines.append(line)
    ld = s.get("lockdep")
    if ld:
        diags = ", ".join(f"{k}: {v}"
                          for k, v in sorted(ld["by_diag"].items()))
        lines.append(
            f"lockdep: {ld['diagnostics']} diagnostic(s) "
            f"({diags}), {ld['inversions']} order inversion(s)"
            + (" — LATENT DEADLOCK (--check exits nonzero)"
               if ld["inversions"] else ""))
    if s["strategies"]:
        lines.append("")
        header = (f"{'strategy':<12}{'matmuls':>8}{'GFLOPs':>10}"
                  f"{'est ICI MiB':>13}")
        lines += [header, "-" * len(header)]
        for name in sorted(s["strategies"],
                           key=lambda k: -s["strategies"][k]["count"]):
            d = s["strategies"][name]
            line = (f"{name:<12}{d['count']:>8}"
                    f"{d['flops'] / 1e9:>10.2f}"
                    f"{d['est_ici_bytes'] / 2**20:>13.2f}")
            if ("est_axis_bytes_x" in d) or ("est_axis_bytes_y" in d):
                line += (f"  axes x/y: "
                         f"{d.get('est_axis_bytes_x', 0.0) / 2**20:.2f}/"
                         f"{d.get('est_axis_bytes_y', 0.0) / 2**20:.2f}"
                         f" MiB")
            if d.get("est_saved_flops") or d.get("est_saved_hbm_bytes"):
                line += (f"  saved: {d.get('est_saved_flops', 0) / 1e9:.2f}"
                         f" GFLOPs / "
                         f"{d.get('est_saved_hbm_bytes', 0) / 2**20:.1f}"
                         f" MiB HBM")
            lines.append(line)
    if s.get("precision_tiers"):
        lines.append("")
        lines.append("precision tiers: " + ", ".join(
            f"{t}={d['count']} ({d['passes']} passes)"
            for t, d in sorted(s["precision_tiers"].items())))
    fus = s.get("fusion")
    if fus:
        lines.append(
            f"fusion: {fus['regions']} region(s) over "
            f"{fus['queries']} query(ies) ["
            + ", ".join(f"{k}={v}"
                        for k, v in sorted(fus["census"].items()))
            + f"], est saved {fus['est_saved_dispatches']} "
              f"dispatch(es) / "
              f"{fus['est_saved_hbm_bytes'] / 2**20:.2f} MiB HBM")
    if s.get("spgemm_kernels"):
        lines.append("")
        lines.append("spgemm kernels: " + ", ".join(
            f"{k}={d['count']}"
            + (f" ({d['measured']} measured)" if d.get("measured")
               else "")
            + (" [" + ", ".join(
                f"{sc}={n}" for sc, n in sorted(
                    d["structures"].items())) + "]"
               if d.get("structures") else "")
            for k, d in sorted(s["spgemm_kernels"].items())))
    rsh = s.get("reshards")
    if rsh:
        lines.append(
            f"reshards: {rsh['matmuls']} staged matmul move(s) ("
            + ", ".join(f"{k}={v}"
                        for k, v in sorted(rsh["steps"].items()))
            + f"), bytes x/y {rsh['bytes_x'] / 2**20:.2f}/"
              f"{rsh['bytes_y'] / 2**20:.2f} MiB, "
              f"peak {rsh['peak_bytes'] / 2**20:.2f} MiB/device")
    if s["rule_hits"]:
        lines.append("")
        lines.append("rewrite-rule hits: " + ", ".join(
            f"{k}={v}" for k, v in sorted(s["rule_hits"].items())))
    return "\n".join(lines)


def main(args) -> int:
    """CLI backend for ``python -m matrel_tpu history``. Path
    precedence matches the writers: ``--log`` beats
    ``$MATREL_OBS_EVENT_LOG`` beats the cwd default — so the reader
    aimed at a host follows the same env var its tools emit under."""
    import os
    path = resolve_path(args.log or os.environ.get("MATREL_OBS_EVENT_LOG"))
    events = read_events(path)
    if not events and not getattr(args, "drift", False):
        print(f"no events in {path}")
        return 0
    print(f"# {len(events)} event(s) in {path}")
    if getattr(args, "drift", False):
        # the cost-model drift auditor (obs/drift.py): calibration
        # ratios + rank-order flags, table persisted next to the
        # autotune tables. --check turns the flags into an exit code
        # so `make obs-report` / CI gate on drift instead of a human
        # reading the table (ROADMAP item 4's first consumable bite)
        from matrel_tpu.obs import drift
        text, flags = drift.audit(
            events,
            table_path_str=getattr(args, "drift_table", None),
            persist=not getattr(args, "no_save", False))
        print(text)
        if getattr(args, "check", False) and flags:
            print(f"DRIFT CHECK FAILED: {len(flags)} rank-order "
                  f"flag(s) — the planner prefers a strategy that "
                  f"measures slower")
            return 1
    elif getattr(args, "coeffs", False):
        # the cost-model loop view (round 19, docs/COST_MODEL.md):
        # rank-order flags the log's samples support, each paired with
        # whether a later `replan` event actioned it. --check turns a
        # firing-but-UNACTIONED flag into a nonzero exit: the drift
        # controller either is not running (coeff_replan_enable off
        # while drift fires) or is wedged — either way the loop is
        # open and `make obs-report` must not read green over it
        from matrel_tpu.obs import drift
        flags = drift.rank_flags(list(drift.iter_samples(events)))
        actioned = set()
        for e in events:
            if e.get("kind") != "replan":
                continue
            for fl in e.get("flags") or ():
                actioned.add((fl.get("class"), fl.get("backend")))
        cmod = _summarize_cost_model(events) or {}
        print(f"cost model: {cmod.get('measured', 0)} measured / "
              f"{cmod.get('analytic', 0)} analytic decision(s), "
              f"epoch {cmod.get('epoch')}, "
              f"{cmod.get('replans', 0)} re-plan round(s)")
        unactioned = []
        for fl in flags:
            key = (fl["class"], fl["backend"])
            done = key in actioned
            if not done:
                unactioned.append(fl)
            print(f"  flag [{fl['class']}|{fl['backend']}]: model "
                  f"prefers {fl['model_prefers']}, measures "
                  f"{fl['slowdown']}x slower than "
                  f"{fl['measured_prefers']} "
                  f"({'actioned' if done else 'UNACTIONED'})")
        if not flags:
            print("  no rank-order flags — model agrees with "
                  "measurement on every sampled population")
        if getattr(args, "check", False) and unactioned:
            print(f"COEFF CHECK FAILED: {len(unactioned)} firing "
                  f"rank-order flag(s) with no re-plan round — the "
                  f"cost-model loop is open")
            return 1
    elif args.summary:
        print(render_summary(events))
        if getattr(args, "check", False):
            # the --drift --check idiom applied to SLO alerts: an
            # alert whose LAST transition is "firing" means the log
            # ends mid-incident — `make obs-report` / CI must not
            # read green over it
            al = _summarize_alerts(events)
            if al and al["uncleared"]:
                print(f"SLO CHECK FAILED: {len(al['uncleared'])} "
                      f"un-cleared alert(s): "
                      + ", ".join(al["uncleared"]))
                return 1
            # same idiom for the concurrency sanitizer: a recorded
            # lock-order inversion is a deadlock that has not
            # happened YET — a capture log carrying one must fail
            # the report, not scroll past in the roll-up
            ld = _summarize_lockdep(events)
            if ld and ld["inversions"]:
                print(f"LOCKDEP CHECK FAILED: {ld['inversions']} "
                      f"lock-order inversion(s) recorded "
                      f"({ld['last_msg']})")
                return 1
    else:
        print(render_queries(events, last=args.last))
    return 0
