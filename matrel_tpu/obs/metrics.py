"""Metrics registry — counters, gauges and timing histograms.

The accumulator/metrics-system analogue of the reference (Spark
accumulators + the metrics registry the UI reads). Thread-safe and
dependency-free: the session, planner and executor record into the
process registry; ``snapshot()`` is the read surface (the event log
embeds slices of it, ``StepTimer.table()`` renders from it).

Design constraints, in order: recording must be cheap (a lock + a few
float ops — it runs once per QUERY, never per element, and never inside
jitted code), values must be aggregatable after the fact (histograms
keep count/total/min/max plus a bounded reservoir of recent samples,
not an unbounded list), and names are plain dotted strings so the log
stays greppable (``plan_cache.hit``, ``query.execute_ms``).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

#: Bounded sample memory per histogram: enough for percentile estimates
#: over a recent window without letting a long-lived server grow a list
#: per metric forever.
_RESERVOIR = 512


class Counter:
    """Monotonic accumulator (the Spark accumulator analogue)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (e.g. cache occupancy)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Timing/size distribution: count, total, min, max + a bounded
    ring of recent samples for percentile estimates."""

    __slots__ = ("_lock", "count", "total", "min", "max", "_ring", "_i")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._ring: List[float] = []
        self._i = 0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._ring) < _RESERVOIR:
                self._ring.append(v)
            else:
                self._ring[self._i] = v
                self._i = (self._i + 1) % _RESERVOIR

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """q in [0, 1], over the bounded recent window (not all-time)."""
        with self._lock:
            window = sorted(self._ring)
        if not window:
            return 0.0
        idx = min(int(q * len(window)), len(window) - 1)
        return window[idx]

    def summary(self) -> dict:
        with self._lock:
            return {"count": self.count,
                    "total": round(self.total, 6),
                    "mean": round(self.mean, 6),
                    "min": self.min, "max": self.max}


class MetricsRegistry:
    """Name → metric map; one lock per registry (recording is per-query,
    not per-element — contention is irrelevant at that rate and a single
    lock keeps snapshot() consistent)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(self._lock)
        return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(self._lock)
        return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(self._lock)
        return h

    def snapshot(self) -> dict:
        """Plain-dict view of every metric — JSON-ready."""
        with self._lock:
            counters = {k: c._value for k, c in self._counters.items()}
            gauges = {k: g._value for k, g in self._gauges.items()}
            hists = list(self._histograms.items())
        return {"counters": counters, "gauges": gauges,
                "histograms": {k: h.summary() for k, h in hists}}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


#: Process-wide default registry — what the session and StepTimer use
#: unless handed a private one.
REGISTRY = MetricsRegistry()
