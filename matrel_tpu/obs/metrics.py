"""Metrics registry — counters, gauges and sketch-backed timing
histograms.

The accumulator/metrics-system analogue of the reference (Spark
accumulators + the metrics registry the UI reads). Thread-safe and
dependency-free: the session, planner and executor record into the
process registry; ``snapshot()`` is the read surface (the event log
embeds slices of it, ``StepTimer.table()`` renders from it, the live
metrics endpoint — obs/export.py — serves it).

Design constraints, in order: recording must be cheap (a lock + a few
float ops — it runs once per QUERY, never per element, and never inside
jitted code), values must be aggregatable after the fact (histograms
keep count/total/min/max plus a bounded, MERGEABLE quantile sketch —
never an unbounded sample list), and names are plain dotted strings so
the log stays greppable (``plan_cache.hit``, ``query.execute_ms``).

The round-15 quantile substrate is :class:`QuantileSketch` — a
DDSketch-style log-bucketed histogram (arXiv:1908.10693's scheme:
geometric buckets, relative-error bound, bucket-count bound enforced by
collapsing the lowest buckets) that replaced the old bounded reservoir:
a reservoir's percentile is exact over a WINDOW but silently forgets
everything older, while the sketch covers the metric's whole lifetime
in bounded memory with a PROVEN bound. Every quantile the repo reports
— the registry's histograms, ``history --summary``'s roll-ups, the live
endpoint, ``matrel_tpu top`` — flows through this one definition
(:func:`percentile`), so an offline replay and the live plane can never
disagree beyond the documented relative error.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, Optional
from matrel_tpu.utils import lockdep

#: Default relative-accuracy target for every timing sketch: a reported
#: quantile x̃_q satisfies |x̃_q − x_q| <= DEFAULT_ALPHA · x_q for the
#: true (nearest-rank, lower) quantile x_q — 1% is far inside what any
#: latency SLO cares about and keeps bucket counts small.
DEFAULT_ALPHA = 0.01

#: Bucket-count bound per sketch (the bounded-memory contract — the
#: old reservoir's 512 slots, now 512 GEOMETRIC buckets ≈ a 1:28000
#: dynamic range at the default alpha). Past it the LOWEST buckets
#: collapse together, so high quantiles — the SLO-bearing ones — keep
#: their bound while the tiny-value tail degrades first.
_MAX_BUCKETS = 512

#: Values at or below this are counted in the zero bucket (timings are
#: nonnegative by domain; exact zeros are legal and common for cache
#: hits). Negative inputs clamp here too.
_MIN_TRACKABLE = 1e-9


class QuantileSketch:
    """Bounded-memory, mergeable quantile sketch over NONNEGATIVE
    values (DDSketch-style log-bucketed histogram).

    A value v > 0 lands in bucket ``ceil(log_γ(v))`` with
    ``γ = (1+α)/(1-α)``; the bucket's midpoint estimate
    ``2·γ^k/(γ+1)`` is within a factor (1±α) of every value the bucket
    holds — THE relative-error bound, asserted by the accuracy battery
    in tests/test_obs.py. ``merge`` adds bucket counts (sketches are a
    commutative monoid — merge order never changes an estimate, also
    test-pinned), so per-thread / per-process sketches aggregate
    exactly like Spark accumulators.

    Not thread-safe on its own — :class:`Histogram` wraps it under the
    registry lock; standalone users (history's replay aggregation)
    are single-threaded.
    """

    __slots__ = ("alpha", "gamma", "_log_gamma", "count", "sum",
                 "min", "max", "zeros", "_buckets", "max_buckets")

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 max_buckets: int = _MAX_BUCKETS):
        if not (0.0 < alpha < 1.0):
            raise ValueError(
                f"QuantileSketch alpha must be in (0, 1), got {alpha!r}")
        if max_buckets < 2:
            raise ValueError(
                f"QuantileSketch needs max_buckets >= 2, "
                f"got {max_buckets!r}")
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self.gamma)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.zeros = 0
        self._buckets: Dict[int, int] = {}
        self.max_buckets = int(max_buckets)

    # -- write side --------------------------------------------------------

    def add(self, value: float, n: int = 1) -> None:
        v = float(value)
        if n <= 0:
            return
        self.count += n
        self.sum += v * n
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if v <= _MIN_TRACKABLE:
            self.zeros += n
            return
        k = math.ceil(math.log(v) / self._log_gamma)
        self._buckets[k] = self._buckets.get(k, 0) + n
        if len(self._buckets) > self.max_buckets:
            self._collapse()

    def _collapse(self) -> None:
        """Fold the lowest bucket into its neighbour above — the
        DDSketch collapse: high quantiles (the SLO-bearing ones) keep
        the bound, the smallest-value tail coarsens first."""
        keys = sorted(self._buckets)
        lo, nxt = keys[0], keys[1]
        self._buckets[nxt] += self._buckets.pop(lo)

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into this sketch (same alpha required —
        bucket keys only line up on one γ). Returns self."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with different alpha "
                f"({self.alpha} vs {other.alpha})")
        self.count += other.count
        self.sum += other.sum
        self.zeros += other.zeros
        for k, n in other._buckets.items():
            self._buckets[k] = self._buckets.get(k, 0) + n
        for v in (other.min, other.max):
            if v is not None:
                self.min = v if self.min is None else min(self.min, v)
                self.max = v if self.max is None else max(self.max, v)
        while len(self._buckets) > self.max_buckets:
            self._collapse()
        return self

    # -- read side ---------------------------------------------------------

    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile estimate (q in [0, 1]); None when empty.
        Matches the nearest-rank (lower) definition — the value at
        0-indexed rank ``floor(q·(count-1))`` — within the documented
        relative error; q == 0 / q == 1 return the EXACT tracked
        min/max."""
        if self.count == 0:
            return None
        q = min(max(float(q), 0.0), 1.0)
        rank = int(q * (self.count - 1))
        if rank <= 0:
            return self.min
        if rank >= self.count - 1:
            return self.max
        if rank < self.zeros:
            return 0.0
        cum = self.zeros
        for k in sorted(self._buckets):
            cum += self._buckets[k]
            if cum > rank:
                est = 2.0 * self.gamma ** k / (self.gamma + 1.0)
                # min/max are tracked exactly — clamping can only
                # move an estimate TOWARD the true value
                return min(max(est, self.min), self.max)
        return self.max      # numerical safety; unreachable in theory

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def summary(self) -> dict:
        """JSON-ready roll-up (the endpoint/`top` payload shape)."""
        return {"count": self.count,
                "sum": round(self.sum, 6),
                "mean": round(self.mean, 6),
                "min": self.min, "max": self.max,
                "p50": self.quantile(0.50),
                "p90": self.quantile(0.90),
                "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def to_dict(self) -> dict:
        """Serialisable form (``from_dict`` round-trips it) — how
        sketches ride JSON snapshots across processes for merging."""
        return {"alpha": self.alpha, "count": self.count,
                "sum": self.sum, "min": self.min, "max": self.max,
                "zeros": self.zeros,
                "buckets": {str(k): n
                            for k, n in sorted(self._buckets.items())}}

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileSketch":
        sk = cls(alpha=float(d.get("alpha", DEFAULT_ALPHA)))
        sk.count = int(d.get("count", 0))
        sk.sum = float(d.get("sum", 0.0))
        sk.min = d.get("min")
        sk.max = d.get("max")
        sk.zeros = int(d.get("zeros", 0))
        sk._buckets = {int(k): int(n)
                       for k, n in (d.get("buckets") or {}).items()}
        return sk


def percentile(values: Iterable[float], q: float,
               alpha: float = DEFAULT_ALPHA) -> Optional[float]:
    """THE shared quantile definition: feed ``values`` through one
    :class:`QuantileSketch` and query it. ``history``'s replay
    roll-ups, the brownout controller's p95 signal and the traffic
    harness all call this, so every quantile the repo reports agrees
    with the live plane's sketches within the documented relative
    error. None when ``values`` is empty."""
    sk = QuantileSketch(alpha)
    for v in values:
        sk.add(v)
    return sk.quantile(q)


class Counter:
    """Monotonic accumulator (the Spark accumulator analogue)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (e.g. cache occupancy)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Timing/size distribution: count, total, min, max + a bounded
    mergeable :class:`QuantileSketch` over ALL observations (the old
    bounded reservoir reported a recent window; the sketch reports the
    metric's lifetime within the documented relative error)."""

    __slots__ = ("_lock", "count", "total", "min", "max", "_sketch")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._sketch = QuantileSketch()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self._sketch.add(v)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """q in [0, 1], over ALL observations (sketch-estimated within
        DEFAULT_ALPHA relative error; q 0/1 exact). 0.0 when empty —
        the historical empty-histogram convention."""
        with self._lock:
            est = self._sketch.quantile(q)
        return 0.0 if est is None else est

    def sketch_summary(self) -> dict:
        """The sketch's quantile roll-up (the endpoint's payload)."""
        with self._lock:
            return self._sketch.summary()

    def summary(self) -> dict:
        with self._lock:
            return {"count": self.count,
                    "total": round(self.total, 6),
                    "mean": round(self.mean, 6),
                    "min": self.min, "max": self.max,
                    "p50": self._sketch.quantile(0.50),
                    "p95": self._sketch.quantile(0.95),
                    "p99": self._sketch.quantile(0.99)}


class MetricsRegistry:
    """Name → metric map; one lock per registry (recording is per-query,
    not per-element — contention is irrelevant at that rate and a single
    lock keeps snapshot() consistent)."""

    def __init__(self):
        self._lock = lockdep.make_lock("obs.metrics_registry")
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(self._lock)
        return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(self._lock)
        return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(self._lock)
        return h

    def snapshot(self) -> dict:
        """Plain-dict view of every metric — JSON-ready."""
        with self._lock:
            counters = {k: c._value for k, c in self._counters.items()}
            gauges = {k: g._value for k, g in self._gauges.items()}
            hists = list(self._histograms.items())
        return {"counters": counters, "gauges": gauges,
                "histograms": {k: h.summary() for k, h in hists}}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


#: Process-wide default registry — what the session and StepTimer use
#: unless handed a private one.
REGISTRY = MetricsRegistry()
