"""Structured JSONL event log — the Spark event-log analogue.

One line per event, append-only, schema-versioned. ``MatrelSession``
emits one ``query`` record per run (plus one ``verify`` record when the
static plan verifier is on — mode, diagnostic count, codes) and one
``serve`` record per micro-batched admission (batch size, queue waits,
result-cache state — session.run_many / the submit pipeline);
``bench.py`` emits ``bench`` records (``bench_error`` on a final probe
failure, carrying the error tail and last-known-good) and
``tools/soak_guard.py`` ``soak`` records into the same file, so one log
replays the whole history of a host (the history-server input —
``python -m matrel_tpu history`` aggregates it). Round 9 adds ``span``
records (parent-linked tracing scopes, obs/trace.py — exported to
Chrome/Perfetto by ``python -m matrel_tpu trace``) and ``analyze``
records (measured per-op trees joined to decision records — the drift
auditor's feed, obs/drift.py).

Writing discipline mirrors the repo's other append-only logs
(PROGRESS.jsonl, SOAKLOG.jsonl): a single ``write()`` of one line per
event (atomic for sane line sizes on POSIX), emission failures are
swallowed after a one-time warning — observability must never fail a
query — and every record carries ``schema`` + ``ts`` so readers can
filter and migrate.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Iterator, List, Optional

log = logging.getLogger("matrel_tpu.obs")

#: Bump when a reader-visible field changes meaning. Readers skip
#: records with a MAJOR version they don't know.
SCHEMA_VERSION = 1

#: Default log file (cwd-relative, like the autotune table's default).
DEFAULT_EVENT_LOG = ".matrel_events.jsonl"


def resolve_path(path: Optional[str]) -> str:
    """Config value → concrete path ('' / None → the default name)."""
    return path or DEFAULT_EVENT_LOG


class EventLog:
    """Append-only JSONL writer. ``emit`` stamps schema/ts/kind and
    writes one line; it never raises (a broken disk must not break the
    query that happened to be observed)."""

    def __init__(self, path: Optional[str] = None):
        self.path = resolve_path(path)
        self._warned = False

    def emit(self, kind: str, record: dict) -> Optional[dict]:
        """Append one event. Returns the full record as written, or
        None when the write failed (already logged)."""
        full = {"schema": SCHEMA_VERSION, "ts": round(time.time(), 3),
                "kind": kind}
        full.update(record)
        try:
            line = json.dumps(full, default=_jsonable)
        except (TypeError, ValueError) as e:
            self._warn(f"unserialisable event dropped: {e}")
            return None
        try:
            with open(self.path, "a") as f:
                f.write(line + "\n")
        except OSError as e:
            self._warn(f"could not append to {self.path}: {e}")
            return None
        return full

    def _warn(self, msg: str) -> None:
        if not self._warned:
            log.warning("event log: %s (further failures silenced)", msg)
            self._warned = True


def _jsonable(v):
    """Last-resort encoder: numpy scalars/arrays and anything else that
    slipped into a record become plain Python or a repr string."""
    tolist = getattr(v, "tolist", None)
    if callable(tolist):
        try:
            return tolist()
        except Exception:  # matlint: disable=ML007 fallback encoder — falls through to the next encoding, ends at repr()
            pass
    item = getattr(v, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:  # matlint: disable=ML007 fallback encoder — falls through to repr()
            pass
    return repr(v)


def emit_tool_event(kind: str, record: dict,
                    anchor_dir: Optional[str] = None) -> Optional[dict]:
    """Emission entry point for out-of-session tools (bench.py,
    tools/soak_guard.py): resolves the log path from
    ``$MATREL_OBS_EVENT_LOG``, else the default log name anchored at
    ``anchor_dir`` (typically the repo root, so tool records land in
    the same file regardless of cwd). Same never-raises contract as
    :meth:`EventLog.emit`."""
    path = os.environ.get("MATREL_OBS_EVENT_LOG")
    if not path and anchor_dir:
        path = os.path.join(anchor_dir, DEFAULT_EVENT_LOG)
    return EventLog(path).emit(kind, record)


def read_events(path: Optional[str] = None,
                kinds: Optional[tuple] = None,
                tail_bytes: Optional[int] = None) -> List[dict]:
    """Parse an event-log file. Unparseable lines and unknown schema
    versions are skipped (a reader must survive a log written by a
    crashed process mid-line). Missing file → empty list.
    ``tail_bytes`` bounds the read to the file's last N bytes — the
    live readers' contract (the metrics endpoint's drift view, `top`
    refresh frames): a multi-GB host log must cost a scrape O(tail),
    not O(history)."""
    out: List[dict] = []
    for rec in iter_events(path, tail_bytes=tail_bytes):
        if kinds is None or rec.get("kind") in kinds:
            out.append(rec)
    return out


def iter_events(path: Optional[str] = None,
                tail_bytes: Optional[int] = None) -> Iterator[dict]:
    """Yield parsed records, skipping anything unreadable. Corrupt
    lines are COUNTED and warned about once per read (the robust-
    reader contract, docs/RESILIENCE.md): a log truncated mid-line by
    a crashed process must never take the reader down with it — but a
    silently shrinking history would hide the corruption entirely.
    With ``tail_bytes`` the read starts at most N bytes before EOF
    (the first, almost-surely partial line is dropped, not counted
    corrupt)."""
    p = resolve_path(path)
    if not os.path.exists(p):
        return
    skipped = 0
    with open(p) as f:
        if tail_bytes is not None:
            size = os.fstat(f.fileno()).st_size
            if size > tail_bytes:
                f.seek(size - tail_bytes)
                f.readline()       # discard the cut-off line
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if not isinstance(rec, dict):
                skipped += 1
                continue
            if rec.get("schema") != SCHEMA_VERSION:
                continue
            yield rec
    if skipped:
        log.warning("event log %s: skipped %d corrupt line(s) "
                    "(crashed-writer debris; readers continue)",
                    p, skipped)
