"""Structured JSONL event log — the Spark event-log analogue.

One line per event, append-only, schema-versioned. ``MatrelSession``
emits one ``query`` record per run (plus one ``verify`` record when the
static plan verifier is on — mode, diagnostic count, codes) and one
``serve`` record per micro-batched admission (batch size, queue waits,
result-cache state — session.run_many / the submit pipeline);
``bench.py`` emits ``bench`` records (``bench_error`` on a final probe
failure, carrying the error tail and last-known-good) and
``tools/soak_guard.py`` ``soak`` records into the same file, so one log
replays the whole history of a host (the history-server input —
``python -m matrel_tpu history`` aggregates it). Round 9 adds ``span``
records (parent-linked tracing scopes, obs/trace.py — exported to
Chrome/Perfetto by ``python -m matrel_tpu trace``) and ``analyze``
records (measured per-op trees joined to decision records — the drift
auditor's feed, obs/drift.py).

Writing discipline mirrors the repo's other append-only logs
(PROGRESS.jsonl, SOAKLOG.jsonl): a single ``write()`` of one line per
event (atomic for sane line sizes on POSIX), emission failures are
swallowed after a one-time warning — observability must never fail a
query — and every record carries ``schema`` + ``ts`` so readers can
filter and migrate.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Iterator, List, Optional

if __package__:
    from matrel_tpu.utils import lockdep
else:
    # Loaded by FILE PATH (bench.py's jax-free parent, soak_guard):
    # a package import here would execute matrel_tpu/__init__ and
    # pull jax into a process that is deliberately backend-free
    # (relay-wedge safety). Load the lock seam the same way — it is
    # stdlib-only, and in these processes lockdep is never enabled,
    # so the private module state is irrelevant (make_lock returns a
    # raw threading.Lock either way).
    import importlib.util as _ilu
    _spec = _ilu.spec_from_file_location(
        "_matrel_lockdep",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     os.pardir, "utils", "lockdep.py"))
    lockdep = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(lockdep)

log = logging.getLogger("matrel_tpu.obs")

#: Bump when a reader-visible field changes meaning. Readers skip
#: records with a MAJOR version they don't know.
SCHEMA_VERSION = 1

#: Default log file (cwd-relative, like the autotune table's default).
DEFAULT_EVENT_LOG = ".matrel_events.jsonl"


def resolve_path(path: Optional[str]) -> str:
    """Config value → concrete path ('' / None → the default name)."""
    return path or DEFAULT_EVENT_LOG


def rotated_path(path: Optional[str]) -> str:
    """The single rotation sibling: ``<log>.1``."""
    return resolve_path(path) + ".1"


#: Serialises the size-check + rename of rotation across every writer
#: thread in this process (fleet slices and the parent session share
#: one log). Cross-process writers stay safe without it: each append
#: is one O_APPEND write, and a concurrent rename at worst lands a
#: line in the .1 sibling instead of the fresh main file — readers
#: stitch both.
_ROTATE_LOCK = lockdep.make_lock("obs.event_rotate")


class EventLog:
    """Append-only JSONL writer. ``emit`` stamps schema/ts/kind and
    writes one line; it never raises (a broken disk must not break the
    query that happened to be observed).

    Line atomicity: each record is ONE ``os.write`` on an O_APPEND
    descriptor — POSIX appends are atomic for sane line sizes, so
    fleet slices and the parent session interleaving on the same log
    produce whole lines, never spliced ones. A torn line (crashed
    writer, full disk) is the READER's problem and is counted + warned
    there (:func:`iter_events`).

    With ``max_bytes`` > 0 the log rotates to a single ``.1`` sibling
    once it reaches the threshold (the previous ``.1`` is replaced) —
    disk is bounded at ~2x max_bytes while readers stitch the pair.
    0 keeps the historical unbounded append, byte-identical."""

    def __init__(self, path: Optional[str] = None, max_bytes: int = 0):
        self.path = resolve_path(path)
        self.max_bytes = max_bytes
        self._warned = False

    def emit(self, kind: str, record: dict) -> Optional[dict]:
        """Append one event. Returns the full record as written, or
        None when the write failed (already logged)."""
        full = {"schema": SCHEMA_VERSION, "ts": round(time.time(), 3),
                "kind": kind}
        full.update(record)
        try:
            line = json.dumps(full, default=_jsonable)
        except (TypeError, ValueError) as e:
            self._warn(f"unserialisable event dropped: {e}")
            return None
        try:
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, (line + "\n").encode())
            finally:
                os.close(fd)
        except OSError as e:
            self._warn(f"could not append to {self.path}: {e}")
            return None
        if self.max_bytes > 0:
            self._maybe_rotate()
        return full

    def _maybe_rotate(self) -> None:
        """Rotate ``path`` → ``path.1`` once the threshold is reached.
        Size is re-checked under the process-wide lock so concurrent
        writers rotate exactly once per crossing; failures are
        swallowed like emit's (rotation must never fail a query)."""
        try:
            if os.path.getsize(self.path) < self.max_bytes:
                return
            with _ROTATE_LOCK:
                if os.path.getsize(self.path) >= self.max_bytes:
                    os.replace(self.path, self.path + ".1")
        except OSError as e:
            self._warn(f"could not rotate {self.path}: {e}")

    def _warn(self, msg: str) -> None:
        if not self._warned:
            log.warning("event log: %s (further failures silenced)", msg)
            self._warned = True


def _jsonable(v):
    """Last-resort encoder: numpy scalars/arrays and anything else that
    slipped into a record become plain Python or a repr string."""
    tolist = getattr(v, "tolist", None)
    if callable(tolist):
        try:
            return tolist()
        except Exception:  # matlint: disable=ML007 fallback encoder — falls through to the next encoding, ends at repr()
            pass
    item = getattr(v, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:  # matlint: disable=ML007 fallback encoder — falls through to repr()
            pass
    return repr(v)


def emit_tool_event(kind: str, record: dict,
                    anchor_dir: Optional[str] = None) -> Optional[dict]:
    """Emission entry point for out-of-session tools (bench.py,
    tools/soak_guard.py): resolves the log path from
    ``$MATREL_OBS_EVENT_LOG``, else the default log name anchored at
    ``anchor_dir`` (typically the repo root, so tool records land in
    the same file regardless of cwd). Same never-raises contract as
    :meth:`EventLog.emit`."""
    path = os.environ.get("MATREL_OBS_EVENT_LOG")
    if not path and anchor_dir:
        path = os.path.join(anchor_dir, DEFAULT_EVENT_LOG)
    return EventLog(path).emit(kind, record)


def read_events(path: Optional[str] = None,
                kinds: Optional[tuple] = None,
                tail_bytes: Optional[int] = None) -> List[dict]:
    """Parse an event-log file. Unparseable lines and unknown schema
    versions are skipped (a reader must survive a log written by a
    crashed process mid-line). Missing file → empty list.
    ``tail_bytes`` bounds the read to the file's last N bytes — the
    live readers' contract (the metrics endpoint's drift view, `top`
    refresh frames): a multi-GB host log must cost a scrape O(tail),
    not O(history)."""
    out: List[dict] = []
    for rec in iter_events(path, tail_bytes=tail_bytes):
        if kinds is None or rec.get("kind") in kinds:
            out.append(rec)
    return out


def iter_events(path: Optional[str] = None,
                tail_bytes: Optional[int] = None) -> Iterator[dict]:
    """Yield parsed records, skipping anything unreadable. Corrupt
    lines are COUNTED and warned about once per read (the robust-
    reader contract, docs/RESILIENCE.md): a log truncated mid-line by
    a crashed process must never take the reader down with it — but a
    silently shrinking history would hide the corruption entirely.
    With ``tail_bytes`` the read starts at most N bytes before EOF
    (the first, almost-surely partial line is dropped, not counted
    corrupt).

    When rotation left a ``<log>.1`` sibling the pair is stitched
    transparently — oldest first, and ``tail_bytes`` spans BOTH files
    (the budget left after the main file reaches into the sibling's
    tail), so every reader (history, top, drift, the scrape endpoint)
    sees one continuous log regardless of when rotation fired."""
    p = resolve_path(path)
    prev = p + ".1"
    # (path, bytes-to-skip-from-its-start) pairs, oldest file first.
    # A rotation between the two stat calls at worst re-reads a
    # record's worth of history — never loses the tail.
    plan: List[tuple] = []
    main_size = os.path.getsize(p) if os.path.exists(p) else None
    prev_size = os.path.getsize(prev) if os.path.exists(prev) else None
    if tail_bytes is None:
        if prev_size is not None:
            plan.append((prev, 0))
        if main_size is not None:
            plan.append((p, 0))
    elif main_size is not None and main_size > tail_bytes:
        plan.append((p, main_size - tail_bytes))
    else:
        if prev_size is not None:
            remain = tail_bytes - (main_size or 0)
            plan.append((prev, max(0, prev_size - remain)))
        if main_size is not None:
            plan.append((p, 0))
    if not plan:
        return
    skipped = 0
    for fpath, start in plan:
        try:
            f = open(fpath)
        except OSError:
            if fpath != p:
                continue           # sibling vanished; nothing to chase
            try:
                # the main file rotated away between the stat and the
                # open — its bytes moved to the sibling, so follow
                # them (at worst this re-reads a little history;
                # never loses the tail)
                f = open(prev)
            except OSError:
                continue
        with f:
            if start > 0:
                f.seek(start)
                f.readline()       # discard the cut-off line
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    skipped += 1
                    continue
                if not isinstance(rec, dict):
                    skipped += 1
                    continue
                if rec.get("schema") != SCHEMA_VERSION:
                    continue
                yield rec
    if skipped:
        log.warning("event log %s: skipped %d corrupt line(s) "
                    "(crashed-writer debris; readers continue)",
                    p, skipped)
