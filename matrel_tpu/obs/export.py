"""In-process live metrics endpoint (docs/OBSERVABILITY.md tier 3).

The reference gets a live UI + pluggable metrics sink from Spark for
free; this is the TPU rebuild's equivalent, sized for a serving host:
a stdlib-only HTTP server on a daemon thread
(``config.obs_metrics_port``; loopback only) serving

- ``/metrics`` — Prometheus text exposition: every registry counter /
  gauge, every timing histogram as a summary (sketch quantiles +
  ``_sum``/``_count``), per-(tenant, objective) SLO burn rates and
  alert states, the brownout rung, breaker states, plan/result-cache
  and IVM counters, and the drift-flag count — the scrape target a
  fleet's Prometheus points at;
- ``/json`` (also ``/`` and ``/snapshot``) — the same state as one
  JSON document, including full sketch summaries — what
  ``python -m matrel_tpu top`` polls.

The OFF contract is structural: ``obs_metrics_port == 0`` (the
default) constructs NO exporter, NO server socket and NO thread
(poisoned-``__init__`` + thread-census test, the flight-recorder
precedent). A nonzero port that cannot bind raises at session
construction — an operator who asked for an endpoint must not
silently run without one (the config-validation discipline).

Serving a snapshot only READS: the registry under its own lock, the
SLO/brownout/breaker snapshots under theirs — a scrape never blocks a
query beyond those per-structure locks.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from matrel_tpu.obs.metrics import REGISTRY


def from_config(session) -> Optional["MetricsExporter"]:
    """None for the default config (port 0): the OFF path constructs
    nothing. Otherwise a STARTED exporter bound to the configured
    port."""
    port = int(getattr(session.config, "obs_metrics_port", 0))
    if port == 0:
        return None
    exporter = MetricsExporter(session, port)
    exporter.start()
    return exporter


class MetricsExporter:
    """One session's metrics endpoint: a ``ThreadingHTTPServer`` on
    127.0.0.1 driven by one daemon thread.

    Lifecycle: the server holds its session by WEAK reference (a
    strong one would make the listening thread a GC root pinning the
    session — catalog, caches, device arrays — for process lifetime),
    and a ``weakref.finalize`` on the session stops the server when
    the session is collected, freeing the port. The deterministic
    teardown paths are ``stop()`` and ``session.serve_close()``
    (which calls it); a daemon thread never wedges interpreter exit
    either way."""

    def __init__(self, session, port: int):
        self._server = ThreadingHTTPServer(
            ("127.0.0.1", int(port)), _Handler)
        self._server.daemon_threads = True
        self._server.matrel_session_ref = weakref.ref(session)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._finalizer = None
        self._session_for_start = session   # dropped by start()

    def start(self) -> None:
        if self._thread is not None:
            return
        # the GC fallback: a dropped session must not leak its bound
        # port (EADDRINUSE on the next same-config session) — the
        # finalizer holds the SERVER, never the session
        self._finalizer = weakref.finalize(
            self._session_for_start, _stop_server, self._server)
        self._session_for_start = None
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="matrel-metrics",
            daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        if self._thread is None:
            return
        if self._finalizer is not None:
            self._finalizer.detach()
        _stop_server(self._server)
        self._thread.join(timeout)
        self._thread = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"


def _stop_server(server) -> None:
    """Shut one exporter server down (stop() and the GC finalizer
    share it). ``shutdown`` needs the serve_forever loop running —
    both callers only fire after start()."""
    try:
        server.shutdown()
        server.server_close()
    except OSError:
        pass  # already closed — the goal state


class _Handler(BaseHTTPRequestHandler):
    # one scrape per poll interval; default request logging would spam
    # the operator's terminal at scrape rate
    def log_message(self, fmt, *args):  # noqa: D102 — stdlib override
        pass

    def do_GET(self):  # noqa: N802 — stdlib contract
        sess = self.server.matrel_session_ref()
        if sess is None:
            self.send_error(503, "owning session was collected")
            return
        try:
            if self.path.split("?", 1)[0] == "/metrics":
                body = render_prometheus(snapshot(sess)).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?", 1)[0] in ("/", "/json",
                                                "/snapshot"):
                body = json.dumps(snapshot(sess)).encode()
                ctype = "application/json"
            else:
                self.send_error(404, "unknown path (try /metrics "
                                     "or /json)")
                return
        except Exception as ex:  # noqa: BLE001 — a scrape must never
            # crash the serving session; the scraper sees the 500
            self.send_error(500, f"snapshot failed: {ex!r}"[:200])
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


# ---------------------------------------------------------------------------
# Snapshot assembly — the one state-gathering path both formats share
# ---------------------------------------------------------------------------


def snapshot(session) -> dict:
    """The live telemetry snapshot: registry metrics (sketch-backed
    histogram summaries included), SLO states, brownout rung, breaker
    states, plan/result-cache and IVM counters, serve-queue depths and
    drift flags. Sections whose subsystem is off are None — the JSON
    shape tells the consumer what is configured."""
    sess = session
    snap = {
        "ts": round(time.time(), 3),
        "metrics": REGISTRY.snapshot(),
        "slo": (sess._slo.snapshot()
                if getattr(sess, "_slo", None) is not None else None),
        "brownout": (sess._brownout.snapshot()
                     if getattr(sess, "_brownout", None) is not None
                     else None),
        "breakers": (sess._breakers.snapshot()
                     if getattr(sess, "_breakers", None) is not None
                     else None),
        "plan_cache": sess.plan_cache_info(),
        "result_cache": (sess._result_cache.info()
                         if sess._rc_enabled() else None),
        "ivm": ({"generation": sess._delta_gen}
                if getattr(sess, "_delta_gen", 0) else None),
        "drift": _drift_flags(sess),
    }
    serve = getattr(sess, "_serve", None)
    if serve is not None:
        snap["serve"] = {
            "queue_depth": serve._q.qsize(),
            "tenant_depths": serve._q.tenant_depths(),
            "inflight": serve.inflight_depth,
            "deadline_misses": serve.deadline_misses,
            "stale_served": serve.stale_served,
            "queue_counters": serve._q.counters(),
        }
    else:
        snap["serve"] = None
    fleet = getattr(sess, "_fleet", None)
    if fleet is not None:
        # the fleet tier (docs/FLEET.md): per-slice state (queue
        # depths, caches, per-slice SLO snapshots — the PR 14
        # monitors aggregated per slice) + directory/placement
        # counters, so `top` and any scraper see the whole fleet
        # from the parent session's one endpoint
        snap["fleet"] = fleet.info()
    else:
        snap["fleet"] = None
    return snap


#: Drift-view read bound: the endpoint audits the log's trailing
#: window, never its whole history — a scrape must cost O(tail).
_DRIFT_TAIL_BYTES = 8 << 20

#: One-slot per-path cache keyed by (size, mtime_ns): a poller
#: scraping every few hundred ms between log appends pays the parse
#: once, not per poll.
_drift_cache: dict = {}


def _drift_flags(session) -> Optional[dict]:
    """Rank-order drift flags over the TRAILING WINDOW of the
    session's event log — the on-line face of ``history --drift``
    (which still audits the full history offline). None when obs is
    off (no log is being written, so there is nothing current to
    audit). Cached on the log file's stat signature so repeated
    scrapes of an idle log parse nothing."""
    if not session._obs_enabled():
        return None
    try:
        from matrel_tpu.obs import drift
        from matrel_tpu.obs.events import read_events, resolve_path
        path = resolve_path(session.config.obs_event_log)
        st = os.stat(path)
        sig = (st.st_size, st.st_mtime_ns)
        hit = _drift_cache.get(path)
        if hit is not None and hit[0] == sig:
            return hit[1]
        events = read_events(path, tail_bytes=_DRIFT_TAIL_BYTES)
        samples = list(drift.iter_samples(events))
        flags = drift.rank_flags(samples)
        out = {"samples": len(samples), "flag_count": len(flags),
               "window_bytes": _DRIFT_TAIL_BYTES,
               "flags": flags[:16]}
        _drift_cache[path] = (sig, out)
        return out
    except Exception:  # noqa: BLE001 — an unreadable log must not
        # break the scrape that would have surfaced it; the None says
        # "no drift view" and the log reader already warned
        return None


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _name(metric: str) -> str:
    return "matrel_" + _NAME_RE.sub("_", metric)


def _esc(label: str) -> str:
    return (str(label).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _num(v) -> str:
    if v is None:
        return "NaN"
    return repr(float(v))


def render_prometheus(snap: dict) -> str:
    """Prometheus text format (version 0.0.4) over a snapshot().
    Counters/gauges one sample each; histograms as summaries (sketch
    quantiles + _sum/_count); SLO, brownout, breaker, cache and drift
    state as labelled gauges. Parses clean under the strict
    line-grammar check the traffic harness applies on every poll."""
    out = []
    typed = set()

    def emit(name, value, labels=None, mtype=None):
        if mtype and name not in typed:
            out.append(f"# TYPE {name} {mtype}")
            typed.add(name)
        lbl = ""
        if labels:
            lbl = ("{" + ",".join(
                f'{k}="{_esc(v)}"' for k, v in labels.items()) + "}")
        out.append(f"{name}{lbl} {_num(value)}")

    m = snap.get("metrics") or {}
    for k in sorted(m.get("counters") or {}):
        emit(_name(k), m["counters"][k], mtype="counter")
    for k in sorted(m.get("gauges") or {}):
        emit(_name(k), m["gauges"][k], mtype="gauge")
    for k in sorted(m.get("histograms") or {}):
        h = m["histograms"][k]
        n = _name(k)
        emit(n, h.get("p50"), {"quantile": "0.5"}, mtype="summary")
        emit(n, h.get("p95"), {"quantile": "0.95"})
        emit(n, h.get("p99"), {"quantile": "0.99"})
        emit(n + "_sum", h.get("total"))
        emit(n + "_count", h.get("count"))
    slo = snap.get("slo")
    if slo:
        for tenant, row in sorted((slo.get("tenants") or {}).items()):
            for obj, st in sorted((row.get("objectives")
                                   or {}).items()):
                lbl = {"tenant": tenant, "objective": obj}
                emit("matrel_slo_burn_rate", st.get("burn_fast"),
                     {**lbl, "window": "fast"}, mtype="gauge")
                emit("matrel_slo_burn_rate", st.get("burn_slow"),
                     {**lbl, "window": "slow"})
                emit("matrel_slo_attainment", st.get("attainment"),
                     lbl, mtype="gauge")
                emit("matrel_slo_alert_firing",
                     1 if st.get("state") == "firing" else 0, lbl,
                     mtype="gauge")
            lat = row.get("latency_ms") or {}
            for q, field in (("0.5", "p50"), ("0.95", "p95"),
                             ("0.99", "p99")):
                emit("matrel_slo_latency_ms", lat.get(field),
                     {"tenant": tenant, "quantile": q},
                     mtype="summary")
            emit("matrel_slo_tenant_qps", row.get("qps"),
                 {"tenant": tenant}, mtype="gauge")
        emit("matrel_slo_alerts_active", slo.get("alerts_active"),
             mtype="gauge")
        emit("matrel_slo_alerts_fired_total",
             slo.get("alerts_fired"), mtype="counter")
        emit("matrel_slo_alerts_cleared_total",
             slo.get("alerts_cleared"), mtype="counter")
    br = snap.get("brownout")
    if br:
        emit("matrel_brownout_rung", br.get("rung"), mtype="gauge")
        emit("matrel_brownout_queue_depth", br.get("queue_depth"),
             mtype="gauge")
        emit("matrel_brownout_wait_p95_ms", br.get("wait_p95_ms"),
             mtype="gauge")
    bk = snap.get("breakers")
    if bk:
        emit("matrel_breakers_open", len(bk.get("open") or ()),
             mtype="gauge")
        emit("matrel_breakers_half_open",
             len(bk.get("half_open") or ()), mtype="gauge")
    pc = snap.get("plan_cache")
    if pc:
        emit("matrel_plan_cache_plans", pc.get("plans"), mtype="gauge")
        emit("matrel_plan_cache_evicted", pc.get("evicted"),
             mtype="gauge")
    rc = snap.get("result_cache")
    if rc:
        for k in ("entries", "bytes", "hits", "misses", "evicted",
                  "invalidated", "patched", "rekeyed"):
            if k in rc:
                emit(f"matrel_result_cache_{k}", rc[k], mtype="gauge")
    ivm = snap.get("ivm")
    if ivm:
        emit("matrel_ivm_generation", ivm.get("generation"),
             mtype="gauge")
    sv = snap.get("serve")
    if sv:
        emit("matrel_serve_queue_depth", sv.get("queue_depth"),
             mtype="gauge")
        for tenant, depth in sorted(
                (sv.get("tenant_depths") or {}).items()):
            emit("matrel_serve_tenant_queue_depth", depth,
                 {"tenant": tenant or "(default)"}, mtype="gauge")
        emit("matrel_serve_inflight", sv.get("inflight"),
             mtype="gauge")
    dr = snap.get("drift")
    if dr:
        emit("matrel_drift_flags", dr.get("flag_count"), mtype="gauge")
    return "\n".join(out) + "\n"
