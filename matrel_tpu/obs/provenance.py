"""Answer provenance ledger — obs tier 4 (docs/OBSERVABILITY.md).

MatRel inherits Spark's RDD lineage (the MatFast persist ancestry),
which this engine replaced with explicit mechanisms: result caches,
delta patches, fleet replicas, stale brownout serves, degradation
rungs. Each mechanism stamps its own seam, but a SERVED ANSWER had no
single reconstructable account of where it came from. This module is
that account: every answer the session or fleet returns while
``config.obs_provenance`` > 0 appends one compact, schema-versioned
lineage record to an in-memory bounded ledger (and emits it as a
``provenance`` event through the session's one emission funnel).

A record names the serve PATH (:data:`PATHS`) and carries the
structural key, producing slice, precision SLA, degrade rung,
result-cache ancestry (whole hit / interior substitution leaf stamps
with entry generations), the IVM patch chain (``delta:<gen>`` rules +
composed err_bound), fleet directory hops (owner → serving slice),
staleness grants, and the planner's strategy/tier/coefficient
provenance — everything the ``why`` console renders.

Capture happens ONLY at the sanctioned seams (``session._rc_admit`` /
``_rc_insert``, the serve pipeline's stale-rung consult, the fleet
directory's hit-anywhere answer, the delta plane's ``apply_patch``
commit); every ``CacheEntry.provenance`` / ``attrs["provenance"]``
store lives in THIS file so matlint ML015 can pin the seam the way
ML012 pins the cache's own mutations.

AUDIT REPLAY (:func:`audit`) is the MV113 dynamic-verify idiom
generalized to every serve path: sampled ledger records re-execute
their recorded expression fresh — straight through the executor,
result cache bypassed — and the served answer must be bit-equal when
its composed bound is 0 (int/exact paths) and within the stamped
err_bound otherwise. Ledger records hold live references (expr,
result, mesh, compile config) precisely so replay reconstructs the
producing configuration; the bounded deque caps what they pin.

Zero-overhead contract: ``obs_provenance = 0`` (the default) builds
NO ledger and NO record objects anywhere on the serve path — the
brownout/breaker structural-zero discipline, poisoned-``__init__``
test-enforced.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
import time
from collections import deque
from typing import Iterable, List, Optional
from matrel_tpu.utils import lockdep

#: Bump when a reader-visible field changes meaning (the event-log
#: SCHEMA_VERSION discipline). Readers warn on records they don't know.
SCHEMA_VERSION = 1

#: The serve-path vocabulary — every answer is exactly one of these.
#: MV115 warns on stamps claiming a path outside it. ``cse_hoist`` is
#: a batch's compute-once shared interior (serve/mqo.py — the producer
#: side); ``cse_interior`` a consumer answer that fed on one or more
#: hoisted results (the rc_interior refinement for the CSE plane).
PATHS = ("execute", "rc_hit", "rc_interior", "ivm_patched",
         "fleet_directory", "fleet_replica", "stale", "degraded",
         "cse_hoist", "cse_interior")

#: Relative floor for audit replay — MV113's: a zero composed bound
#: means EXACT; a nonzero bound is asserted as-is but never below one
#: f32 ulp-scale unit (measurement noise on reductions).
_REL_FLOOR = 2.0 ** -20

_prov_seq = itertools.count(1)


def from_config(config) -> Optional["ProvenanceLedger"]:
    """The structural-zero gate (the brownout/breaker idiom): None —
    not an inert object, NO object — when the ledger is off."""
    cap = getattr(config, "obs_provenance", 0)
    if cap <= 0:
        return None
    return ProvenanceLedger(cap)


@dataclasses.dataclass
class ProvenanceRecord:
    """One served answer's lineage. ``summary`` is the JSON-safe
    projection (what the ``provenance`` event carries and ``why``
    renders); the live references (expr/result/mesh/config) exist so
    :func:`audit` can replay the answer fresh — None when the serving
    seam had no expression in hand (nothing to replay)."""

    query_id: str
    path: str
    key: str
    key_hash: str
    sla: str
    rung: int
    err_bound: float
    ts: float
    summary: dict
    expr: Optional[object] = None
    result: Optional[object] = None
    mesh: Optional[object] = None
    config: Optional[object] = None


class ProvenanceLedger:
    """Thread-safe bounded ledger of :class:`ProvenanceRecord` plus
    the per-entry IVM patch chains (ivm_id → [{gen, rule, err_bound}]
    in patch order — the composed-bound audit trail a single
    ``delta_gen`` stamp cannot carry)."""

    def __init__(self, cap: int):
        self.cap = cap
        self._records: "deque[ProvenanceRecord]" = deque(maxlen=cap)
        self._chains: dict = {}
        self._lock = lockdep.make_lock("obs.provenance")
        self.captured = 0

    # -- the sanctioned stamp writers (ML015 pins every other one) -----

    def stamp_entry(self, ent, path: str, query_id: str) -> None:
        """Write a fresh entry's ``provenance`` stamp (called from
        ``session._rc_insert`` and fleet replication — the put seam)."""
        ent.provenance = {"schema": SCHEMA_VERSION, "path": path,
                          "query_id": query_id,
                          "key_hash": ent.key_hash}

    def stamp_patched(self, ent, gen: int, rule: Optional[str],
                      err_bound: float) -> None:
        """Append one patch to the entry's chain and restamp it
        ``ivm_patched`` (called from the delta plane's ``apply_patch``
        commit — the ONE cache-mutation seam)."""
        link = {"gen": gen, "rule": rule,
                "err_bound": float(err_bound)}
        with self._lock:
            chain = self._chains.setdefault(ent.ivm_id, [])
            chain.append(link)
            chain_copy = list(chain)
        prev = ent.provenance or {}
        ent.provenance = {"schema": SCHEMA_VERSION,
                          "path": "ivm_patched",
                          "query_id": prev.get("query_id", ""),
                          "key_hash": ent.key_hash,
                          "chain": chain_copy}

    def stamp_leaf(self, leaf, ent):
        """Thread a consumed entry's provenance onto its substitution
        leaf (``attrs["provenance"]``) so MV115's static half can
        cross-check it against the ``result_cache`` stamp both ways.
        Entries inserted before the ledger existed pass through
        unstamped — the historical shape."""
        if ent.provenance is None:
            return leaf
        return leaf.with_attrs(provenance=dict(ent.provenance))

    def chain(self, ivm_id) -> List[dict]:
        with self._lock:
            return list(self._chains.get(ivm_id, ()))

    # -- capture (one call per served answer) --------------------------

    def capture(self, path: str, key: str, sla: str,
                rung: int = 0, expr=None, result=None, ent=None,
                executed=None, plan=None, strategies=None,
                mesh=None, config=None,
                fleet: Optional[dict] = None,
                stale: Optional[dict] = None,
                coeff_epoch: Optional[str] = None) -> dict:
        """Assemble + append one lineage record; returns the JSON-safe
        summary for the caller to emit as a ``provenance`` event.
        ``ent`` is the serving cache entry (hit paths), ``executed``
        the possibly-substituted tree that actually ran (interior
        ancestry), ``plan`` the compiled plan (strategy provenance);
        ``strategies`` overrides the plan's decision records with one
        root's (the MultiPlan batch path); ``coeff_epoch`` records
        which learned-coefficient epoch priced the answer's plan
        (docs/COST_MODEL.md — None with the loop off: no new field,
        the bit-identity contract)."""
        from matrel_tpu.resilience import degrade as degrade_lib
        qid = f"p{next(_prov_seq)}"
        if ent is not None and path in ("rc_hit", "stale"):
            # refine the consult paths by what the entry records: a
            # hit on a patched entry IS an IVM-maintained answer, a
            # hit on a replicated entry IS a fleet-replica answer
            if ent.delta_gen:
                path = "ivm_patched"
            elif ent.fleet and path == "rc_hit":
                path = "fleet_replica"
        interior = _interior_stamps(executed) if executed is not None \
            else []
        if path == "execute" and interior:
            # cse-stamped leaves refine to the CSE plane's path; mixed
            # cse+rc ancestry stays honest — the leaves list carries
            # both kinds of stamps either way
            path = ("cse_interior"
                    if any(s.get("cse") for s in interior)
                    else "rc_interior")
        if path == "execute" and rung > 0:
            path = "degraded"
        err_bound = 0.0
        if ent is not None:
            err_bound = float(ent.err_bound or 0.0)
        elif plan is not None:
            err_bound = float(((plan.meta or {}).get("precision") or {})
                              .get("est_rel_err_bound") or 0.0)
        key_hash = hashlib.sha1(key.encode()).hexdigest()[:16]
        summary: dict = {
            "schema": SCHEMA_VERSION,
            "query_id": qid,
            "path": path,
            "key_hash": key_hash,
            "sla": sla,
            "err_bound": err_bound,
        }
        if rung > 0:
            summary["degrade"] = degrade_lib.rung_meta(rung)
        if coeff_epoch is not None:
            summary["coeff_epoch"] = coeff_epoch
        if ent is not None:
            cache: dict = {"kind": "whole", "entry": _entry_stamp(ent)}
            if ent.delta_gen:
                cache["ivm"] = {"gen": ent.delta_gen,
                                "rule": ent.delta_rule,
                                "err_bound": float(ent.err_bound or 0.0),
                                "chain": self.chain(ent.ivm_id)}
            summary["cache"] = cache
        elif interior:
            summary["cache"] = {"kind": "interior", "leaves": interior}
        if fleet is not None:
            summary["fleet"] = dict(fleet)
        elif ent is not None and ent.fleet:
            summary["fleet"] = dict(ent.fleet)
        if stale is not None:
            summary["stale"] = dict(stale)
        if plan is not None or strategies is not None:
            stamps = _strategy_stamps(plan, strategies)
            if stamps:
                summary["strategies"] = stamps
        rec = ProvenanceRecord(
            query_id=qid, path=path, key=key, key_hash=key_hash,
            sla=sla, rung=rung, err_bound=err_bound,
            ts=time.time(), summary=summary,  # matlint: disable=ML006 record timestamp — the ledger's ts mirrors EventLog.emit's stamp
            expr=expr if expr is not None
            else (ent.expr if ent is not None else None),
            result=result if result is not None
            else (ent.result if ent is not None else None),
            mesh=mesh, config=config)
        with self._lock:
            self._records.append(rec)
            self.captured += 1
        return summary

    # -- read surfaces --------------------------------------------------

    def records(self) -> List[ProvenanceRecord]:
        with self._lock:
            return list(self._records)

    def last(self, n: int) -> List[ProvenanceRecord]:
        with self._lock:
            recs = list(self._records)
        return recs[-n:] if n else recs

    def find(self, key: str) -> List[ProvenanceRecord]:
        """Records whose full key or key hash contains ``key``."""
        with self._lock:
            recs = list(self._records)
        return [r for r in recs
                if key in r.key_hash or key in r.key
                or key == r.query_id]

    def info(self) -> dict:
        with self._lock:
            return {"records": len(self._records), "cap": self.cap,
                    "captured": self.captured,
                    "chains": len(self._chains)}


def _entry_stamp(ent) -> dict:
    """A cache entry's JSON-safe ancestry stamp — the ``_rc_leaf``
    vocabulary, projected for the ledger."""
    stamp = {"key_hash": ent.key_hash, "layout": ent.layout,
             "dtype": ent.dtype, "gen": ent.delta_gen,
             "err_bound": float(ent.err_bound or 0.0)}
    if ent.delta_rule:
        stamp["rule"] = ent.delta_rule
    if ent.fleet:
        stamp["fleet"] = dict(ent.fleet)
    if ent.provenance is not None:
        stamp["provenance"] = dict(
            (k, v) for k, v in ent.provenance.items() if k != "chain")
    return stamp


def _interior_stamps(executed) -> List[dict]:
    """Substitution-leaf ancestry of the tree that actually ran: one
    stamp per ``result_cache`` leaf (the MV107 stamps, which already
    carry delta/fleet provenance when the consumed entry did) and one
    per ``cse`` leaf (a batch-shared interior hoisted by serve/mqo.py
    — marked ``"cse": True`` so readers can tell the planes apart)."""
    out: List[dict] = []
    seen: set = set()

    def walk(n):
        if n.uid in seen:
            return
        seen.add(n.uid)
        rc = n.attrs.get("result_cache")
        if n.kind == "leaf" and isinstance(rc, dict):
            stamp = {k: v for k, v in rc.items() if k != "deps"}
            pv = n.attrs.get("provenance")
            if isinstance(pv, dict):
                stamp["provenance"] = {
                    k: v for k, v in pv.items() if k != "chain"}
            out.append(stamp)
        cse = n.attrs.get("cse")
        if n.kind == "leaf" and isinstance(cse, dict):
            stamp = {k: v for k, v in cse.items() if k != "deps"}
            stamp["cse"] = True
            out.append(stamp)
        for c in n.children:
            walk(c)

    walk(executed)
    return out


def _strategy_stamps(plan, decisions=None) -> List[dict]:
    """The planner's per-matmul decisions, projected to the
    provenance-relevant columns (executor.plan_provenance) — lazily
    derived + cached on the plan like the obs query event's feed."""
    from matrel_tpu import executor as executor_lib
    try:
        return executor_lib.plan_provenance(plan, decisions)
    except Exception:
        return []


# -- audit replay (the MV113 dynamic idiom, every serve path) ----------

def audit(session, sample: int = 8,
          records: Optional[Iterable[ProvenanceRecord]] = None) -> dict:
    """Replay sampled ledger records fresh — compile the recorded
    expression under the recorded mesh/config (falling back to the
    session's), run it with the result cache bypassed, and prove the
    served answer bit-equal when its composed bound is 0, within the
    stamped err_bound otherwise. Returns a verdict dict; ``ok`` is
    True iff every sampled lineage proved."""
    led = getattr(session, "_prov", None)
    if records is None:
        records = led.records() if led is not None else []
    records = list(records)
    replayable = [r for r in records
                  if r.expr is not None and r.result is not None]
    skipped = len(records) - len(replayable)
    if sample and len(replayable) > sample:
        # evenly spaced over the ledger, newest included — a tail-only
        # sample would never re-prove the oldest surviving lineage
        step = len(replayable) / sample
        picked = [replayable[min(int(i * step), len(replayable) - 1)]
                  for i in range(1, sample)] + [replayable[-1]]
    else:
        picked = replayable
    results = [_replay(session, r) for r in picked]
    failed = [r for r in results if not r["ok"]]
    return {"sampled": len(picked), "replayable": len(replayable),
            "skipped_no_expr": skipped, "failed": len(failed),
            "results": results, "ok": bool(picked) and not failed}


def _replay(session, rec: ProvenanceRecord) -> dict:
    import numpy as np

    from matrel_tpu import executor as executor_lib
    out = {"query_id": rec.query_id, "path": rec.path,
           "key_hash": rec.key_hash, "err_bound": rec.err_bound}
    try:
        plan = executor_lib.compile_expr(
            rec.expr, rec.mesh or session.mesh,
            rec.config or session.config)
        fresh = plan.run().to_numpy()
        got = rec.result.to_numpy()
    except Exception as ex:
        out.update(ok=False, error=repr(ex))
        return out
    exact = (rec.err_bound or 0.0) <= 0.0
    scale = max(float(np.abs(fresh).max()), 1.0)
    err = float(np.abs(got.astype(np.float64)
                       - fresh.astype(np.float64)).max()) / scale
    tol = 0.0 if exact else max(float(rec.err_bound), _REL_FLOOR)
    out.update(exact=exact, rel_err=err, tol=tol,
               ok=(err == 0.0) if exact else (err <= tol))
    return out


# -- the `why` console -------------------------------------------------

def render(summary: dict) -> str:
    """One lineage record (the JSON-safe summary — live or replayed
    from the event log) as an indented lineage tree."""
    lines = []
    head = (f"{summary.get('query_id', '?')}  "
            f"path={summary.get('path', '?')}  "
            f"key={summary.get('key_hash', '?')}  "
            f"sla={summary.get('sla', '?')}")
    if summary.get("slice") is not None:
        head += f"  slice={summary['slice']}"
    bound = summary.get("err_bound", 0.0)
    head += f"  err_bound={'exact' if not bound else f'{bound:.3e}'}"
    lines.append(head)
    deg = summary.get("degrade")
    if deg:
        lines.append(f"  degrade: rung {deg.get('rung')} "
                     f"({deg.get('label')})")
    cache = summary.get("cache")
    if cache:
        if cache.get("kind") == "whole":
            e = cache.get("entry") or {}
            lines.append(f"  cache: whole hit <- entry "
                         f"{e.get('key_hash')} (layout "
                         f"{e.get('layout')}, {e.get('dtype')})")
        else:
            lines.append(f"  cache: interior substitution "
                         f"({len(cache.get('leaves') or ())} leaves)")
            for leaf in cache.get("leaves") or ():
                d = leaf.get("delta")
                extra = (f", delta gen {d['gen']} rule {d.get('rule')}"
                         if d else "")
                lines.append(f"    <- entry {leaf.get('key_hash')} "
                             f"(layout {leaf.get('layout')}, "
                             f"{leaf.get('dtype')}{extra})")
        ivm = cache.get("ivm")
        if ivm:
            chain = ivm.get("chain") or []
            hops = " <- ".join(
                f"gen {c['gen']} {c.get('rule')} "
                f"(+{c.get('err_bound', 0.0):.1e})"
                for c in reversed(chain)) or (
                f"gen {ivm.get('gen')} {ivm.get('rule')}")
            lines.append(f"  ivm: patched, composed err_bound "
                         f"{ivm.get('err_bound', 0.0):.3e}")
            lines.append(f"    {hops}")
    fleet = summary.get("fleet")
    if fleet:
        serving = fleet.get("serving", fleet.get("owner"))
        remote = " (remote)" if fleet.get("remote") else ""
        lines.append(f"  fleet: owner slice {fleet.get('owner')} -> "
                     f"served by slice {serving}{remote}")
    stale = summary.get("stale")
    if stale:
        lines.append(f"  stale: served under a "
                     f"{stale.get('staleness_ms', 0):.0f}ms "
                     f"staleness grant")
    strategies = summary.get("strategies")
    if strategies:
        lines.append("  strategies: " + ", ".join(
            s.get("strategy", "?")
            + (f"@{s['tier']}" if s.get("tier") else "")
            + (f" [{s['provenance']}]" if s.get("provenance") else "")
            for s in strategies))
    return "\n".join(lines)


def _audit_workload():
    """A self-contained serve workload covering the replayable paths
    (fresh execute, whole rc hit, interior substitution, exact int
    path, rebind + delta patch) on a ledger-enabled session — what
    ``why --audit`` samples when no live session exists. CPU-scale
    sizes; the fleet/degrade paths need threads and are the
    provenance drill's job (tools/provenance_drill.py)."""
    import numpy as np

    from matrel_tpu.config import default_config
    from matrel_tpu.session import MatrelSession

    cfg = default_config().replace(obs_provenance=64,
                                   result_cache_max_bytes=1 << 26)
    sess = MatrelSession(config=cfg)
    rng = np.random.default_rng(7)
    A = sess.from_numpy(rng.standard_normal((48, 64)).astype(np.float32))
    B = sess.from_numpy(rng.standard_normal((64, 32)).astype(np.float32))
    adj = (rng.random((32, 32)) < 0.2).astype(np.float32)
    sess.register("A", sess.from_numpy(adj, integral=True))

    def q_int():
        return sess.table("A").expr().multiply(
            sess.table("A").expr())

    # fresh executes (one batch, the int query riding it for the
    # exact path), the same batch again = whole hits, then a
    # superexpression = interior substitution
    batch = [A.expr().multiply(B.expr()),
             A.expr().multiply(B.expr()).multiply_scalar(2.0),
             q_int()]
    sess.run_many(batch)
    sess.run_many(batch)
    sess.run(A.expr().multiply(B.expr()).multiply_scalar(3.0))
    # rebind + delta patch (docs/IVM.md): the patched entry's next
    # serve is the ivm_patched path, exact (integer counts)
    rows = rng.integers(0, 32, 5)
    cols = rng.integers(0, 32, 5)
    sess.register_delta("A", (rows, cols, np.ones(5, np.float32)),
                        kind="coo")
    sess.run(q_int())
    return sess


def main(args) -> int:
    """``python -m matrel_tpu why`` — render lineage records from the
    event log, or (``--audit``) drive the self-contained workload and
    replay sampled lineages fresh."""
    if getattr(args, "audit", False):
        sess = _audit_workload()
        verdict = audit(sess, sample=args.sample)
        for r in verdict["results"]:
            status = "ok" if r["ok"] else "FAIL"
            detail = (f"bit-equal" if r.get("exact")
                      else f"rel_err {r.get('rel_err', 0.0):.3e} "
                           f"<= tol {r.get('tol', 0.0):.3e}")
            if not r["ok"]:
                detail = r.get(
                    "error",
                    f"rel_err {r.get('rel_err', 0.0):.3e} "
                    f"> tol {r.get('tol', 0.0):.3e}")
            print(f"audit {r['query_id']} [{r['path']}] "
                  f"{status}: {detail}")
        print(f"audit: {verdict['sampled']} sampled, "
              f"{verdict['failed']} failed, "
              f"{verdict['skipped_no_expr']} unreplayable"
              f" -> {'OK' if verdict['ok'] else 'FAILED'}")
        if getattr(args, "check", False):
            return 0 if verdict["ok"] else 1
        return 0
    from matrel_tpu.obs.events import read_events
    events = read_events(getattr(args, "log", None) or None,
                         kinds=("provenance",))
    key = getattr(args, "key", None)
    if key:
        events = [e for e in events
                  if key in e.get("key_hash", "")
                  or key == e.get("query_id")]
    last = getattr(args, "last", None) or 10
    events = events[-last:]
    if not events:
        print("no provenance records (is obs_provenance > 0 and "
              "obs_level != 'off'?)")
        return 0
    for e in events:
        print(render(e))
    return 0
