"""Measured per-op plan analysis — ``session.explain(expr, analyze=True)``.

The single most useful debugging surface the reference's Spark UI
provides is the per-stage timeline next to the plan: which operator the
time actually went to, compared against what the planner THOUGHT. This
module is that surface for the TPU rebuild.

How it measures: the compiled plan's optimized tree is lowered a second
time with the executor's ``op_hook`` installed and run EAGERLY (no jit)
— each physical node executes as its own dispatch, is synced
(``block_until_ready``) and wall-clocked. Eager per-op times do not sum
to the fused program's runtime (XLA fuses elementwise traffic into the
matmuls — that is the point of the single-program executor), so the
fused end-to-end time is measured too and printed alongside; the per-op
column answers "where does the time go", the fused line answers "what
does it cost in production". Strictly off-hot-path: nothing here runs
unless analysis was explicitly requested.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

import jax


def measure_per_op(plan) -> Tuple[Dict[int, Tuple[str, float]], float]:
    """Run the plan's physical tree once, eagerly, timing every node.

    Returns ``(per_op, eager_total_s)`` where ``per_op`` maps node uid →
    (label, seconds) — EXCLUSIVE of children (the executor's op_hook
    subtracts time spent in child frames), so the per-op values sum to
    roughly the eager total instead of multiplying it by tree depth.
    Shared DAG nodes execute (and are timed) once, like in the real
    executor's memo. Autotune SpMV reroutes are not re-derived
    here — analysis times the hand-default dispatches.
    """
    from matrel_tpu import executor as executor_lib

    per_op: Dict[int, Tuple[str, float]] = {}

    def hook(node, label, seconds):
        per_op[node.uid] = (label, seconds)

    low = executor_lib.Lowerer(plan.mesh, plan.config, op_hook=hook)
    roots = (plan.optimized if isinstance(plan.optimized, tuple)
             else (plan.optimized,))
    fn = low.lower_multi(roots, plan.leaf_order)
    arrays = [l.attrs["matrix"].data for l in plan.leaf_order]
    t0 = time.perf_counter()
    out = fn(*arrays)
    jax.block_until_ready(out)
    return per_op, time.perf_counter() - t0


def measure_fused(plan) -> float:
    """End-to-end seconds for ONE synced run of the real jitted program
    (warmed first so the number is execution, not XLA compilation)."""
    arrays = [l.attrs["matrix"].data for l in plan.leaf_order]
    jax.block_until_ready(plan.jitted(*arrays, *plan.extra_args))
    t0 = time.perf_counter()
    jax.block_until_ready(plan.jitted(*arrays, *plan.extra_args))
    return time.perf_counter() - t0


def _fmt_bytes(b) -> str:
    if b is None:
        return "?"
    b = float(b)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if b < 1024.0 or unit == "GiB":
            return f"{b:.1f}{unit}"
        b /= 1024.0
    return f"{b:.1f}GiB"


def _fusion_stamps(plan) -> Dict[int, dict]:
    """uid -> stamp attrs for every fused-region root in the plan's
    optimized tree(s) (ir/fusion.py) — empty with fusion off."""
    from matrel_tpu.ir import fusion as fusion_lib
    roots = (plan.optimized if isinstance(plan.optimized, tuple)
             else (plan.optimized,))
    out: Dict[int, dict] = {}
    for r in roots:
        for node in fusion_lib.collect_stamps(r):
            out[node.uid] = node.attrs
    return out


def render(plan, per_op: Dict[int, Tuple[str, float]],
           fused_s: float) -> str:
    """Physical tree annotated with measured per-op milliseconds and,
    per matmul, the planner's choice + its estimated ICI bytes/FLOPs —
    measured-vs-estimated on one screen. Fused regions (ir/fusion.py)
    report their EXCLUSIVE ms on the region-root row with absorbed
    members marked "(in fused region)" — never zero-ms ghost rows that
    would skew the drift auditor's per-op samples."""
    from matrel_tpu import executor as executor_lib
    decisions = {d["uid"]: d
                 for d in executor_lib.plan_matmul_decisions(plan)
                 if "uid" in d}
    stamps = _fusion_stamps(plan)
    member_uids = {u for a in stamps.values()
                   for u in (a.get("fused_members") or ())}
    lines = ["== Analyzed physical plan (per-op measured, eager) =="]
    printed = set()

    def walk(n, indent):
        pad = "  " * indent
        extra = ""
        if n.kind == "matmul":
            extra = f" strategy={n.attrs.get('strategy', 'xla')}"
            if "strategy_source" in n.attrs:
                extra += f"[{n.attrs['strategy_source']}]"
        elif n.kind == "elemwise":
            extra = f" op={n.attrs['op']}"
        elif n.kind == "scalar":
            extra = f" op={n.attrs['op']} v={n.attrs['value']}"
        elif n.kind == "agg":
            extra = f" {n.attrs['agg']}/{n.attrs['axis']}"
        elif n.kind in ("join_rows", "join_cols") \
                and "replicate" in n.attrs:
            extra = f" replicate={n.attrs['replicate']}"
        timed = per_op.get(n.uid)
        if n.uid in printed:
            lines.append(f"{pad}{n.kind}{extra} shape={n.shape} "
                         f"(shared — timed above)")
            return
        printed.add(n.uid)
        if n.uid in stamps:
            a = stamps[n.uid]
            extra += (f" fused={a.get('fused_region')} "
                      f"members={len(a.get('fused_members') or ()) + 1}")
        ms = f" [{timed[1] * 1e3:.3f} ms]" if timed else ""
        if not timed and n.uid in member_uids:
            ms = " (in fused region — ms attributed to region root)"
        line = f"{pad}{n.kind}{extra} shape={n.shape}{ms}"
        d = decisions.get(n.uid)
        if d is not None:
            if d.get("precision_tier"):
                # chosen precision tier + the pass count the cost
                # model billed (docs/PRECISION.md)
                line += (f" tier={d['precision_tier']}"
                         f"x{d.get('est_passes', '?')}")
            if d.get("est_ici_bytes") is not None:
                line += (f" est_ici={_fmt_bytes(d['est_ici_bytes'])}"
                         f" flops={d['flops']:.3g}")
            elif d.get("dispatch"):
                line += f" dispatch={d['dispatch']} flops={d['flops']:.3g}"
                if d.get("est_saved_flops") is not None:
                    # SpGEMM records: what the tile-intersection saved
                    # vs densifying (planner.matmul_decisions)
                    line += (
                        f" est_saved_flops={d['est_saved_flops']:.3g}"
                        f" est_saved_hbm="
                        f"{_fmt_bytes(d.get('est_saved_hbm_bytes'))}")
        lines.append(line)
        for c in n.children:
            walk(c, indent + 1)

    roots = (plan.optimized if isinstance(plan.optimized, tuple)
             else (plan.optimized,))
    for r in roots:
        walk(r, 0)
    eager_total = sum(s for _, s in per_op.values())
    lines.append(f"== Eager per-op total: {eager_total * 1e3:.3f} ms; "
                 f"fused program: {fused_s * 1e3:.3f} ms ==")
    return "\n".join(lines)


def analyze_record(plan, per_op: Dict[int, Tuple[str, float]],
                   fused_s: float) -> dict:
    """The ``analyze`` event-log record: the measured per-op tree
    joined (by uid) to the plan's decision records — the cost-model
    drift auditor's highest-fidelity sample source (obs/drift.py reads
    these back to calibrate estimated bytes/FLOPs against measured
    per-op milliseconds, per strategy / shape class / backend).

    Fused-region rows carry ``fused_region`` + ``members`` so the
    auditor joins an absorbed anchor's decision to the region's
    measured ms BY MEMBERSHIP and keys the sample ``fused:<sig>`` —
    absorbed ops contribute no zero-ms ghost samples."""
    from matrel_tpu import executor as executor_lib
    stamps = _fusion_stamps(plan)
    rows = []
    for uid, (label, seconds) in sorted(per_op.items()):
        row = {"uid": uid, "label": label,
               "ms": round(seconds * 1e3, 4)}
        a = stamps.get(uid)
        if a is not None:
            row["fused_region"] = a.get("fused_region")
            row["members"] = sorted(a.get("fused_members") or ())
        rows.append(row)
    return {
        "backend": jax.default_backend(),
        "fused_ms": round(fused_s * 1e3, 3),
        "per_op": rows,
        "matmuls": executor_lib.plan_matmul_decisions(plan),
    }
