"""Multi-slice serving fleet — the scale-out tier over the serve
plane (docs/FLEET.md; ROADMAP item 1).

One session, ``config.fleet_slices`` serving SLICES: the session mesh
partitions into sub-meshes (real ``device.slice_index`` boundaries
when they match the count, contiguous virtual sub-meshes otherwise —
the CPU-testable form tier-1 runs), and each slice owns a full serve
plane of its own: admission queue, worker thread, brownout state,
SLO monitors, and a slice-local result cache, all carried by a
per-slice :class:`~matrel_tpu.session.MatrelSession` on the slice's
sub-mesh. ``session.submit`` becomes a ROUTING decision:

- **Placement** (serve/placement.py): whole-query-to-one-slice (data
  parallel over the query stream) vs spanning one query across the
  full mesh, decided by the PR 4 topology weights — DCN-crossing only
  happens when the byte model says it pays. Span-placed queries carry
  a ``placement`` stamp MV114 verifies.
- **Directory**: a global structural-key directory (catalog-NAME
  keyed, so replicas on different slices agree) maps each cached plan
  key to its owning slice — a hit ANYWHERE in the fleet answers from
  the owner's slice-local cache without recompute. The directory is
  an affinity hint, never a correctness surface: a stale record just
  costs one recompute.
- **Hot-entry replication**: sustained remote demand
  (``config.fleet_replicate_hits``) replicates an entry into the
  demanding slice — priced and staged through the PR 9 reshard
  planner under the existing ``reshard_peak_budget_bytes`` peak-HBM
  budget, provenance-stamped for MV114.
- **Catalog replication**: hot read-only catalog tables replicate per
  slice at fleet construction and on every later ``register`` (a
  rebind re-replicates and invalidates slice caches + directory
  records exactly like the single-controller path).
- **Failover** (the PR 8 ladder generalized): a dead/wedged slice's
  queued entries re-admit onto surviving slices — futures, deadlines
  and tenant attribution intact — and every refusal is TYPED
  (``FleetSliceLost`` / ``AdmissionShed`` / ``DeadlineExceeded``).

Default off (``fleet_slices=0``): ``submit`` runs the historical
single-controller pipeline and ZERO fleet objects are constructed
(the brownout/breaker structural-zero contract, poisoned-init
test-enforced).

matlint ML014 pins cross-slice state mutation onto THIS module: no
other serve/ module may write another slice's result cache.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import logging
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from typing import Dict, Optional

import numpy as np

from matrel_tpu.core import mesh as mesh_lib
from matrel_tpu.resilience import retry as retry_lib
from matrel_tpu.resilience.errors import (AdmissionShed,
                                          DeadlineExceeded,
                                          FleetSliceLost,
                                          PipelineClosed)
from matrel_tpu.serve import placement as placement_lib
from matrel_tpu.serve.result_cache import CacheEntry, result_nbytes
from matrel_tpu.utils import lockdep

log = logging.getLogger("matrel_tpu.serve.fleet")


def _fail(fut: Future, ex: BaseException) -> None:
    if fut.set_running_or_notify_cancel() and not fut.done():
        fut.set_exception(ex)


_remaining = retry_lib.deadline_left


# ---------------------------------------------------------------------------
# Directory — plan key -> owning slice, hit-anywhere protocol
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DirectoryRecord:
    """One fleet-keyed entry's ownership record. ``owner_key`` is the
    owning slice's LOCAL result-cache key (its session's id-based
    structural key + tier prefix) — what the fleet looks up in the
    owner's cache on a hit; ``replicas`` maps additional slice ids to
    their local keys after hot-entry replication; ``hits`` counts
    per-slice demand (the replication trigger); ``dep_names`` are the
    catalog names the entry depends on (the rebind-invalidation
    set)."""

    owner: int
    owner_key: str
    nbytes: int
    layout: str
    dtype: str
    dep_names: frozenset
    hits: Dict[int, int] = dataclasses.field(default_factory=dict)
    replicas: Dict[int, str] = dataclasses.field(default_factory=dict)
    #: slices whose migration of THIS record priced out of the reshard
    #: peak budget — memoized so the hottest keys don't re-run
    #: compile_reshard and emit one migrate_priced_out event per
    #: remote hit forever. Dies with the record (rebind, ownership
    #: move), so a changed entry re-prices.
    priced_out: set = dataclasses.field(default_factory=set)


class FleetDirectory:
    """Bounded LRU map of fleet structural keys to
    :class:`DirectoryRecord` — thread-safe; counters feed the
    ``fleet`` obs surface and ``history --summary``."""

    def __init__(self, max_entries: int):
        self.max_entries = max(int(max_entries), 1)
        self._lock = lockdep.make_lock("fleet.directory")
        self._records: "OrderedDict[str, DirectoryRecord]" = \
            OrderedDict()
        self.inserts = 0
        self.hits = 0
        self.remote_hits = 0
        self.misses = 0
        self.evicted = 0
        self.invalidated = 0
        self.stale_inserts = 0
        #: registration generation — bumped under the lock on every
        #: name invalidation / slice drop. An insert for a query that
        #: was ROUTED before the bump is stale (its result was
        #: computed from the old binding) and must not be recorded:
        #: the name-keyed fleet key would otherwise serve the old
        #: value to queries built from the new binding.
        self.reg_gen = 0
        #: restored demand hints (``seed_hits``): per-key historical
        #: hit counts carried across a restart by save_state/restore
        #: (serve/spill.py). NEVER inserted as records — a restored
        #: owner key points at a cache that no longer exists, and
        #: lookup would drop-and-recompute exactly the hot keys. The
        #: first fresh record_insert per key merges its history in,
        #: so the replication trigger (``fleet_replicate_hits``)
        #: re-arms at pre-restart demand instead of from zero. Pure
        #: affinity hint — never a correctness surface.
        self._seed_hits: Dict[str, Dict[int, int]] = {}

    def lookup(self, key: str) -> Optional[DirectoryRecord]:
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                self.misses += 1
                return None
            self._records.move_to_end(key)
            return rec

    def record_insert(self, key: str, rec: DirectoryRecord,
                      expected_gen: Optional[int] = None) -> None:
        with self._lock:
            if (expected_gen is not None
                    and expected_gen != self.reg_gen):
                # a catalog rebind (or slice drop) ran between this
                # query's routing and its completion: the result was
                # computed from the OLD binding, and recording it
                # under the name-keyed fleet key would serve it to
                # queries built from the NEW one — drop the record
                # (the entry itself is id-keyed dead weight in its
                # slice's LRU, unreachable through the fleet)
                self.stale_inserts += 1
                return
            seeded = self._seed_hits.pop(key, None)
            if seeded:
                # restored demand history ADDS to the fresh insert's
                # own counts — a hint re-arms the replication trigger
                # at pre-restart demand, it never erases a live hit
                for sid, n in seeded.items():
                    rec.hits[sid] = rec.hits.get(sid, 0) + n
            old = self._records.pop(key, None)
            if old is not None:
                # ownership moved (owner evicted its copy and another
                # slice recomputed): keep demand history, drop stale
                # replica claims on the new owner's slot
                rec.hits.update(old.hits)
            self._records[key] = rec
            self.inserts += 1
            while len(self._records) > self.max_entries:
                self._records.popitem(last=False)
                self.evicted += 1

    def record_hit(self, key: str, asking_slice: int,
                   remote: bool) -> None:
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                return
            rec.hits[asking_slice] = rec.hits.get(asking_slice, 0) + 1
            self.hits += 1
            if remote:
                self.remote_hits += 1

    def drop(self, key: str) -> None:
        with self._lock:
            if self._records.pop(key, None) is not None:
                self.invalidated += 1

    def invalidate_name(self, name: str) -> int:
        """Drop every record depending on a rebound catalog name —
        the directory face of the result cache's rebind
        invalidation."""
        with self._lock:
            self.reg_gen += 1
            stale = [k for k, r in self._records.items()
                     if name in r.dep_names]
            for k in stale:
                del self._records[k]
            self.invalidated += len(stale)
            return len(stale)

    def drop_slice(self, slice_id: int) -> int:
        """A dead slice owns nothing: drop its records, strip its
        replica claims."""
        with self._lock:
            self.reg_gen += 1
            stale = [k for k, r in self._records.items()
                     if r.owner == slice_id]
            for k in stale:
                del self._records[k]
            for r in self._records.values():
                r.replicas.pop(slice_id, None)
                r.hits.pop(slice_id, None)
            self.invalidated += len(stale)
            return len(stale)

    def mark_priced_out(self, key: str, slice_id: int) -> None:
        """Memoize one slice's priced-out migration verdict on the
        CURRENT record (under the lock — the record_hit mutation
        discipline). A later record under the same key starts
        clean."""
        with self._lock:
            rec = self._records.get(key)
            if rec is not None:
                rec.priced_out.add(slice_id)

    def drop_replica(self, key: str, slice_id: int) -> None:
        """Strip ONE slice's replica claim (its copy was evicted or
        its slice died) without touching the owner's record — the
        hit-anywhere protocol falls back to the owner."""
        with self._lock:
            rec = self._records.get(key)
            if rec is not None:
                rec.replicas.pop(slice_id, None)

    def claim_replica(self, key: str, slice_id: int,
                      local_key: str,
                      expected_gen: Optional[int] = None) -> bool:
        """Attach a replica claim to the CURRENT record for ``key`` —
        under the lock, so a claim staged against a record the
        directory replaced or evicted mid-migration lands nowhere
        (the caller then reclaims the orphaned cache entry) instead
        of on a discarded object the hit-anywhere protocol can never
        reach. ``expected_gen`` is the registration generation the
        migration was staged under (the ``record_insert`` idiom): a
        rebind between staging and claim means the copied value
        belongs to the OLD binding while the record now found under
        the key describes the NEW one — claiming would serve stale
        answers, so the claim refuses and the caller reclaims the
        replica."""
        with self._lock:
            if (expected_gen is not None
                    and expected_gen != self.reg_gen):
                self.stale_inserts += 1
                return False
            rec = self._records.get(key)
            if rec is None:
                return False
            rec.replicas[slice_id] = local_key
            return True

    def export_state(self) -> list:
        """JSON-safe demand snapshot for save_state (serve/spill.py):
        per-key total hit history plus the cosmetic record fields a
        restore summary reports. Local owner keys are deliberately
        NOT exported — they are id-based and die with the process."""
        with self._lock:
            out = []
            for key, rec in self._records.items():
                out.append({
                    "key": key,
                    "nbytes": int(rec.nbytes),
                    "layout": rec.layout,
                    "dtype": rec.dtype,
                    "dep_names": sorted(rec.dep_names),
                    "hits": {str(s): int(n)
                             for s, n in rec.hits.items()},
                })
            # not-yet-consumed hints from a previous restore carry
            # forward (restart-of-a-restart)
            for key, hits in self._seed_hits.items():
                out.append({"key": key, "hits": {str(s): int(n)
                                                 for s, n in
                                                 hits.items()}})
            return out

    def seed_hints(self, records) -> int:
        """Install restored demand hints (see ``_seed_hits``).
        Bounded by ``max_entries``; malformed rows are skipped — a
        snapshot is never a correctness surface."""
        installed = 0
        with self._lock:
            for rec in records:
                if len(self._seed_hits) >= self.max_entries:
                    break
                if not isinstance(rec, dict):
                    continue
                key = rec.get("key")
                hits = rec.get("hits")
                if not isinstance(key, str) or not isinstance(
                        hits, dict):
                    continue
                slot = self._seed_hits.setdefault(key, {})
                for sid, n in hits.items():
                    try:
                        slot[int(sid)] = (slot.get(int(sid), 0)
                                          + int(n))
                    except (TypeError, ValueError):
                        continue
                installed += 1
        return installed

    def info(self) -> dict:
        with self._lock:
            return {"entries": len(self._records),
                    "max_entries": self.max_entries,
                    "inserts": self.inserts,
                    "hits": self.hits,
                    "remote_hits": self.remote_hits,
                    "misses": self.misses,
                    "evicted": self.evicted,
                    "invalidated": self.invalidated,
                    "stale_inserts": self.stale_inserts,
                    "seed_hints": len(self._seed_hits)}


# ---------------------------------------------------------------------------
# Slices
# ---------------------------------------------------------------------------


class FleetSlice:
    """One serving slice: a full :class:`MatrelSession` on the
    slice's sub-mesh (its own plan cache, result cache, admission
    queue, worker, brownout/SLO state) plus fleet-side bookkeeping.
    ``names_by_id`` maps this slice's replica matrix ids back to
    catalog names — the failover rebind's source vocabulary."""

    def __init__(self, slice_id: int, session):
        self.slice_id = slice_id
        self.session = session
        self.alive = True
        self.submitted = 0
        self.names_by_id: Dict[int, str] = {}

    @property
    def devices(self) -> int:
        return int(np.prod(self.session.mesh.devices.shape))

    def queue_depth(self) -> int:
        pipe = self.session._serve
        return pipe._q.qsize() if pipe is not None else 0

    def snapshot(self) -> dict:
        sess = self.session
        out = {"id": self.slice_id,
               "alive": self.alive,
               "devices": self.devices,
               "submitted": self.submitted,
               "queued": self.queue_depth()}
        if sess._rc_enabled():
            out["result_cache"] = sess._result_cache.info()
        if sess._slo is not None:
            out["slo"] = sess._slo.snapshot()
        if sess._brownout is not None:
            out["brownout"] = sess._brownout.snapshot()
        return out


# ---------------------------------------------------------------------------
# The controller
# ---------------------------------------------------------------------------


class FleetController:
    """The fleet plane of one session (built lazily on the first
    ``submit`` when ``config.fleet_slices >= 1``). The parent session
    stays the SPAN executor — full-mesh programs run through its own
    pipeline — while slice-placed queries route to per-slice
    sessions."""

    def __init__(self, session):
        from matrel_tpu.session import MatrelSession
        self.session = session
        self.config = session.config
        n = int(self.config.fleet_slices)
        meshes, source = mesh_lib.slice_meshes(session.mesh, n)
        self.source = source
        # per-slice sessions: same knobs as the parent except the
        # recursion/port hazards — a slice must never build its own
        # fleet, and two slices must never race one metrics port
        slice_cfg = self.config.replace(fleet_slices=0,
                                        obs_metrics_port=0,
                                        mesh_shape=None)
        # execution arbitration: the parent's span programs and every
        # slice's programs share (subsets of) one device pool on a
        # single-process deployment — two collective programs in
        # flight over overlapping device lists deadlock the
        # cross-program rendezvous (colliding run-ids, one rendezvous
        # key; observed on the CPU backend, and the same
        # order-inversion hazard exists on shared TPU domains). ONE
        # RLock serializes dispatch-to-completion across the fleet;
        # cache/directory hits, planning and admission never take it.
        # Real multi-host slice deployments run one process per slice
        # — there the lock is trivially uncontended.
        self._exec_lock = lockdep.make_rlock("fleet.exec", dispatch_ok=True)
        session._exec_lock = self._exec_lock
        self.slices = []
        for i, m in enumerate(meshes):
            s = MatrelSession(mesh=m, config=slice_cfg)
            s._slice_tag = i
            s._exec_lock = self._exec_lock
            self.slices.append(FleetSlice(i, s))
        self.directory = FleetDirectory(self.config.fleet_directory_max)
        self._lock = lockdep.make_rlock("fleet.controller")
        # registration plane: serializes on_register end-to-end
        # (map surgery + directory invalidation + re-replication) so
        # two rebinds of one name cannot interleave, WITHOUT holding
        # the controller lock across _replicate's device->host
        # staging — that hold span stalled kill_slice/failover and
        # every controller-lock reader behind a host transfer (the
        # LK102 drain-wedge class). Never taken while _lock is held.
        # dispatch_ok: holding it across _replicate's transfers is
        # the lock's entire purpose — only rebinds contend on it.
        self._reg_lock = lockdep.make_lock("fleet.registration",
                                           dispatch_ok=True)
        self._repl_inflight: set = set()
        self._repl_threads: list = []
        self._rr = itertools.count()
        self._names: Dict[int, str] = {}     # parent matrix id -> name
        self.placed = {"slice": 0, "span": 0}
        self.pinned = 0
        self.migrations = 0
        self.migrations_priced_out = 0
        self.failovers = 0
        self.requeued = 0
        for name in sorted(session.catalog):
            self._replicate(name, session.catalog[name])

    # -- catalog replication ----------------------------------------------

    def _replicate(self, name: str, matrix) -> None:
        """Replicate one catalog table into every slice (the
        hot-read-only-table contract). Dense BlockMatrix tables
        rebuild on each slice's sub-mesh; anything else (sparse
        stacks, COO) is SHARED when the slice mesh is the parent mesh
        (degenerate/oversubscribed slices) and otherwise left
        unreplicated — queries touching it stay full-mesh ("pinned"
        placement), still correct."""
        from matrel_tpu.core.blockmatrix import BlockMatrix
        self._names[id(matrix)] = name
        # host-stage lazily, on the first slice whose mesh differs
        # from the parent's: shared/solo partitions take the
        # share-the-object branch for every slice, and an eager
        # to_numpy() would bill a full device->host transfer per
        # table per register()/rebind for a copy nobody reads
        host = None
        host_failed = False
        replicated = False
        for sl in self.slices:
            if sl.session.mesh == self.session.mesh:
                replica = matrix
            else:
                if (host is None and not host_failed
                        and type(matrix) is BlockMatrix):
                    try:
                        host = np.asarray(matrix.to_numpy())
                    except Exception:
                        host_failed = True
                        log.warning(
                            "fleet: could not host-stage table %r; "
                            "queries over it pin to the full mesh",
                            name, exc_info=True)
                if host is None:
                    continue  # unreplicable on a real sub-mesh: pinned
                replica = BlockMatrix.from_numpy(
                    host, mesh=sl.session.mesh,
                    config=sl.session.config)
            sl.session.register(name, replica)
            sl.names_by_id[id(replica)] = name
            replicated = True
        if not replicated:
            # NO slice holds a replica (sparse/COO table on real
            # sub-meshes, or a failed host stage): leaving the name
            # mapped would make every query over it fleet-eligible,
            # routed to a slice, and bounced through the KeyError
            # fallback — per submit, forever, recorded as the
            # transient "fallback" reason and never counted in the
            # pinned census. Unmapped, fleet_key returns None and the
            # query pins to the full mesh up front.
            del self._names[id(matrix)]

    def on_register(self, name: str, matrix) -> None:
        """Parent-catalog write-through: a (re)bound table
        re-replicates, slice caches invalidate through each slice
        session's own register() rebind path, and directory records
        depending on the name drop."""
        with self._reg_lock:
            with self._lock:
                stale = [i for i, nm in self._names.items()
                         if nm == name]
                for i in stale:
                    del self._names[i]
                for sl in self.slices:
                    # the per-slice reverse maps track the same
                    # binding: a rebind that leaves the old replica's
                    # id behind leaks one entry per slice per tick on
                    # a streaming host (the DeltaPlane._programs
                    # orphan class)
                    for i in [i for i, nm in sl.names_by_id.items()
                              if nm == name]:
                        del sl.names_by_id[i]
                # invalidate BEFORE replicating: _replicate's first
                # step maps the NEW matrix id to the name, so from
                # that moment a concurrent submit built from the new
                # binding resolves the same name-keyed fleet key as
                # the old record — a still-live record would answer
                # it with the OLD value (lookups don't take the
                # controller lock; the reg_gen bump here also drops
                # any old-binding insert in flight)
                self.directory.invalidate_name(name)
            # replicate OUTSIDE the controller lock: host staging is
            # a full device->host transfer per table — under _lock it
            # wedges every controller-lock reader (kill_slice,
            # failover, depth probes) behind the transfer. _reg_lock
            # still serializes rebinds of the same name end-to-end,
            # and _replicate's _names/names_by_id updates are single-
            # key dict ops (lock-free readers see either binding,
            # never a torn one).
            self._replicate(name, matrix)

    # -- helpers ------------------------------------------------------------

    def slice_by_id(self, slice_id: int) -> Optional[FleetSlice]:
        for sl in self.slices:
            if sl.slice_id == slice_id:
                return sl
        return None

    def live_slices(self):
        return [sl for sl in self.slices if sl.alive]

    def _rebind(self, e, target: FleetSlice,
                src_names: Optional[Dict[int, str]] = None):
        """Rebind a query's leaves onto ``target``'s catalog replicas
        (by name). ``src_names`` defaults to the parent-catalog map;
        failover passes the dead slice's own map. Raises KeyError on
        an unnamed/unreplicated leaf — callers treat that as
        placement-ineligible (or a typed failover refusal)."""
        names = src_names if src_names is not None else self._names

        def walk(n):
            if n.kind in ("leaf", "sparse_leaf", "coo_leaf"):
                m = n.attrs["matrix"]
                name = names.get(id(m))
                if name is None:
                    raise KeyError(n.kind)
                replica = target.session.catalog.get(name)
                if replica is None:
                    raise KeyError(name)
                return n if replica is m else n.with_attrs(
                    matrix=replica)
            if not n.children:
                return n
            new = tuple(walk(c) for c in n.children)
            return (n if all(a is b for a, b in zip(new, n.children))
                    else n.with_children(new))

        return walk(e)

    def _dep_names(self, e) -> frozenset:
        out = set()

        def walk(n):
            if n.kind in ("leaf", "sparse_leaf", "coo_leaf"):
                nm = self._names.get(id(n.attrs["matrix"]))
                if nm is not None:
                    out.add(nm)
                return
            for c in n.children:
                walk(c)

        walk(e)
        return frozenset(out)

    # -- submit routing ------------------------------------------------------

    def submit(self, e, sla: str = "default",
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               staleness_ms: Optional[float] = None) -> Future:
        import jax
        from matrel_tpu.session import _prec_prefix
        self.check_health()
        live = self.live_slices()
        if not live:
            fut: Future = Future()
            _fail(fut, FleetSliceLost(-1, "no live slices"))
            return fut
        # capture the registration generation BEFORE the key is built:
        # any rebind from here to completion makes this query's
        # eventual directory insert stale (record_insert drops it)
        reg_gen = self.directory.reg_gen
        fkey = placement_lib.fleet_key(e, self._names,
                                       _prec_prefix(sla))
        eligible = fkey is not None
        loads = {sl.slice_id: sl.queue_depth() for sl in live}
        rr = next(self._rr)
        preferred = placement_lib.pick_slice(loads, rr)
        # directory consult BEFORE the cost model: a hit anywhere in
        # the fleet answers without recompute, wherever placement
        # would have sent the query — the steady-state repeat path
        # pays the key walk and one lookup, never the FLOP/byte model
        if eligible:
            hit = self._directory_answer(e, fkey, sla, preferred,
                                         tenant=tenant)
            if hit is not None:
                return hit
        weights = mesh_lib.axis_weights(self.session.mesh, self.config)
        dec = placement_lib.decide(
            e, self.config, weights,
            total_devices=int(np.prod(
                self.session.mesh.devices.shape)),
            slice_devices=live[0].devices,
            slice_loads=loads,
            backend=jax.default_backend(),
            sla=sla, eligible=eligible,
            rr_tick=rr)
        if dec.mode == "span":
            # census under the controller lock: submit runs
            # concurrently from many client threads and a bare
            # read-modify-write drops counts the artifacts report
            with self._lock:
                if dec.reason == "pinned":
                    self.pinned += 1
                self.placed["span"] += 1
            stamped = e.with_attrs(placement=dec.stamp())
            fut = self.session._submit_pipeline(
                stamped, sla, deadline_ms=deadline_ms, tenant=tenant,
                staleness_ms=staleness_ms)
            self._emit_placement(dec, fkey, "span", None)
            return fut
        sl = self.slice_by_id(dec.slice_id) or live[0]
        try:
            rebound = self._rebind(e, sl)
            fut = sl.session.submit(rebound, precision=sla,
                                    deadline_ms=deadline_ms,
                                    tenant=tenant,
                                    staleness_ms=staleness_ms)
        except (KeyError, PipelineClosed):
            # raced a rebind (KeyError: replica gone between
            # eligibility and routing) or a slice kill (PipelineClosed:
            # the slice's pipeline closed between the live check and
            # the enqueue — kill_slice flips it before stealing, so a
            # racing submit refuses typed here instead of stranding a
            # future in a stopped-worker queue): fall back to the
            # full-mesh session (always correct). NOT counted as
            # "pinned" (that is the un-rebindable-leaves census the
            # traffic artifact reports) and the record says what
            # happened: the cost model chose a slice, routing fell
            # back.
            with self._lock:
                self.placed["span"] += 1
            fut = self.session._submit_pipeline(
                e, sla, deadline_ms=deadline_ms, tenant=tenant,
                staleness_ms=staleness_ms)
            self._emit_placement(
                dataclasses.replace(dec, mode="span",
                                    reason="fallback"),
                fkey, "span", None)
            return fut
        with self._lock:
            sl.submitted += 1
            self.placed["slice"] += 1
        if eligible and sl.session._rc_enabled():
            self._track_insert(fkey, sl, e, rebound, sla, fut,
                               reg_gen)
        self._emit_placement(dec, fkey, "slice", sl.slice_id)
        return fut

    def _local_key(self, sl: FleetSlice, rebound, sla: str) -> str:
        from matrel_tpu.session import _plan_key
        lk, _pins = _plan_key(rebound)
        return sl.session._rc_key_prefix(sla) + lk

    def _track_insert(self, fkey: str, sl: FleetSlice, orig, rebound,
                      sla: str, fut: Future,
                      reg_gen: Optional[int] = None) -> None:
        """Record directory ownership when the slice-placed query
        completes (and its slice cache therefore holds the result).
        ``reg_gen`` is the directory registration generation captured
        at routing — a rebind in flight bumps it and the insert drops
        (the completed result belongs to the OLD binding). The
        owner-key and dep-name walks run in the DONE callback (worker
        thread, at completion), not here: they are O(nodes) each and
        only needed on success — on the submit hot path they doubled
        the structural-walk count per admission. A rebind between
        routing and the late walks is covered by the same reg_gen
        drop (record_insert checks the gen before anything else)."""

        def _done(f: Future) -> None:
            try:
                if f.cancelled() or f.exception() is not None:
                    return
                out = f.result()
                owner_key = self._local_key(sl, rebound, sla)
                dep_names = self._dep_names(orig)
                if sl.session._result_cache.probe(owner_key) is None:
                    # the slice did NOT cache under the routing-time
                    # key — a brownout downshift re-keyed the entry
                    # (prec:fast| + stamp), or the insert was
                    # declined (byte budget). Recording ownership
                    # anyway would seed a dead record every later
                    # lookup drops and re-inserts (churn, and a
                    # cold-slice recompute per repeat under exactly
                    # the overload brownout exists for).
                    return
                from matrel_tpu.ir import expr as expr_mod
                from matrel_tpu.parallel import planner
                self.directory.record_insert(fkey, DirectoryRecord(
                    owner=sl.slice_id,
                    owner_key=owner_key,
                    nbytes=result_nbytes(out),
                    layout=planner._layout_of(expr_mod.leaf(out),
                                              sl.session.mesh),
                    dtype=str(np.dtype(out.dtype)),
                    dep_names=dep_names), expected_gen=reg_gen)
            except Exception:       # the never-fail obs/hint contract
                log.warning("fleet: directory insert dropped",
                            exc_info=True)

        fut.add_done_callback(_done)

    def _directory_answer(self, e, fkey: str, sla: str,
                          preferred: int,
                          tenant: Optional[str] = None
                          ) -> Optional[Future]:
        """The hit-anywhere protocol: when the directory knows an
        owning slice whose cache still holds the key, answer from it
        directly — zero compile, zero execute, wherever placement
        would have routed. ``preferred`` is the slice placement would
        pick (the shared :func:`placement.pick_slice` verdict — the
        cost model itself never runs on a hit): a replica there is
        preferred (that is what replication bought); sustained remote
        demand triggers :meth:`_maybe_replicate`. A served hit is an
        OK outcome for ``tenant``'s SLO objectives on the SERVING
        slice's plane — the steady-state repeat path is the fleet's
        best-performing one, and leaving it unaccounted would starve
        the availability windows of good events and read as burn."""
        t0 = time.perf_counter()  # matlint: disable=ML006 SLO resolution-latency sample — lands in the slo plane's sketches and alert records
        rec = self.directory.lookup(fkey)
        if rec is None:
            return None
        # serving-copy candidates, preference order: the replica on
        # the placement-preferred slice (what replication bought),
        # then the owner. A dead/evicted REPLICA only loses its own
        # claim — the owner's copy is still valid, and dropping the
        # whole record here would force a recompute of exactly the
        # entries hot enough to have been replicated (an
        # evict/recompute/re-replicate churn loop). Only a dead/
        # evicted OWNER copy invalidates the record.
        candidates = []
        if preferred in rec.replicas:
            candidates.append((preferred, rec.replicas[preferred]))
        candidates.append((rec.owner, rec.owner_key))
        ent, serving_id, key = None, rec.owner, rec.owner_key
        for sid, k in candidates:
            sl = self.slice_by_id(sid)
            alive = (sl is not None and sl.alive
                     and sl.session._rc_enabled())
            ent = sl.session._result_cache.lookup(k) if alive else None
            if ent is not None:
                serving_id, key = sid, k
                break
            if sid != rec.owner:
                self.directory.drop_replica(fkey, sid)
        if ent is None:
            # stale OWNER hint (evicted/invalidated/dead since) — one
            # recompute, never a wrong answer
            self.directory.drop(fkey)
            return None
        remote = serving_id != preferred
        self.directory.record_hit(fkey, preferred, remote)
        if self.session._prov is not None:
            # lineage on the PARENT ledger (the fleet-facing surface
            # the caller queries), with the SERVING slice's mesh and
            # SLA config — that is the configuration an audit replay
            # must reproduce the answer under
            sl = self.slice_by_id(serving_id)
            self.session._prov_capture(
                "fleet_replica" if ent.fleet is not None
                else "fleet_directory",
                key, sla, ent=ent,
                fleet={"owner": rec.owner, "serving": serving_id,
                       "remote": remote},
                mesh=sl.session.mesh,
                config=sl.session._sla_config(sla))
        fut: Future = Future()
        fut.set_result(ent.result)
        slo = self.slice_by_id(serving_id).session._slo
        if slo is not None:
            slo.record_ok(tenant,
                          (time.perf_counter() - t0) * 1e3)  # matlint: disable=ML006 SLO resolution-latency sample — lands in the slo plane's sketches and alert records
        if remote:
            # AFTER the future resolves, and off-thread: replication
            # is a device->host->device copy of the whole entry — run
            # inline it would stall the hit fast path (whose entire
            # point is ~zero cost) for the duration of the migration
            self._maybe_replicate(e, fkey, rec, ent, sla,
                                  self.slice_by_id(preferred))
        self._emit_hit(fkey,
                       "directory_remote" if remote
                       else "directory", serving_id)
        return fut

    # -- hot-entry replication (priced through the reshard planner) --------

    def _maybe_replicate(self, e, fkey: str, rec: DirectoryRecord,
                         ent: CacheEntry, sla: str,
                         target: Optional[FleetSlice]) -> None:
        cfg = self.config
        if (cfg.fleet_replicate_hits <= 0 or target is None
                or not target.alive
                or not target.session._rc_enabled()
                or rec.hits.get(target.slice_id, 0)
                < cfg.fleet_replicate_hits
                or target.slice_id in rec.replicas
                or target.slice_id in rec.priced_out):
            return
        with self._lock:
            if fkey in self._repl_inflight:
                return
            self._repl_inflight.add(fkey)
            self._repl_threads = [t for t in self._repl_threads
                                  if t.is_alive()]
        # staged-generation capture (the record_insert idiom): a
        # rebind while the slow copy runs makes the staged value
        # stale — claim_replica refuses the claim under a bumped gen
        reg_gen = self.directory.reg_gen

        def _run() -> None:
            try:
                self._replicate_entry(e, fkey, rec, ent, sla, target,
                                      expected_gen=reg_gen)
            except Exception:   # replication is an optimization — a
                # failure must never fail the query it piggybacked on
                log.warning("fleet: entry replication failed",
                            exc_info=True)
            finally:
                with self._lock:
                    self._repl_inflight.discard(fkey)

        t = threading.Thread(target=_run, name="fleet-replicate",
                             daemon=True)
        with self._lock:
            self._repl_threads.append(t)
        t.start()

    def quiesce_replication(self,
                            timeout: Optional[float] = None) -> None:
        """Wait for in-flight hot-entry migrations (tests, drain):
        replication runs on background threads so the directory-hit
        fast path never pays the copy. ``timeout`` bounds the WHOLE
        wait (absolute deadline across the joins), matching the
        drain contract."""
        t_end = (None if timeout is None
                 else retry_lib.now() + timeout)
        with self._lock:
            threads = list(self._repl_threads)
        for t in threads:
            t.join(timeout=_remaining(t_end))

    def _replicate_entry(self, e, fkey: str, rec: DirectoryRecord,
                         ent: CacheEntry, sla: str,
                         target: FleetSlice,
                         expected_gen: Optional[int] = None) -> None:
        """Stage one hot entry into ``target``'s slice-local cache.
        Priced through the PR 9 reshard planner: the owner-side
        gather of the entry's layout to replicated form compiles as a
        ReshardPlan whose peak must fit the existing
        ``reshard_peak_budget_bytes`` (the migration never gets a
        private budget), and the inter-slice hop bills
        nbytes x the DCN axis weight — both recorded on the ``fleet``
        obs event."""
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.ir import expr as expr_mod
        from matrel_tpu.parallel import planner, reshard
        from matrel_tpu.session import _plan_key, _prec_prefix
        cfg = self.config
        gx, gy = mesh_lib.mesh_grid_shape(self.session.mesh)
        weights = mesh_lib.axis_weights(self.session.mesh, cfg)
        src_layout = reshard.normalize_layout(rec.layout) or "rep"
        plan = reshard.compile_reshard(src_layout, "rep",
                                       float(rec.nbytes), gx, gy,
                                       weights,
                                       cfg.reshard_peak_budget_bytes)
        budget = cfg.reshard_peak_budget_bytes
        if budget > 0 and not plan.fits(budget):
            self.directory.mark_priced_out(fkey, target.slice_id)
            with self._lock:
                self.migrations_priced_out += 1
            self._emit_fleet({"event": "migrate_priced_out",
                              "key_hash": _khash(fkey),
                              "owner": rec.owner,
                              "to": target.slice_id,
                              "nbytes": rec.nbytes,
                              "peak_bytes": plan.peak_bytes,
                              "peak_budget": budget})
            return
        rebound = self._rebind(e, target)
        host = np.asarray(ent.result.to_numpy())
        replica = BlockMatrix.from_numpy(host,
                                         mesh=target.session.mesh,
                                         config=target.session.config)
        lk, pins = _plan_key(rebound)
        key = target.session._rc_key_prefix(sla) + lk
        new_ent = CacheEntry(
            key_hash=_khash(key),
            result=replica,
            pins=tuple(pins),
            dep_ids=target.session._rc_deps(rebound),
            layout=planner._layout_of(expr_mod.leaf(replica),
                                      target.session.mesh),
            dtype=str(np.dtype(replica.dtype)),
            nbytes=result_nbytes(replica),
            expr=rebound,
            prec=_prec_prefix(sla),
            err_bound=ent.err_bound,
            fleet={"owner": rec.owner, "layout": rec.layout,
                   "dtype": rec.dtype})
        if self.session._prov is not None:
            # the replica inherits the owner entry's ancestry: its
            # stamp points back at the record that produced the
            # owner's answer (sanctioned seam — obs/provenance.py)
            self.session._prov.stamp_entry(
                new_ent, "fleet_replica",
                (ent.provenance or {}).get("query_id"))
        if target.session._result_cache.put(
                key, new_ent, cfg.result_cache_max_bytes,
                cfg.result_cache_max_entries):
            if not self.directory.claim_replica(
                    fkey, target.slice_id, key,
                    expected_gen=expected_gen):
                # the record this migration staged against was
                # replaced/evicted mid-flight: the fresh replica is
                # unreachable by the hit-anywhere protocol — reclaim
                # its cache budget instead of leaving LRU dead weight
                target.session._result_cache.drop(key)
                return
            with self._lock:
                self.migrations += 1
            self._emit_fleet({
                "event": "migrate",
                "key_hash": _khash(fkey),
                "owner": rec.owner,
                "to": target.slice_id,
                "nbytes": rec.nbytes,
                "est_dcn_cost": rec.nbytes
                * placement_lib.effective_dcn_weight(weights),
                "reshard_steps": [s.kind for s in plan.steps],
                "peak_bytes": plan.peak_bytes})

    # -- failover ------------------------------------------------------------

    def check_health(self) -> None:
        """Wedge detection on the submit path: a slice whose worker
        thread DIED while entries sit queued (and nobody asked it to
        stop) is failed over exactly like an explicit kill."""
        for sl in self.slices:
            if not sl.alive:
                continue
            pipe = sl.session._serve
            if (pipe is not None and pipe._worker is not None
                    and not pipe._worker.is_alive()
                    and not pipe._stop.is_set()
                    and pipe._q.qsize() > 0):
                self.kill_slice(sl.slice_id, reason="wedged")

    def kill_slice(self, slice_id: int, reason: str = "kill") -> int:
        """Take one slice out of the fleet: mark it dead (placement
        stops considering it, its directory records drop), stop its
        worker, steal its queued entries and re-admit them onto
        surviving slices — futures, deadlines and tenant attribution
        intact. Entries the worker already pulled complete normally
        (their results are still correct — the slice session itself
        is healthy host-side). Returns the number re-admitted."""
        with self._lock:
            sl = self.slice_by_id(slice_id)
            if sl is None or not sl.alive:
                return 0
            sl.alive = False
            stolen = []
            pipe = sl.session._serve
            if pipe is not None:
                # close FIRST, under the pipeline's own submit lock:
                # a racing submit that already passed the closed
                # check has its entry enqueued (the steal below
                # re-admits it); any later one refuses typed
                # (PipelineClosed — fleet.submit falls back to the
                # full-mesh session) instead of stranding a future
                # in a stopped-worker queue
                with pipe._lock:
                    pipe._closed = True
                pipe._stop.set()
                stolen = pipe._q.steal_entries()
            self.directory.drop_slice(slice_id)
            requeued = self._readmit(stolen, sl)
            self.failovers += 1
            self.requeued += requeued
            self._emit_fleet({"event": "slice_kill",
                              "slice": slice_id,
                              "reason": reason,
                              "stolen": len(stolen),
                              "requeued": requeued})
            return requeued

    def _readmit(self, stolen, dead: FleetSlice) -> int:
        """Re-admit stolen queue entries onto surviving slices — the
        PR 8 re-admission discipline generalized across slices. Every
        refusal is typed; nothing is silently dropped."""
        from matrel_tpu.serve.pipeline import _ENTRY_DEFAULTS
        live = self.live_slices()
        ok = 0
        for raw, tenant_key in stolen:
            it = ((*raw, *_ENTRY_DEFAULTS[len(raw) - 3:])
                  if len(raw) < 7 else raw)
            expr, fut, t_enq, sla, dl, tenant, stale = it
            if dl is not None and dl.expired():
                _fail(fut, DeadlineExceeded(
                    dl.budget_ms, dl.elapsed_ms(),
                    context="queued query (slice failover)"))
                continue
            if not self.config.fleet_failover or not live:
                _fail(fut, FleetSliceLost(
                    dead.slice_id,
                    "failover disabled" if live
                    else "no surviving slice"))
                continue
            target = min(live, key=lambda s: s.queue_depth())
            try:
                rebound = self._rebind(expr, target,
                                       src_names=dead.names_by_id)
            except KeyError:
                _fail(fut, FleetSliceLost(
                    dead.slice_id,
                    "query not rebindable onto a survivor"))
                continue
            entry = (rebound, fut, t_enq, sla, dl, tenant, stale)
            pipe = target.session._ensure_serve()
            try:
                # atomic closed-check + enqueue + worker-ensure (the
                # pipeline's own submit invariant): a survivor being
                # concurrently close()d refuses typed instead of
                # stranding the stolen future in a workerless queue
                pipe.readmit_entry(entry, tenant or "")
                target.submitted += 1
                ok += 1
            except AdmissionShed as ex:
                _fail(fut, ex)     # typed — the survivor's bounds hold
            except PipelineClosed:
                _fail(fut, FleetSliceLost(
                    dead.slice_id,
                    "surviving slice's pipeline closed during "
                    "re-admission"))
        return ok

    # -- lifecycle / observability ------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> None:
        """``timeout`` bounds the WHOLE fleet drain (one absolute
        deadline shared across the replication quiesce and every
        slice — the ServePipeline.drain t_abs pattern), not each
        sub-wait: the caller's documented bound must hold however
        many slices the fleet has."""
        t_end = (None if timeout is None
                 else retry_lib.now() + timeout)
        self.quiesce_replication(timeout=_remaining(t_end))
        # live slices first, then killed ones: kill_slice steals only
        # QUEUED entries — a batch its worker had already pulled keeps
        # executing (by design), and the serve_drain contract ("every
        # in-flight batch has materialised") covers those futures too.
        # The stopped worker's finally task_done()s the pulled batch,
        # so a dead pipeline's drain terminates; a genuinely wedged
        # corpse raises the typed DrainTimeout, after the live fleet
        # has already drained within the shared budget.
        for sl in sorted(self.slices, key=lambda s: not s.alive):
            sl.session.serve_drain(timeout=_remaining(t_end))

    def close(self, timeout: Optional[float] = None) -> None:
        t_end = (None if timeout is None
                 else retry_lib.now() + timeout)
        self.quiesce_replication(timeout=_remaining(t_end))
        # close EVERY slice before reporting failure: one wedged
        # slice's DrainTimeout aborting the loop would leave the
        # remaining slices' workers running for the life of the
        # parent. Dead slices (queue already stolen) only log; the
        # first LIVE slice's failure propagates after the sweep.
        first: Optional[BaseException] = None
        for sl in self.slices:
            try:
                sl.session.serve_close(timeout=_remaining(t_end))
            except Exception as ex:
                if sl.alive and first is None:
                    first = ex
                else:
                    log.warning("fleet: slice %d close failed",
                                sl.slice_id, exc_info=True)
        if first is not None:
            raise first

    def export_directory(self) -> list:
        """The directory's demand snapshot for ``save_state()``
        (serve/spill.py) — name-keyed hit histories, no local cache
        keys (those die with the process)."""
        return self.directory.export_state()

    def seed_directory(self, records) -> int:
        """Warm a restarted fleet's directory with a snapshot's
        demand hints (``restore()``'s seam) — see
        :meth:`FleetDirectory.seed_hints`."""
        return self.directory.seed_hints(records)

    def info(self) -> dict:
        return {"slices": [sl.snapshot() for sl in self.slices],
                "source": self.source,
                "directory": self.directory.info(),
                "placed": dict(self.placed),
                "pinned": self.pinned,
                "migrations": self.migrations,
                "migrations_priced_out": self.migrations_priced_out,
                "failovers": self.failovers,
                "requeued": self.requeued}

    def _emit_placement(self, dec, fkey: Optional[str], routed: str,
                        slice_id: Optional[int]) -> None:
        sess = self.session
        if not (sess._obs_enabled() or sess._flight is not None):
            return
        try:
            sess._emit_placement_event({
                "key_hash": _khash(fkey) if fkey else None,
                "mode": dec.mode,
                "routed": routed,
                "slice": slice_id,
                "reason": dec.reason,
                "coeff_source": dec.coeff_source,
                "est_slice_ms": round(dec.est_slice_ms, 4),
                "est_span_ms": round(dec.est_span_ms, 4),
                "weights": list(dec.weights),
                "dcn_axis": dec.dcn_axis,
            })
        except Exception:    # the never-fail obs contract
            log.warning("obs: placement event dropped", exc_info=True)

    def _emit_hit(self, fkey: str, routed: str,
                  serving_id: int) -> None:
        """The directory-hit placement record: no cost model ran
        (the fast path's whole point), so the record carries the
        routing outcome only — ``mode: "hit"``, no estimates, no
        coefficient provenance (docs/OBSERVABILITY.md)."""
        sess = self.session
        if not (sess._obs_enabled() or sess._flight is not None):
            return
        try:
            sess._emit_placement_event({
                "key_hash": _khash(fkey),
                "mode": "hit",
                "routed": routed,
                "slice": serving_id,
                "reason": "directory",
            })
        except Exception:    # the never-fail obs contract
            log.warning("obs: placement event dropped", exc_info=True)

    def _emit_fleet(self, record: dict) -> None:
        sess = self.session
        if not (sess._obs_enabled() or sess._flight is not None):
            return
        try:
            sess._emit_fleet_event(record)
        except Exception:
            log.warning("obs: fleet event dropped", exc_info=True)


def _khash(key: Optional[str]) -> Optional[str]:
    if key is None:
        return None
    return hashlib.sha1(key.encode()).hexdigest()[:16]
