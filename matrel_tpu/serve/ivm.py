"""DeltaPlane — incremental view maintenance over the result cache
(docs/IVM.md).

``session.register_delta(name, delta)`` routes here: instead of the
transitive invalidation a catalog rebind pays today, every cached
entry depending on the rebound matrix is PATCHED in place through the
delta algebra (ir/delta.py) when a rule applies and the pricing says
the patch beats recompute; ineligible or priced-out entries fall back
to exactly the historical kill, so correctness never regresses.

The plane owns:
  * generation bookkeeping — the ``delta:<gen>|`` key-prefix idiom
    (session._rc_key_prefix), with surviving un-dependent entries
    RENAMED across the generation so they keep hitting;
  * delta propagation order — dependents patch smallest-expression
    first, and each patched entry's (old, new) value pair enters the
    ``known`` map so downstream entries consume its delta as a leaf
    (the cached-DAG propagation, not per-entry re-derivation);
  * patch-vs-recompute pricing — the flop estimate
    (``delta_est_saved_flops``, recorded on the patch plan's
    matmul_decisions) decided by default, a measured autotune ``ivm|``
    winner overriding it (the ``fuse|`` precedent);
  * steady-state plan reuse — a patch plan whose delta signature and
    sibling set repeat is RE-RUN with rebound factor/dense/result
    leaves (CompiledPlan.run(bindings=...)) instead of recompiled:
    constant-batch streams pay one compile per entry, ever.

Entry mutation happens ONLY through the result cache's patch/apply
seam (apply_patch / rekey / drop — matlint ML012 pins that).

Nothing here constructs on the default path: the session builds a
DeltaPlane lazily on the first ``register_delta`` (the brownout /
breaker zero-object contract).
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import logging
from typing import Dict, Optional, Tuple

import numpy as np

from matrel_tpu.ir import delta as delta_lib
from matrel_tpu.serve.result_cache import CacheEntry, result_nbytes

log = logging.getLogger("matrel_tpu.ivm")


@dataclasses.dataclass
class PatchProgram:
    """One compiled patch plan, reusable across delta generations for
    the same entry when the delta signature (and the sibling entries
    the plan reads) repeat — the steady-state path of a constant-batch
    stream."""

    plan: object                              # executor.CompiledPlan
    binds: Tuple[Tuple[int, tuple], ...]      # (leaf uid, ivm_role)
    signature: tuple                          # (delta sig, entry core key)
    known_keys: Tuple[str, ...]
    rule: str
    rules: Dict[str, int]
    est_patch_flops: float
    est_full_flops: float
    err_bound: float


class DeltaPlane:
    """Per-session IVM orchestrator (see module docstring)."""

    def __init__(self, session):
        delta_lib._CONSTRUCTED["count"] += 1
        self.sess = session
        self._programs: Dict[int, PatchProgram] = {}
        self._ivm_ids = itertools.count(1)
        self.stats = {"patch_compiles": 0, "patch_reuses": 0,
                      "measured_overrides": 0}

    # -- entry point --------------------------------------------------------

    def apply(self, name: str, old, delta: delta_lib.MatrixDelta) -> dict:
        from matrel_tpu.resilience.retry import now as _now
        sess = self.sess
        cfg = sess.config
        mesh = sess.mesh
        t0 = _now()
        new = delta.apply_to(old, mesh, cfg)
        gen_old = sess._delta_gen
        gen = gen_old + 1
        old_prefix = delta_lib.delta_prefix(gen_old)
        new_prefix = delta_lib.delta_prefix(gen)
        rc = sess._result_cache
        keep_stale = sess._brownout is not None
        deps = frozenset({id(old)})
        snapshot = rc.items_snapshot()
        dependents = [(k, e) for k, e in snapshot if e.dep_ids & deps]
        others = [(k, e) for k, e in snapshot
                  if not (e.dep_ids & deps)]
        # smallest expression first: interior entries (A·A) patch
        # before the composites (trace(A·A·A)) that read their deltas
        dependents.sort(key=lambda kv: _expr_size(kv[1].expr))
        # known-sibling values are NAMESPACED BY TIER PREFIX: a
        # default-SLA patch must never consume a fast-tier sibling's
        # (old, new) pair — that would inject bf16-tier error into a
        # result whose composed bound was built from f32 units (the
        # prec:-prefix isolation contract, applied to propagation)
        known_by_prec: Dict[str, Dict[str, tuple]] = {}
        counters = {"patched": 0, "killed": 0, "priced_out": 0,
                    "reused_plans": 0}
        rules_census: Dict[str, int] = {}
        saved_total = 0.0
        for key, ent in dependents:
            ok = False
            if cfg.delta_patch_mode != "off" and ent.expr is not None:
                try:
                    ok, saved = self._patch_entry(
                        key, ent, old, new, delta, gen, new_prefix,
                        known_by_prec.setdefault(ent.prec, {}),
                        rules_census, counters)
                    saved_total += saved
                except Exception:
                    # a failing patch must degrade to the kill, never
                    # fail the register — the correctness floor
                    log.warning("ivm: patch failed for %s; falling "
                                "back to invalidation",
                                ent.key_hash, exc_info=True)
                    ok = False
            if not ok:
                rc.drop(key, keep_stale=keep_stale,
                        stale_max=cfg.result_cache_max_entries,
                        stale_max_bytes=cfg.result_cache_max_bytes)
                counters["killed"] += 1
        # survivors rename across the generation so they keep hitting
        # (generation 0 had the historical empty prefix)
        for key, _ent in others:
            if key.startswith(old_prefix):
                rc.rekey(key, new_prefix + key[len(old_prefix):])
        rc.rebuild_stale(
            lambda k: (new_prefix + k[len(old_prefix):]
                       if k.startswith(old_prefix) else k), deps)
        # the catalog rebind itself — DIRECT, not register(): the
        # dependent entries were just maintained or killed above;
        # register()'s blanket invalidation would kill the patches
        sess.catalog[name] = new
        sess._delta_gen = gen
        # reconcile the patch-plan cache against the LIVE entry set:
        # entries killed above, evicted under byte pressure, or
        # invalidated by a plain register() since the last delta leave
        # orphaned PatchPrograms whose plans pin old-generation device
        # arrays — unbounded over a long session (the ML011 failure
        # class), so they drop the moment their entry is gone
        live = {e.ivm_id for _k, e in rc.items_snapshot()
                if e.ivm_id is not None}
        self._programs = {i: p for i, p in self._programs.items()
                          if i in live}
        record = {
            "name": name, "gen": gen, "delta_kind": delta.kind,
            "delta_rank": delta.rank, "delta_nnz": delta.nnz,
            "examined": len(dependents),
            "patched": counters["patched"],
            "killed": counters["killed"],
            "priced_out": counters["priced_out"],
            "reused_plans": counters["reused_plans"],
            "rekeyed": len(others),
            "rules": rules_census,
            "est_saved_flops": round(saved_total, 1),
            "ms": round((_now() - t0) * 1e3, 3),
        }
        sess._emit_delta_event(record)
        return record

    # -- one entry ----------------------------------------------------------

    def _patch_entry(self, key: str, ent: CacheEntry, old, new,
                     delta, gen: int, new_prefix: str,
                     known: Dict[str, tuple],
                     rules_census: Dict[str, int],
                     counters: dict) -> Tuple[bool, float]:
        from matrel_tpu import executor as executor_lib
        sess = self.sess
        cfg = sess.config
        mesh = sess.mesh
        ck = delta_lib.core_key(ent.expr, frozenset({id(old)}))
        prog = (self._programs.get(ent.ivm_id)
                if ent.ivm_id is not None else None)
        out_bm = None
        meta: Optional[PatchProgram] = None
        if prog is not None \
                and prog.signature == (delta.signature(), ck) \
                and all(k in known for k in prog.known_keys):
            # steady state: same entry, same-shaped delta, siblings
            # available — rebind the dynamic leaves and re-run
            try:
                bindings = self._bindings(prog, ent, old, new, delta,
                                          known)
                out_bm = self._wrap(prog.plan.run(bindings=bindings))
                meta = prog
                self.stats["patch_reuses"] += 1
                counters["reused_plans"] += 1
            except (KeyError, ValueError):
                out_bm = None       # shape/sibling drift: recompile
        if out_bm is None:
            spec = delta_lib.derive_patch(ent.expr, old, new, delta,
                                          ent.result, mesh, cfg, known)
            if spec is None:
                return False, 0.0
            if not self._decide(spec, ent, cfg, mesh):
                counters["priced_out"] += 1
                return False, 0.0
            if spec.refine is not None:
                res = spec.refine(ent.result, new, delta)
                out_bm = self._wrap(res)
                meta = PatchProgram(
                    plan=None, binds=(), signature=(None,),
                    known_keys=(), rule=spec.rule, rules=spec.rules,
                    est_patch_flops=spec.est_patch_flops,
                    est_full_flops=spec.est_full_flops,
                    err_bound=spec.err_bound)
            else:
                stamp = {"rule": spec.rule, "gen": gen,
                         "est_saved_flops": spec.est_saved_flops}
                plan = executor_lib.compile_expr(
                    spec.expr.with_attrs(ivm_patch=stamp), mesh, cfg)
                # provenance for obs/explain: plan_matmul_decisions
                # threads this onto every decision record as
                # delta_est_saved_flops (the root stamp may not
                # survive the optimizer's rebuild — meta always does)
                plan.meta["ivm"] = dict(stamp)
                out_bm = self._wrap(plan.run())
                self.stats["patch_compiles"] += 1
                meta = PatchProgram(
                    plan=plan,
                    binds=tuple(
                        (l.uid, tuple(l.attrs["ivm_role"]))
                        for l in plan.leaf_order
                        if "ivm_role" in l.attrs),
                    signature=(delta.signature(), ck),
                    known_keys=spec.known_keys,
                    rule=spec.rule, rules=spec.rules,
                    est_patch_flops=spec.est_patch_flops,
                    est_full_flops=spec.est_full_flops,
                    err_bound=spec.err_bound)
        for r, n in meta.rules.items():
            rules_census[r] = rules_census.get(r, 0) + n
        rules_census[meta.rule] = rules_census.get(meta.rule, 0)
        # re-key under the new binding: the substituted expression is
        # structurally what a re-run query over the new catalog value
        # computes, so the patched entry answers it with a plain hit
        from matrel_tpu import session as session_lib
        from matrel_tpu.ir import expr as expr_mod
        from matrel_tpu.parallel import planner
        sub_expr = delta_lib.substitute(ent.expr, old, new)
        structural, pins = session_lib._plan_key(sub_expr)
        new_key = new_prefix + ent.prec + structural
        ivm_id = ent.ivm_id if ent.ivm_id is not None \
            else next(self._ivm_ids)
        new_ent = dataclasses.replace(
            ent,
            key_hash=hashlib.sha1(new_key.encode()).hexdigest()[:16],
            result=out_bm,
            pins=tuple(pins),
            dep_ids=(ent.dep_ids - {id(old)}) | {id(new)},
            layout=planner._layout_of(expr_mod.leaf(out_bm), mesh),
            dtype=str(np.dtype(out_bm.dtype)),
            nbytes=result_nbytes(out_bm),
            expr=sub_expr,
            err_bound=ent.err_bound + meta.err_bound,
            delta_gen=gen,
            delta_rule=meta.rule,
            ivm_id=ivm_id)
        ok = sess._result_cache.apply_patch(
            key, new_key, new_ent, cfg.result_cache_max_bytes,
            cfg.result_cache_max_entries)
        if not ok:
            self._programs.pop(ivm_id, None)
            return False, 0.0
        if sess._prov is not None:
            # one lineage link per applied patch: the chain (and the
            # composed err_bound a later audit replays against) lives
            # on the ledger, the stamp on the entry (sanctioned seam
            # — obs/provenance.py)
            sess._prov.stamp_patched(new_ent, gen, meta.rule,
                                     meta.err_bound)
        if meta.plan is not None:
            self._programs[ivm_id] = meta
        counters["patched"] += 1
        known[ck] = (ent.result, out_bm)
        return True, meta.est_full_flops - meta.est_patch_flops

    # -- helpers ------------------------------------------------------------

    def _wrap(self, res):
        """Refine hooks may hand back host arrays; patch plans hand
        BlockMatrices. One canonical form enters the cache."""
        from matrel_tpu.core.blockmatrix import BlockMatrix
        if isinstance(res, BlockMatrix):
            return res
        arr = np.asarray(res)
        if arr.ndim == 1:
            arr = arr.reshape(-1, 1)
        return BlockMatrix.from_numpy(arr, mesh=self.sess.mesh,
                                      config=self.sess.config)

    def _bindings(self, prog: PatchProgram, ent: CacheEntry, old, new,
                  delta, known: Dict[str, tuple]) -> dict:
        cfg = self.sess.config
        mesh = self.sess.mesh
        fac = delta.factors(mesh, cfg)
        fixed = {
            delta_lib.ROLE_TARGET_OLD: old,
            delta_lib.ROLE_TARGET_NEW: new,
            delta_lib.ROLE_OLD_RESULT: ent.result,
        }
        out = {}
        for uid, role in prog.binds:
            head = role[0]
            if head == "factor_u":
                if fac is None:
                    raise ValueError("delta lost its factored form")
                bm = fac[0]
            elif head == "factor_v":
                if fac is None:
                    raise ValueError("delta lost its factored form")
                bm = fac[1]
            elif head == "delta_dense":
                bm = delta.materialize(mesh, cfg)
            elif head == "known_old":
                bm = known[role[1]][0]
            elif head == "known_new":
                bm = known[role[1]][1]
            else:
                bm = fixed[tuple(role)]
            out[uid] = bm
        return out

    def _decide(self, spec: delta_lib.PatchSpec, ent: CacheEntry,
                cfg, mesh) -> bool:
        """Patch-vs-recompute: the flop estimate decides, a measured
        autotune ``ivm|`` winner overrides it (the fuse| precedent).
        Measurement itself happens lazily through the bench/soak
        harnesses (autotune.lookup_or_measure_ivm with runners) — the
        hot register path only ever LOOKS UP."""
        if cfg.delta_patch_mode == "force":
            return True
        # ties favor the patch: at equal flops the patched entry still
        # amortizes compiles (the recompute arm recompiles every
        # generation — rebinding changes every plan key) and keeps the
        # cache warm
        est_win = spec.est_saved_flops >= 0.0
        if cfg.autotune:
            from matrel_tpu.parallel import autotune
            side = max(ent.result.shape[0], ent.result.shape[1],
                       *spec_shape(spec))
            winner = autotune.lookup_or_measure_ivm(
                spec.rule, side, mesh, cfg)
            if winner in ("patch", "recompute"):
                self.stats["measured_overrides"] += 1
                return winner == "patch"
        return est_win


def spec_shape(spec: delta_lib.PatchSpec) -> tuple:
    e = spec.expr
    return tuple(e.shape) if e is not None else (1, 1)


def _expr_size(e) -> int:
    if e is None:
        return 0
    seen = set()

    def walk(n) -> int:
        if n.uid in seen:
            return 0
        seen.add(n.uid)
        return 1 + sum(walk(c) for c in n.children)

    return walk(e)
