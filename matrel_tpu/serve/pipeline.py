"""Micro-batched admission + async execution pipeline.

``session.submit(expr)`` returns a ``concurrent.futures.Future``; one
admission worker per session drains the submission queue, coalesces up
to ``config.serve_max_batch`` concurrent queries into ONE MultiPlan
(one fusion/CSE domain, shared leaf transfers — ``session.run_many``)
and dispatches it WITHOUT waiting for device completion: JAX's async
dispatch returns arrays whose values are still materialising, so the
worker immediately starts optimize/verify/trace of the next batch while
the device executes this one — the MPMD overlap-dispatch-with-execution
discipline, host-side.

The overlap is BOUNDED: past ``config.serve_max_inflight``
dispatched-but-unsynced batches the worker blocks on the oldest, so
host planning never runs unboundedly ahead of the device (an unbounded
queue would pile un-materialised results — and their HBM — without
backpressure).

Futures resolve with the BlockMatrix as soon as its batch is
DISPATCHED (the array is usable immediately; touching its values
blocks until the device delivers them — ordinary JAX semantics).

Resilience contracts (docs/RESILIENCE.md):

- **Poison-query isolation by batch bisection**: a failing MultiPlan is
  recursively SPLIT instead of failing every sibling future — only the
  poison query's own future resolves with the (typed) error, siblings
  re-admit in halves and complete normally. Depth is bounded by
  log2(batch).
- **Backpressure**: ``config.serve_queue_max`` bounds the admission
  queue; a submit against a full queue raises the typed
  ``AdmissionShed`` rather than growing the queue without bound.
- **Deadlines**: a future whose per-query deadline expires while
  queued — or whose batch finishes past it — resolves with the typed
  ``DeadlineExceeded``; expired entries never reach compilation.
- **Typed shutdown**: ``drain(timeout=...)`` raises ``DrainTimeout``
  instead of hanging on a wedged worker; ``submit`` after ``close()``
  raises ``PipelineClosed`` instead of enqueueing into a dead worker.

Overload control plane (docs/OVERLOAD.md, round 13):

- **Per-tenant admission**: the FIFO queue became the weighted-fair
  :class:`serve.admission.AdmissionQueue` — per-tenant queues,
  stride-scheduled pops (so batch formation is fair by construction),
  per-tenant quota sheds BEFORE the global bound, and deadline-expired
  entries purged at every shed decision point. ``submit`` carries
  ``tenant=`` and ``staleness_ms=``.
- **Adaptive brownout**: when the session owns a
  :class:`resilience.brownout.LoadController` the worker feeds it one
  sample per admission cycle (queue depth, waits, deadline misses);
  rung 1 downshifts default-SLA queries to the "fast" tier (stamped,
  MV112-verified, SLA-key-isolated), rung 2 serves STALE result-cache
  entries to queries declaring ``staleness_ms``, rung 3 sheds
  lowest-weight tenants typed at submit.
- **Circuit breakers**: with a session
  :class:`resilience.breaker.BreakerRegistry`, each entry's plan
  class is gated at batch formation — an OPEN class fails its future
  fast with the typed ``CircuitOpen`` (half-open probe schedule
  attached) instead of riding a batch it would poison.
- **Obs**: one ``overload`` event per admission cycle (rung, tenant
  depths/waits, shed/purge/stale deltas, breaker state) whenever the
  control plane is active.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

from matrel_tpu.obs import trace as trace_lib
from matrel_tpu.resilience import breaker as breaker_lib
from matrel_tpu.resilience import brownout as brownout_lib
from matrel_tpu.resilience import faults as faults_lib
from matrel_tpu.resilience import retry as retry_lib
from matrel_tpu.resilience.errors import (AdmissionShed, CircuitOpen,
                                          DeadlineExceeded,
                                          DrainTimeout, PipelineClosed)
from matrel_tpu.resilience.retry import Deadline
from matrel_tpu.serve.admission import AdmissionQueue
from matrel_tpu.utils import lockdep

log = logging.getLogger("matrel_tpu.serve")

#: Entry layout: (expr, future, t_enqueue, sla, deadline, tenant,
#: staleness_ms). Legacy white-box callers enqueue shorter tuples;
#: the worker right-pads with these defaults.
_ENTRY_DEFAULTS = ("default", None, "", None)


class ServePipeline:
    """One session's admission queue + worker thread (daemon, started
    on first submit). Not a pool: queries of one session share its
    plan/result caches, so one worker keeps every cache consult
    race-free while the caller's thread stays free to submit."""

    def __init__(self, session):
        self.session = session
        self.max_batch = session.config.serve_max_batch
        self.max_inflight = session.config.serve_max_inflight
        self.queue_max = session.config.serve_queue_max
        # SLO plane (obs/slo.py; None when off): the queue reports
        # typed sheds / purges, this pipeline reports resolution
        # latency and deadline misses — together the full outcome
        # stream the burn-rate monitors watch
        self._slo = getattr(session, "_slo", None)
        self._q = AdmissionQueue(session.config, slo=self._slo)
        self._inflight: "collections.deque" = collections.deque()  # matlint: disable=ML011 bounded by the serve_max_inflight sync loop in _run_group
        self._worker: threading.Thread = None
        self._stop = threading.Event()
        self._closed = False
        # RLock: submit() holds it across the closed-check + enqueue +
        # _ensure_worker (which locks again) so a concurrent close()
        # can never interleave between them
        self._lock = lockdep.make_rlock("serve.pipeline")
        # overload control plane (session-owned; None when off — the
        # bit-identity contract): brownout controller + breakers, plus
        # the last counter snapshot the overload event diffs against
        self._brownout = getattr(session, "_brownout", None)
        self._breakers = getattr(session, "_breakers", None)
        self._overload_active = (
            self._brownout is not None or self._breakers is not None
            or self._slo is not None or bool(self._q.weights))
        self._overload_last: dict = {}
        self.stale_served = 0
        self.deadline_misses = 0
        # late deadline misses (batch finished past a query's SLA),
        # folded into the NEXT cycle's controller sample — one
        # observe() per admission cycle is the hysteresis contract,
        # so _run_group must not sample mid-batch. Worker-thread-only.
        self._late_misses = 0

    # -- public surface ----------------------------------------------------

    def submit(self, expr, sla: str = "default",
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               staleness_ms: Optional[float] = None) -> Future:
        """Enqueue one query; returns its future. ``sla`` is the
        query's precision SLA — the admission worker only coalesces
        same-SLA queries into one MultiPlan (one planning config per
        batch; mixed SLAs run as separate sub-batches).
        ``deadline_ms`` starts the query's deadline clock NOW (queue
        wait counts against it). ``tenant`` names the submitting
        tenant for weighted-fair admission (None = the implicit
        tenant); ``staleness_ms`` declares how old a STALE result-
        cache answer this query tolerates (consumed only at brownout
        rung >= 2 — docs/OVERLOAD.md)."""
        fut: Future = Future()
        dl = Deadline(deadline_ms) if deadline_ms is not None else None
        # enqueue timestamp, not a measurement: its delta lands in the
        # serve event record as queue_wait_ms
        entry = (expr, fut, time.perf_counter(), sla, dl, tenant or "",  # matlint: disable=ML006 queue-wait timestamp — lands in the serve event record
                 staleness_ms)
        # closed-check + enqueue + worker-ensure are ONE atomic step
        # vs close(): a submit that passes the check enqueues with the
        # worker alive BEFORE close() can flip _closed, and close()'s
        # drain then still processes the entry — no future can ever be
        # stranded in a dead queue
        with self._lock:
            if self._closed:
                raise PipelineClosed(
                    "submit after close(): the admission worker is "
                    "stopped — build a new session (or pipeline) to "
                    "serve again")
            # brownout rung 3: shed lowest-weight tenants FIRST —
            # typed, before any queue slot is consumed
            ctl = self._brownout
            if (ctl is not None
                    and ctl.rung() >= brownout_lib.SHED_RUNG
                    and self._q.lowest_weight_tenant(tenant)):
                self._q.record_shed(tenant)
                raise AdmissionShed(self._q.tenant_max
                                    or self._q.global_max,
                                    tenant=tenant, scope="brownout")
            # typed load shed (per-tenant quota first, then the global
            # bound — each after purging deadline-expired entries):
            # the bounded queue protects the queries already admitted
            self._q.put(entry, tenant or "")
            self._ensure_worker()
        return fut

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted query is dispatched AND every
        dispatched batch has materialised on device. ``timeout``
        (seconds) bounds the whole wait: a wedged worker raises the
        typed ``DrainTimeout``; queue state is untouched."""
        t_abs = (retry_lib.now() + timeout
                 if timeout is not None else None)
        # queue.Queue.join() has no timeout — wait the same condition
        # it waits, re-checking the clock on every wakeup
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                rem = (None if t_abs is None
                       else t_abs - retry_lib.now())
                if rem is not None and rem <= 0:
                    raise DrainTimeout(timeout,
                                       self._q.unfinished_tasks)
                self._q.all_tasks_done.wait(rem)
        while self._inflight:
            rem = None if t_abs is None else t_abs - retry_lib.now()
            if rem is not None and rem <= 0:
                raise DrainTimeout(timeout, len(self._inflight))
            try:
                outs = self._inflight.popleft()
            except IndexError:      # worker synced it concurrently
                break
            if not _sync_bounded(outs, rem):
                # a device-side wedge: block_until_ready cannot be
                # interrupted, so the sync ran on a helper thread and
                # the batch goes BACK in front (a later drain — or the
                # still-running helper — can finish it)
                self._inflight.appendleft(outs)
                raise DrainTimeout(timeout, len(self._inflight))

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop the worker after the queue drains. A later ``submit``
        raises the typed ``PipelineClosed``."""
        with self._lock:
            # flip FIRST (atomic vs submit): any submit that already
            # passed the check has its entry enqueued with the worker
            # alive, and the drain below processes it; any later one
            # raises typed
            self._closed = True
        self.drain(timeout=timeout)
        self._stop.set()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def inflight_depth(self) -> int:
        return len(self._inflight)

    # -- worker ------------------------------------------------------------

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._closed:
                return
            if self._worker is None or not self._worker.is_alive():
                self._stop.clear()
                self._worker = threading.Thread(
                    target=self._run, name="matrel-serve", daemon=True)
                self._worker.start()

    def readmit_entry(self, entry, tenant: str) -> None:
        """Fleet-failover seam (serve/fleet.py is the one caller):
        enqueue an already-built entry under the SAME closed-check +
        enqueue + worker-ensure atomicity ``submit`` enforces — a
        stolen future re-admitted into a pipeline that a concurrent
        ``close()`` just flipped would otherwise strand in a closed,
        workerless queue (``_ensure_worker`` no-ops once ``_closed``
        is set). Raises ``PipelineClosed``/``AdmissionShed`` typed;
        the fleet turns either into a typed refusal."""
        with self._lock:
            if self._closed:
                raise PipelineClosed(
                    "re-admission after close(): the admission "
                    "worker is stopped")
            self._q.put(entry, tenant)
            self._ensure_worker()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._slo is not None:
                    # burn decays as the windows slide: a drained
                    # plane must CLEAR its alerts without waiting for
                    # the next query (obs/slo.py tick contract)
                    self._slo.tick()
                continue
            pulled = [first]
            while len(pulled) < self.max_batch:
                try:
                    pulled.append(self._q.get_nowait())
                except queue.Empty:
                    break
            # normalise legacy short entries (pre-SLA white-box
            # callers enqueue (expr, fut, t_enq); later rounds added
            # sla / deadline / tenant / staleness) to the 7-tuple
            pulled = [(*it, *_ENTRY_DEFAULTS[len(it) - 3:])
                      if len(it) < 7 else it for it in pulled]
            # transition each future to RUNNING; a future the caller
            # cancelled while queued drops out here (and can no longer
            # be cancelled mid-flight) — set_result on a cancelled
            # future would raise InvalidStateError and kill the worker,
            # stranding every sibling future of the batch
            batch = [it for it in pulled
                     if it[1].set_running_or_notify_cancel()]
            t_admit = time.perf_counter()  # matlint: disable=ML006 queue-wait timestamp — lands in the serve event record
            cycle_waits = [round((t_admit - it[2]) * 1e3, 3)
                           for it in batch]
            # deadline shed BEFORE compilation: an entry that expired
            # while queued resolves typed and never costs a compile
            live = []
            misses = 0
            for it in batch:
                dl = it[4]
                if dl is not None and dl.expired():
                    _fail(it[1], DeadlineExceeded(
                        dl.budget_ms, dl.elapsed_ms(),
                        context="queued query"))
                    misses += 1
                    if self._slo is not None:
                        self._slo.record_miss(it[5] or None)
                else:
                    live.append(it)
            self.deadline_misses += misses
            # circuit breakers: an entry whose plan class is OPEN
            # fails fast (typed, probe schedule attached) instead of
            # riding — and poisoning — a batch
            if self._breakers is not None:
                admitted = []
                for it in live:
                    try:
                        self._breakers.admit(
                            self._breakers.plan_class(it[0]))
                    except CircuitOpen as ex:
                        _fail(it[1], ex)
                        if self._slo is not None:
                            # a breaker refusal is a shed the tenant
                            # sees — availability budget burn
                            self._slo.record_shed(it[5] or None)
                    else:
                        admitted.append(it)
                live = admitted
            # per-tenant queue waits AT ADMISSION (t_admit) — both the
            # controller and the overload event read these; measuring
            # at emission time would fold compile/dispatch time into
            # a number named "queue wait"
            tenant_waits: dict = {}
            for it, w in zip(batch, cycle_waits):
                tenant_waits.setdefault(it[5] or "", []).append(w)  # matlint: disable=ML013 one admission cycle's event-record assembly — these waits land in the overload event and the controller sample, not a private stopwatch
            # brownout: ONE load sample per admission cycle (late
            # deadline misses from earlier batches fold in here), then
            # act on the (possibly new) rung
            rung = 0
            ctl = self._brownout
            if ctl is not None:
                late, self._late_misses = self._late_misses, 0
                rung = ctl.observe(depth=self._q.qsize(),
                                   waits_ms=cycle_waits,
                                   misses=misses + late,
                                   admitted=len(live))
            stale_served = 0
            if (rung >= brownout_lib.STALE_RUNG
                    and self.session._rc_enabled()):
                # rung 2: a query that DECLARED a staleness tolerance
                # may be answered by the stale ghost of a rebind-
                # invalidated entry — exact answer, slightly old
                # catalog; nothing compiles, nothing executes
                remaining = []
                for it in live:
                    ent = (self.session._rc_stale_probe(
                        it[0], it[3], it[6]) if it[6] else None)
                    if ent is not None:
                        if not it[1].done():
                            it[1].set_result(ent.result)
                        stale_served += 1
                        if self.session._prov is not None:
                            self.session._prov_capture_stale(
                                it[0], ent,
                                AdmissionQueue.entry_provenance(it))
                        if self._slo is not None:
                            self._slo.record_ok(
                                it[5] or None,
                                (time.perf_counter() - it[2]) * 1e3)  # matlint: disable=ML006 SLO resolution-latency sample — lands in the slo plane's sketches and alert records

                        # a cache hit says NOTHING about the class's
                        # execution health — release the (possibly
                        # half-open probe) slot without a transition,
                        # never close a breaker on work that never ran
                        self._breaker_done(it[0], None)
                    else:
                        remaining.append(it)
                live = remaining
                self.stale_served += stale_served
            if rung >= brownout_lib.TIER_RUNG:
                # rung 1: default-SLA queries downshift to the "fast"
                # tier, STAMPED on the expr root so MV112 can verify
                # the claim and the prec:fast| key prefix isolates the
                # browned-out plan/result from full-fidelity ones
                live = [self._downshift(it, rung) for it in live]
            # same-SLA sub-batches, admission order preserved: one
            # MultiPlan compiles under ONE planning config, so a
            # "fast" submission must never ride an "exact" query's
            # batch (precision SLAs are per query, not per batch)
            groups: "collections.OrderedDict" = collections.OrderedDict()
            for it in live:
                groups.setdefault(it[3], []).append(it)
            try:
                for sla, part in groups.items():
                    self._admit_group(sla, part, t_admit, rung)
            finally:
                for _ in pulled:
                    self._q.task_done()
                if self._overload_active:
                    self._emit_overload(rung, tenant_waits, misses,
                                        stale_served)

    @staticmethod
    def _downshift(it, rung: int):
        """Rung >= 1: rewrite one entry's expr/sla for the fast tier.
        Non-default SLAs pass through untouched — an explicit accuracy
        ask is an ask, brownout only downgrades the defaults. The
        stamp carries the AUTHORIZING rung (brownout.downshift_stamp),
        so every downshifted plan shares one cache key regardless of
        the controller's instantaneous rung."""
        if it[3] != "default":
            return it
        stamp = brownout_lib.downshift_stamp(
            it[6] if rung >= brownout_lib.STALE_RUNG else None)
        e = it[0].with_attrs(brownout=stamp)
        return (e, it[1], it[2], "fast", it[4], it[5], it[6])

    def _breaker_done(self, expr, ok, ex: BaseException = None) -> None:
        """Record one admitted entry's terminal outcome against its
        plan-class breaker (no-op when breakers are off). Outcomes
        that say nothing about the class — deadline, shed, abort —
        release the probe slot without a transition."""
        if self._breakers is None:
            return
        cls = self._breakers.plan_class(expr)
        if ok:
            self._breakers.record(cls, True)
        elif ex is not None and breaker_lib.counts_as_failure(ex):
            self._breakers.record(cls, False)
        else:
            self._breakers.record(cls, None)

    def _emit_overload(self, rung: int, tenant_waits: dict,
                       misses: int, stale_served: int) -> None:
        """One ``overload`` record per admission cycle while the
        control plane is active: instantaneous rung/depths, this
        cycle's per-tenant ADMISSION-TIME waits (the same numbers the
        controller sampled), and shed/purge/breaker-transition DELTAS
        (cumulative counters diffed against the last cycle — the
        multi-session-log discipline of the serve roll-up)."""
        sess = self.session
        if not (sess._obs_enabled() or sess._flight is not None):
            return
        try:
            counters = self._q.counters()
            last = self._overload_last
            shed_delta = {
                t: n - last.get("sheds", {}).get(t, 0)
                for t, n in counters["sheds"].items()
                if n - last.get("sheds", {}).get(t, 0)}
            admitted = {t: len(ws) for t, ws in tenant_waits.items()}
            rec = {
                "rung": rung,
                "rung_label": brownout_lib.rung_label(rung),
                "queue_depth": self._q.qsize(),
                "tenant_depths": self._q.tenant_depths(),
                "admitted": admitted,
                "tenant_waits_ms": tenant_waits,
                "sheds": shed_delta,
                "purged_expired": (counters["purged_expired"]
                                   - last.get("purged_expired", 0)),
                "deadline_misses": misses,
                "stale_served": stale_served,
            }
            if self._brownout is not None:
                rec["brownout"] = self._brownout.snapshot()
            if self._slo is not None:
                # the SLO plane's live state rides the overload
                # stream, so `top --log` (and any offline replay)
                # reconstructs burn rates/alert states without the
                # endpoint (obs/top.py snapshot_from_log)
                rec["slo"] = self._slo.snapshot()
            if self._breakers is not None:
                snap = self._breakers.snapshot()
                lt = last.get("breaker_transitions", {})
                rec["breakers"] = {
                    "open": snap["open"],
                    "half_open": snap["half_open"],
                    "transitions": {
                        k: v - lt.get(k, 0)
                        for k, v in snap["transitions"].items()},
                }
                counters["breaker_transitions"] = snap["transitions"]
            self._overload_last = counters
            sess._emit_overload_event(rec)
        except Exception:   # the never-fail obs contract
            log.warning("obs: overload event dropped", exc_info=True)

    def _admit_group(self, sla: str, batch: list, t_admit: float,
                     rung: int = 0) -> None:
        self._run_group(sla, batch, t_admit, depth=0,
                        retries=self.session.config.retry_max_attempts,
                        rung=rung)

    def _run_group(self, sla: str, batch: list, t_admit: float,
                   depth: int, retries: int = 0,
                   rung: int = 0) -> None:
        """Run one same-SLA sub-batch through session.run_many and
        resolve its futures. A failing batch BISECTS: the halves
        re-admit independently, so one poison query fails only its own
        future (typed) while every sibling completes — the worker
        survives regardless. A single-query group that fails TRANSIENT
        re-admits up to ``retries`` times (the admission-level sites
        sit outside run_many's own retry loop), so injected admission
        hiccups converge instead of failing a healthy query."""
        if not batch:
            return
        waits_ms = [round((t_admit - t_enq) * 1e3, 3)
                    for _, _, t_enq, *_ in batch]
        try:
            # fault site "serve_admit" INSIDE the try: an injected
            # admission fault exercises the same bisection/re-admission
            # path as any other batch failure (free when off)
            faults_lib.check("serve_admit", self.session.config)
            # worker-thread tracer activation: the admission
            # span is the serve trail's root — run_many's
            # batch/plan/execute spans parent-link under it,
            # so a chrome export shows queue bubbles next to
            # compile/execute overlap
            with trace_lib.activate(
                    getattr(self.session, "_tracer", None)), \
                    trace_lib.span(
                        "serve.admit", batch=len(batch),
                        inflight=len(self._inflight),
                        bisect_depth=depth,
                        max_wait_ms=(max(waits_ms)
                                     if waits_ms else 0.0)):
                outs = self.session.run_many(
                    [it[0] for it in batch],
                    precision=sla,
                    _queue_wait_ms=waits_ms,
                    _inflight_depth=len(self._inflight),
                    _tenants=[it[5] for it in batch],
                    _brownout_rung=rung or None)
        except Exception as ex:  # noqa: BLE001 — any planning/
            # compile/execute failure either bisects (isolating the
            # poison query), re-admits a transient single, or fails
            # the lone future typed; the worker survives either way
            if depth == 0:
                dump = getattr(self.session, "_flight_auto_dump", None)
                if dump is not None:
                    # the post-mortem trail for a failed serve batch
                    # (no-op when the flight recorder is off)
                    dump(ex, reason="serve_batch_failure")
            emit = getattr(self.session, "_emit_retry_event", None)
            if len(batch) == 1:
                from matrel_tpu.resilience.errors import is_transient
                if retries > 0 and is_transient(ex):
                    if emit is not None:
                        emit(ex, attempt=depth + 1, rung=0,
                             scope="serve_readmit")
                    self._run_group(sla, batch, t_admit, depth + 1,
                                    retries=retries - 1, rung=rung)
                else:
                    # TERMINAL single-query failure: the breaker's
                    # class-health signal (retry budget already spent)
                    self._breaker_done(batch[0][0], False, ex)
                    _fail(batch[0][1], ex)
                    if self._slo is not None:
                        self._slo.record_bad(batch[0][5] or None,
                                             "error")
                return
            # POISON ISOLATION: split and re-admit each half — only
            # the failing query's own future ends up carrying the
            # error. Recursion depth is bounded by log2(batch).
            if emit is not None:
                emit(ex, attempt=depth + 1, rung=0,
                     scope="serve_bisect")
            mid = len(batch) // 2
            self._run_group(sla, batch[:mid], t_admit, depth + 1,
                            retries=retries, rung=rung)
            self._run_group(sla, batch[mid:], t_admit, depth + 1,
                            retries=retries, rung=rung)
        else:
            for it, out in zip(batch, outs):
                fut, dl = it[1], it[4]
                if dl is not None and dl.expired():
                    # the batch finished past this query's deadline:
                    # the future resolves TYPED (the result exists but
                    # the caller's SLA already failed — honoring it
                    # beats handing back a late answer marked on-time).
                    # The miss folds into the NEXT cycle's controller
                    # sample (one observe per cycle — the hysteresis
                    # dwell must not be advanced mid-batch).
                    self.deadline_misses += 1
                    self._late_misses += 1
                    self._breaker_done(it[0], None)
                    _fail(fut, DeadlineExceeded(
                        dl.budget_ms, dl.elapsed_ms(),
                        context="served query"))
                    if self._slo is not None:
                        self._slo.record_miss(it[5] or None)
                else:
                    self._breaker_done(it[0], True)
                    if not fut.done():
                        fut.set_result(out)
                    if self._slo is not None:
                        # resolution latency = enqueue → dispatch-
                        # complete, the serve plane's own SLA clock
                        # since PR 5 (what the traffic harness
                        # measures too)
                        self._slo.record_ok(
                            it[5] or None,
                            (time.perf_counter() - it[2]) * 1e3)  # matlint: disable=ML006 SLO resolution-latency sample — lands in the slo plane's sketches and alert records
            if outs:
                self._inflight.append(outs)
            while len(self._inflight) > self.max_inflight:
                # backpressure: sync the OLDEST dispatched batch
                # before admitting more host-side planning
                try:
                    _sync(self._inflight.popleft())
                except IndexError:
                    break


def _fail(fut: Future, ex: BaseException) -> None:
    if not fut.done():
        fut.set_exception(ex)


def _sync_bounded(outs, rem: Optional[float]) -> bool:
    """Sync one dispatched batch within ``rem`` seconds (None = no
    bound). ``block_until_ready`` itself cannot be interrupted, so the
    bounded form runs it on a daemon helper and gives up on it after
    the budget — returning False so the caller can raise the typed
    ``DrainTimeout`` instead of hanging (the drain contract)."""
    if rem is None:
        _sync(outs)
        return True
    t = threading.Thread(target=_sync, args=(outs,),
                         name="matrel-serve-sync", daemon=True)
    t.start()
    t.join(rem)
    return not t.is_alive()


def _sync(outs) -> None:
    # sanctioned blocking point (utils/lockdep.py): syncing a batch
    # while holding any serve/fleet lock is the PR 8 drain-wedge class
    # — with the sanitizer on, a held unsanctioned lock diagnoses as
    # HeldAcrossDispatch. One flag check when off.
    lockdep.note_dispatch("serve.sync")
    for o in outs:
        try:
            o.data.block_until_ready()
        except Exception:  # a device-side error surfaces at the
            # consumer's own touch of the array; the pipeline only
            # needed the backpressure
            log.warning("serve: in-flight batch sync failed",
                        exc_info=True)
