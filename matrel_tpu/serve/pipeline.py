"""Micro-batched admission + async execution pipeline.

``session.submit(expr)`` returns a ``concurrent.futures.Future``; one
admission worker per session drains the submission queue, coalesces up
to ``config.serve_max_batch`` concurrent queries into ONE MultiPlan
(one fusion/CSE domain, shared leaf transfers — ``session.run_many``)
and dispatches it WITHOUT waiting for device completion: JAX's async
dispatch returns arrays whose values are still materialising, so the
worker immediately starts optimize/verify/trace of the next batch while
the device executes this one — the MPMD overlap-dispatch-with-execution
discipline, host-side.

The overlap is BOUNDED: past ``config.serve_max_inflight``
dispatched-but-unsynced batches the worker blocks on the oldest, so
host planning never runs unboundedly ahead of the device (an unbounded
queue would pile un-materialised results — and their HBM — without
backpressure).

Futures resolve with the BlockMatrix as soon as its batch is
DISPATCHED (the array is usable immediately; touching its values
blocks until the device delivers them — ordinary JAX semantics).

Resilience contracts (docs/RESILIENCE.md):

- **Poison-query isolation by batch bisection**: a failing MultiPlan is
  recursively SPLIT instead of failing every sibling future — only the
  poison query's own future resolves with the (typed) error, siblings
  re-admit in halves and complete normally. Depth is bounded by
  log2(batch).
- **Backpressure**: ``config.serve_queue_max`` bounds the admission
  queue; a submit against a full queue raises the typed
  ``AdmissionShed`` rather than growing the queue without bound.
- **Deadlines**: a future whose per-query deadline expires while
  queued — or whose batch finishes past it — resolves with the typed
  ``DeadlineExceeded``; expired entries never reach compilation.
- **Typed shutdown**: ``drain(timeout=...)`` raises ``DrainTimeout``
  instead of hanging on a wedged worker; ``submit`` after ``close()``
  raises ``PipelineClosed`` instead of enqueueing into a dead worker.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

from matrel_tpu.obs import trace as trace_lib
from matrel_tpu.resilience import faults as faults_lib
from matrel_tpu.resilience import retry as retry_lib
from matrel_tpu.resilience.errors import (AdmissionShed,
                                          DeadlineExceeded,
                                          DrainTimeout, PipelineClosed)
from matrel_tpu.resilience.retry import Deadline

log = logging.getLogger("matrel_tpu.serve")


class ServePipeline:
    """One session's admission queue + worker thread (daemon, started
    on first submit). Not a pool: queries of one session share its
    plan/result caches, so one worker keeps every cache consult
    race-free while the caller's thread stays free to submit."""

    def __init__(self, session):
        self.session = session
        self.max_batch = session.config.serve_max_batch
        self.max_inflight = session.config.serve_max_inflight
        self.queue_max = session.config.serve_queue_max
        self._q: "queue.Queue" = queue.Queue(maxsize=self.queue_max)
        self._inflight: "collections.deque" = collections.deque()
        self._worker: threading.Thread = None
        self._stop = threading.Event()
        self._closed = False
        # RLock: submit() holds it across the closed-check + enqueue +
        # _ensure_worker (which locks again) so a concurrent close()
        # can never interleave between them
        self._lock = threading.RLock()

    # -- public surface ----------------------------------------------------

    def submit(self, expr, sla: str = "default",
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one query; returns its future. ``sla`` is the
        query's precision SLA — the admission worker only coalesces
        same-SLA queries into one MultiPlan (one planning config per
        batch; mixed SLAs run as separate sub-batches).
        ``deadline_ms`` starts the query's deadline clock NOW (queue
        wait counts against it)."""
        fut: Future = Future()
        dl = Deadline(deadline_ms) if deadline_ms is not None else None
        # enqueue timestamp, not a measurement: its delta lands in the
        # serve event record as queue_wait_ms
        entry = (expr, fut, time.perf_counter(), sla, dl)  # matlint: disable=ML006 queue-wait timestamp — lands in the serve event record
        # closed-check + enqueue + worker-ensure are ONE atomic step
        # vs close(): a submit that passes the check enqueues with the
        # worker alive BEFORE close() can flip _closed, and close()'s
        # drain then still processes the entry — no future can ever be
        # stranded in a dead queue
        with self._lock:
            if self._closed:
                raise PipelineClosed(
                    "submit after close(): the admission worker is "
                    "stopped — build a new session (or pipeline) to "
                    "serve again")
            try:
                self._q.put_nowait(entry)
            except queue.Full:
                # typed load shed: the bounded queue protects the
                # queries already admitted — growing it unboundedly
                # would trade one caller's latency for every caller's
                # memory
                raise AdmissionShed(self.queue_max) from None
            self._ensure_worker()
        return fut

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted query is dispatched AND every
        dispatched batch has materialised on device. ``timeout``
        (seconds) bounds the whole wait: a wedged worker raises the
        typed ``DrainTimeout``; queue state is untouched."""
        t_abs = (retry_lib.now() + timeout
                 if timeout is not None else None)
        # queue.Queue.join() has no timeout — wait the same condition
        # it waits, re-checking the clock on every wakeup
        with self._q.all_tasks_done:
            while self._q.unfinished_tasks:
                rem = (None if t_abs is None
                       else t_abs - retry_lib.now())
                if rem is not None and rem <= 0:
                    raise DrainTimeout(timeout,
                                       self._q.unfinished_tasks)
                self._q.all_tasks_done.wait(rem)
        while self._inflight:
            rem = None if t_abs is None else t_abs - retry_lib.now()
            if rem is not None and rem <= 0:
                raise DrainTimeout(timeout, len(self._inflight))
            try:
                outs = self._inflight.popleft()
            except IndexError:      # worker synced it concurrently
                break
            if not _sync_bounded(outs, rem):
                # a device-side wedge: block_until_ready cannot be
                # interrupted, so the sync ran on a helper thread and
                # the batch goes BACK in front (a later drain — or the
                # still-running helper — can finish it)
                self._inflight.appendleft(outs)
                raise DrainTimeout(timeout, len(self._inflight))

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop the worker after the queue drains. A later ``submit``
        raises the typed ``PipelineClosed``."""
        with self._lock:
            # flip FIRST (atomic vs submit): any submit that already
            # passed the check has its entry enqueued with the worker
            # alive, and the drain below processes it; any later one
            # raises typed
            self._closed = True
        self.drain(timeout=timeout)
        self._stop.set()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def inflight_depth(self) -> int:
        return len(self._inflight)

    # -- worker ------------------------------------------------------------

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._closed:
                return
            if self._worker is None or not self._worker.is_alive():
                self._stop.clear()
                self._worker = threading.Thread(
                    target=self._run, name="matrel-serve", daemon=True)
                self._worker.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            pulled = [first]
            while len(pulled) < self.max_batch:
                try:
                    pulled.append(self._q.get_nowait())
                except queue.Empty:
                    break
            # normalise legacy short entries (pre-SLA white-box callers
            # enqueue (expr, fut, t_enq); pre-deadline ones the
            # 4-tuple) to the 5-tuple shape
            pulled = [(*it, *(("default", None)[len(it) - 3:]))
                      if len(it) < 5 else it for it in pulled]
            # transition each future to RUNNING; a future the caller
            # cancelled while queued drops out here (and can no longer
            # be cancelled mid-flight) — set_result on a cancelled
            # future would raise InvalidStateError and kill the worker,
            # stranding every sibling future of the batch
            batch = [it for it in pulled
                     if it[1].set_running_or_notify_cancel()]
            # deadline shed BEFORE compilation: an entry that expired
            # while queued resolves typed and never costs a compile
            live = []
            for it in batch:
                dl = it[4]
                if dl is not None and dl.expired():
                    _fail(it[1], DeadlineExceeded(
                        dl.budget_ms, dl.elapsed_ms(),
                        context="queued query"))
                else:
                    live.append(it)
            t_admit = time.perf_counter()  # matlint: disable=ML006 queue-wait timestamp — lands in the serve event record
            # same-SLA sub-batches, admission order preserved: one
            # MultiPlan compiles under ONE planning config, so a
            # "fast" submission must never ride an "exact" query's
            # batch (precision SLAs are per query, not per batch)
            groups: "collections.OrderedDict" = collections.OrderedDict()
            for it in live:
                groups.setdefault(it[3], []).append(it)
            try:
                for sla, part in groups.items():
                    self._admit_group(sla, part, t_admit)
            finally:
                for _ in pulled:
                    self._q.task_done()

    def _admit_group(self, sla: str, batch: list,
                     t_admit: float) -> None:
        self._run_group(sla, batch, t_admit, depth=0,
                        retries=self.session.config.retry_max_attempts)

    def _run_group(self, sla: str, batch: list, t_admit: float,
                   depth: int, retries: int = 0) -> None:
        """Run one same-SLA sub-batch through session.run_many and
        resolve its futures. A failing batch BISECTS: the halves
        re-admit independently, so one poison query fails only its own
        future (typed) while every sibling completes — the worker
        survives regardless. A single-query group that fails TRANSIENT
        re-admits up to ``retries`` times (the admission-level sites
        sit outside run_many's own retry loop), so injected admission
        hiccups converge instead of failing a healthy query."""
        if not batch:
            return
        waits_ms = [round((t_admit - t_enq) * 1e3, 3)
                    for _, _, t_enq, _, _ in batch]
        try:
            # fault site "serve_admit" INSIDE the try: an injected
            # admission fault exercises the same bisection/re-admission
            # path as any other batch failure (free when off)
            faults_lib.check("serve_admit", self.session.config)
            # worker-thread tracer activation: the admission
            # span is the serve trail's root — run_many's
            # batch/plan/execute spans parent-link under it,
            # so a chrome export shows queue bubbles next to
            # compile/execute overlap
            with trace_lib.activate(
                    getattr(self.session, "_tracer", None)), \
                    trace_lib.span(
                        "serve.admit", batch=len(batch),
                        inflight=len(self._inflight),
                        bisect_depth=depth,
                        max_wait_ms=(max(waits_ms)
                                     if waits_ms else 0.0)):
                outs = self.session.run_many(
                    [e for e, _, _, _, _ in batch],
                    precision=sla,
                    _queue_wait_ms=waits_ms,
                    _inflight_depth=len(self._inflight))
        except Exception as ex:  # noqa: BLE001 — any planning/
            # compile/execute failure either bisects (isolating the
            # poison query), re-admits a transient single, or fails
            # the lone future typed; the worker survives either way
            if depth == 0:
                dump = getattr(self.session, "_flight_auto_dump", None)
                if dump is not None:
                    # the post-mortem trail for a failed serve batch
                    # (no-op when the flight recorder is off)
                    dump(ex, reason="serve_batch_failure")
            emit = getattr(self.session, "_emit_retry_event", None)
            if len(batch) == 1:
                from matrel_tpu.resilience.errors import is_transient
                if retries > 0 and is_transient(ex):
                    if emit is not None:
                        emit(ex, attempt=depth + 1, rung=0,
                             scope="serve_readmit")
                    self._run_group(sla, batch, t_admit, depth + 1,
                                    retries=retries - 1)
                else:
                    _fail(batch[0][1], ex)
                return
            # POISON ISOLATION: split and re-admit each half — only
            # the failing query's own future ends up carrying the
            # error. Recursion depth is bounded by log2(batch).
            if emit is not None:
                emit(ex, attempt=depth + 1, rung=0,
                     scope="serve_bisect")
            mid = len(batch) // 2
            self._run_group(sla, batch[:mid], t_admit, depth + 1,
                            retries=retries)
            self._run_group(sla, batch[mid:], t_admit, depth + 1,
                            retries=retries)
        else:
            for (_, fut, _, _, dl), out in zip(batch, outs):
                if dl is not None and dl.expired():
                    # the batch finished past this query's deadline:
                    # the future resolves TYPED (the result exists but
                    # the caller's SLA already failed — honoring it
                    # beats handing back a late answer marked on-time)
                    _fail(fut, DeadlineExceeded(
                        dl.budget_ms, dl.elapsed_ms(),
                        context="served query"))
                elif not fut.done():
                    fut.set_result(out)
            if outs:
                self._inflight.append(outs)
            while len(self._inflight) > self.max_inflight:
                # backpressure: sync the OLDEST dispatched batch
                # before admitting more host-side planning
                try:
                    _sync(self._inflight.popleft())
                except IndexError:
                    break


def _fail(fut: Future, ex: BaseException) -> None:
    if not fut.done():
        fut.set_exception(ex)


def _sync_bounded(outs, rem: Optional[float]) -> bool:
    """Sync one dispatched batch within ``rem`` seconds (None = no
    bound). ``block_until_ready`` itself cannot be interrupted, so the
    bounded form runs it on a daemon helper and gives up on it after
    the budget — returning False so the caller can raise the typed
    ``DrainTimeout`` instead of hanging (the drain contract)."""
    if rem is None:
        _sync(outs)
        return True
    t = threading.Thread(target=_sync, args=(outs,),
                         name="matrel-serve-sync", daemon=True)
    t.start()
    t.join(rem)
    return not t.is_alive()


def _sync(outs) -> None:
    for o in outs:
        try:
            o.data.block_until_ready()
        except Exception:  # a device-side error surfaces at the
            # consumer's own touch of the array; the pipeline only
            # needed the backpressure
            log.warning("serve: in-flight batch sync failed",
                        exc_info=True)
