"""Micro-batched admission + async execution pipeline.

``session.submit(expr)`` returns a ``concurrent.futures.Future``; one
admission worker per session drains the submission queue, coalesces up
to ``config.serve_max_batch`` concurrent queries into ONE MultiPlan
(one fusion/CSE domain, shared leaf transfers — ``session.run_many``)
and dispatches it WITHOUT waiting for device completion: JAX's async
dispatch returns arrays whose values are still materialising, so the
worker immediately starts optimize/verify/trace of the next batch while
the device executes this one — the MPMD overlap-dispatch-with-execution
discipline, host-side.

The overlap is BOUNDED: past ``config.serve_max_inflight``
dispatched-but-unsynced batches the worker blocks on the oldest, so
host planning never runs unboundedly ahead of the device (an unbounded
queue would pile un-materialised results — and their HBM — without
backpressure).

Futures resolve with the BlockMatrix as soon as its batch is
DISPATCHED (the array is usable immediately; touching its values
blocks until the device delivers them — ordinary JAX semantics).
Compile/planning errors fail every future of their batch.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from concurrent.futures import Future

from matrel_tpu.obs import trace as trace_lib

log = logging.getLogger("matrel_tpu.serve")


class ServePipeline:
    """One session's admission queue + worker thread (daemon, started
    on first submit). Not a pool: queries of one session share its
    plan/result caches, so one worker keeps every cache consult
    race-free while the caller's thread stays free to submit."""

    def __init__(self, session):
        self.session = session
        self.max_batch = session.config.serve_max_batch
        self.max_inflight = session.config.serve_max_inflight
        self._q: "queue.Queue" = queue.Queue()
        self._inflight: "collections.deque" = collections.deque()
        self._worker: threading.Thread = None
        self._stop = threading.Event()
        self._lock = threading.Lock()

    # -- public surface ----------------------------------------------------

    def submit(self, expr, sla: str = "default") -> Future:
        """Enqueue one query; returns its future. ``sla`` is the
        query's precision SLA — the admission worker only coalesces
        same-SLA queries into one MultiPlan (one planning config per
        batch; mixed SLAs run as separate sub-batches)."""
        fut: Future = Future()
        # enqueue timestamp, not a measurement: its delta lands in the
        # serve event record as queue_wait_ms
        self._q.put((expr, fut, time.perf_counter(), sla))  # matlint: disable=ML006 queue-wait timestamp — lands in the serve event record
        self._ensure_worker()
        return fut

    def drain(self) -> None:
        """Block until every submitted query is dispatched AND every
        dispatched batch has materialised on device."""
        self._q.join()
        while self._inflight:
            try:
                outs = self._inflight.popleft()
            except IndexError:      # worker synced it concurrently
                break
            _sync(outs)

    def close(self) -> None:
        """Stop the worker after the queue drains."""
        self.drain()
        self._stop.set()

    @property
    def inflight_depth(self) -> int:
        return len(self._inflight)

    # -- worker ------------------------------------------------------------

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._stop.clear()
                self._worker = threading.Thread(
                    target=self._run, name="matrel-serve", daemon=True)
                self._worker.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            pulled = [first]
            while len(pulled) < self.max_batch:
                try:
                    pulled.append(self._q.get_nowait())
                except queue.Empty:
                    break
            # normalise legacy 3-tuple entries (pre-SLA white-box
            # callers enqueue (expr, fut, t_enq)) to the 4-tuple shape
            pulled = [it if len(it) > 3 else (*it, "default")
                      for it in pulled]
            # transition each future to RUNNING; a future the caller
            # cancelled while queued drops out here (and can no longer
            # be cancelled mid-flight) — set_result on a cancelled
            # future would raise InvalidStateError and kill the worker,
            # stranding every sibling future of the batch
            batch = [it for it in pulled
                     if it[1].set_running_or_notify_cancel()]
            t_admit = time.perf_counter()  # matlint: disable=ML006 queue-wait timestamp — lands in the serve event record
            # same-SLA sub-batches, admission order preserved: one
            # MultiPlan compiles under ONE planning config, so a
            # "fast" submission must never ride an "exact" query's
            # batch (precision SLAs are per query, not per batch)
            groups: "collections.OrderedDict" = collections.OrderedDict()
            for it in batch:
                groups.setdefault(it[3], []).append(it)
            try:
                for sla, part in groups.items():
                    self._admit_group(sla, part, t_admit)
            finally:
                for _ in pulled:
                    self._q.task_done()

    def _admit_group(self, sla: str, batch: list,
                     t_admit: float) -> None:
        """Run one same-SLA sub-batch through session.run_many and
        resolve its futures; a planning/compile failure fails only
        THIS group's futures and the worker survives."""
        waits_ms = [round((t_admit - t_enq) * 1e3, 3)
                    for _, _, t_enq, _ in batch]
        try:
            # worker-thread tracer activation: the admission
            # span is the serve trail's root — run_many's
            # batch/plan/execute spans parent-link under it,
            # so a chrome export shows queue bubbles next to
            # compile/execute overlap
            with trace_lib.activate(
                    getattr(self.session, "_tracer", None)), \
                    trace_lib.span(
                        "serve.admit", batch=len(batch),
                        inflight=len(self._inflight),
                        max_wait_ms=(max(waits_ms)
                                     if waits_ms else 0.0)):
                outs = self.session.run_many(
                    [e for e, _, _, _ in batch],
                    precision=sla,
                    _queue_wait_ms=waits_ms,
                    _inflight_depth=len(self._inflight))
        except Exception as ex:  # noqa: BLE001 — any planning/
            # compile failure fails every future of the batch; the
            # worker survives to serve the next one
            dump = getattr(self.session, "_flight_auto_dump", None)
            if dump is not None:
                # the post-mortem trail for a failed serve batch
                # (no-op when the flight recorder is off)
                dump(ex, reason="serve_batch_failure")
            for _, fut, _, _ in batch:
                if not fut.done():
                    fut.set_exception(ex)
        else:
            for (_, fut, _, _), out in zip(batch, outs):
                if not fut.done():
                    fut.set_result(out)
            if outs:
                self._inflight.append(outs)
            while len(self._inflight) > self.max_inflight:
                # backpressure: sync the OLDEST dispatched batch
                # before admitting more host-side planning
                try:
                    _sync(self._inflight.popleft())
                except IndexError:
                    break


def _sync(outs) -> None:
    for o in outs:
        try:
            o.data.block_until_ready()
        except Exception:  # a device-side error surfaces at the
            # consumer's own touch of the array; the pipeline only
            # needed the backpressure
            log.warning("serve: in-flight batch sync failed",
                        exc_info=True)
