"""Cross-query materialized-result cache — the MatFast persist/RDD-cache
analogue (ICDE 2017 §"in-memory reuse of distributed intermediates").

Entries map the CANONICAL STRUCTURAL plan key of an executed expression
(``session._plan_key`` — the same key the compiled-plan cache uses) to
the BlockMatrix it produced. Keying discipline matters: the key is the
structural string, never a sharding spec or a bare ``id()`` (the ML005
hazard class — spec objects hash by identity across jax versions, and a
recycled id would alias two distinct queries). Every object the key
references by id() rides the entry's ``pins`` tuple, so an address can
never be garbage-collected and reused into a false hit — the plan
cache's pinning contract, applied here.

Invalidation: each entry records the id() set of every source matrix it
was computed from (``dep_ids``, transitively through entries it itself
consumed). A catalog rebind invalidates every entry whose deps
intersect the rebound matrix. Dep ids are only ever compared against
LIVE catalog objects (the session calls ``invalidate_deps(id(old))``
with ``old`` in hand), so a recycled address can at worst invalidate a
valid entry — the safe direction — never keep a stale one.

Eviction: byte-budgeted LRU over the DEVICE bytes each cached result
pins (its padded array). A result larger than the whole budget is
never inserted. Thread-safe — the async serve pipeline's worker and
the caller's thread share one cache.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import threading
from collections import OrderedDict
from typing import FrozenSet, Optional, Tuple

import numpy as np

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.resilience.retry import now as _now
from matrel_tpu.utils import lockdep

_log = logging.getLogger("matrel_tpu.serve")

#: warn-once latch for the result_nbytes fallback (list so tests can
#: reset it without a global statement)
_NBYTES_WARNED = [False]


def result_nbytes(result: BlockMatrix) -> int:
    """Device bytes a cached result pins: its PADDED array. Computed
    from shape/dtype — jax 0.9 arrays may lack .nbytes.

    An array missing even shape/dtype (a foreign array type, a
    donated/deleted buffer) must NOT size as 0: a 0-byte entry escapes
    the LRU byte budget entirely, so a stream of them would pin
    unbounded device memory while the cache believes it is empty.
    Fall back to the UNPADDED ``shape × itemsize`` estimate (the
    logical shape is a plain tuple on the BlockMatrix itself, never
    derived from the array) — an under-estimate of the padded truth,
    but budget-visible — and warn once per process."""
    try:
        return int(np.prod(result.data.shape)) * np.dtype(
            result.data.dtype).itemsize
    except (AttributeError, TypeError):
        pass
    try:
        itemsize = np.dtype(result.data.dtype).itemsize
    except (AttributeError, TypeError):
        itemsize = 4            # f32, the package-wide default dtype
    try:
        est = int(np.prod(result.shape)) * itemsize
    except (AttributeError, TypeError):
        est = 0                 # not a BlockMatrix at all
    if not _NBYTES_WARNED[0]:
        _NBYTES_WARNED[0] = True
        _log.warning(
            "result_nbytes: cached result's array has no usable "
            "shape/dtype; falling back to the unpadded shape*itemsize "
            "estimate (%d bytes) for LRU accounting (warned once)", est)
    return est


@dataclasses.dataclass
class CacheEntry:
    """One cached query result.

    key_hash: short digest of the structural key — the stable name obs
      records and MV107 stamps carry (the full key embeds id()s and is
      meaningless across sessions).
    result: the executed BlockMatrix (device-resident).
    pins: every object the structural key references by id() — held so
      no keyed address can be recycled into a false hit.
    dep_ids: id() of every source matrix this result depends on,
      transitively through consumed cache entries — the
      catalog-rebind invalidation set.
    layout: planner layout vocabulary ("2d"/"row"/"col"/"rep"/"other")
      of the result's spec at insertion — what a substituted leaf
      claims to the planner, and what MV107 re-checks.
    dtype: canonical numpy dtype name of the result at insertion.
    nbytes: device bytes the entry pins (eviction accounting).
    expr: the query expression this result computed (PRE-substitution,
      rebased onto the live binding when patched) — what the delta
      plane (ir/delta.py; docs/IVM.md) derives patches from and what
      MV113's dynamic check re-executes fresh. A plain reference; no
      extra device memory, no behavior change when deltas are unused.
    prec: the precision-tier key prefix this entry keyed under (the
      ``prec:<sla>|`` idiom) — patching re-keys under the SAME tier,
      so SLA isolation survives a delta generation.
    err_bound: composed numeric error bound of the stored result
      (the stamped tier's bound at insertion, PLUS each patch's
      contribution — docs/IVM.md error-bound composition). MV113's
      dynamic check verifies patched results within it; 0 = exact.
    delta_gen: delta generation of the last patch (0 = fresh
      execution, never patched) — the provenance stamp.
    delta_rule: ir/delta.DELTA_RULES member of the last patch.
    ivm_id: stable identity across patch generations (the delta
      plane's patch-plan reuse key; None until first patched).
    fleet: multi-slice provenance (serve/fleet.py; docs/FLEET.md) for
      entries REPLICATED into this slice's cache from another slice:
      ``{"owner": slice_id, "layout": ..., "dtype": ...}`` — the
      owning slice's recorded layout/dtype at replication, which
      MV114 re-checks against the entry's own claims (the MV107
      stale-stamp idiom applied across slices). None (the default)
      for every locally-computed entry — the historical shape.
    provenance: compact lineage stamp (obs tier 4,
      docs/OBSERVABILITY.md) written ONLY at the sanctioned seams —
      ``session._rc_insert`` (fresh execution), the delta plane's
      ``apply_patch`` commit (patch-chain append), and fleet
      replication (ML015 pins every other writer). None (the
      default) when ``obs_provenance`` is off — the historical
      shape, zero objects.
    hits: lifetime consult count of THIS entry (lookup + probe) — the
      expected-reuse signal the spill policy's host→disk demotion gate
      reads (``config.spill_disk_hits``; docs/DURABILITY.md). 0 until
      first consulted; costs one int, no behavior change when spill
      is off.
    spill: tier provenance (serve/spill.py; docs/DURABILITY.md) for
      entries PROMOTED back from a lower tier: ``{"tier": "host"/
      "disk"/"restored", "legs": [...], "est_ms": float, "cost":
      "measured"/"analytic"}`` — which tier the value thawed from and
      the priced transfer legs it paid, which MV117 re-checks against
      the plan vocabulary. None (the default) for every entry that
      has only ever lived in HBM — the historical shape.
    """

    key_hash: str
    result: BlockMatrix
    pins: Tuple
    dep_ids: FrozenSet[int]
    layout: str
    dtype: str
    nbytes: int
    expr: Optional[object] = None
    prec: str = ""
    err_bound: float = 0.0
    delta_gen: int = 0
    delta_rule: Optional[str] = None
    ivm_id: Optional[int] = None
    fleet: Optional[dict] = None
    provenance: Optional[dict] = None
    hits: int = 0
    spill: Optional[dict] = None


class ResultCache:
    """Byte-budgeted LRU over :class:`CacheEntry`, structurally keyed.

    ``lookup`` is the ROOT-level consult (counts hit/miss — the ratio
    serve events and ``result_cache_info()`` report); ``probe`` is the
    interior-substitution consult (counts hits only — a miss there just
    means the walk recurses, not that a query missed the cache).
    """

    def __init__(self):
        self._lock = lockdep.make_rlock("serve.result_cache")
        self._entries: "OrderedDict[str, CacheEntry]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.interior_hits = 0
        self.evicted = 0
        self.invalidated = 0
        # brownout stale graveyard (docs/OVERLOAD.md): entries a
        # rebind invalidated, kept with their invalidation timestamp
        # so rung >= 2 can serve them to queries declaring a
        # staleness_ms tolerance. Populated ONLY when the session asks
        # (keep_stale=True — a brownout controller exists); the
        # default path drops invalidated entries exactly as before.
        # Bounded in ENTRIES and BYTES (stale results stay device-
        # pinned — an entry-only bound would let a few huge ghosts
        # retain device memory far past the live cache's byte budget).
        self._stale: "OrderedDict[str, tuple]" = OrderedDict()
        self._stale_bytes = 0
        self.stale_hits = 0
        # incremental view maintenance (docs/IVM.md): lifetime counts
        # of entries PATCHED in place by a registered delta and of
        # entries renamed across a delta generation — both zero until
        # register_delta is ever used (the bit-identity contract)
        self.patched = 0
        self.rekeyed = 0
        # spill hierarchy (serve/spill.py; docs/DURABILITY.md): the
        # attached SpillManager, or None — the default, and the ONLY
        # state the default config ever sees (zero spill objects).
        # When attached, evictions DEMOTE instead of dropping and
        # lookup/probe fall through to the lower tiers on a miss.
        self.spill = None

    def attach_spill(self, spill) -> None:
        """Wire the tier hierarchy under this cache (session-build
        seam; ``config.spill_enable`` gates the one call site)."""
        with self._lock:
            self.spill = spill

    def _thaw(self, key: str) -> Optional[CacheEntry]:
        """Lower-tier consult on an HBM miss: promote the entry back
        (the spill manager prices + stages the move and stamps
        ``entry.spill``), re-insert it under the HBM budget, and hand
        it back — the caller counts the hit. The entry is served even
        when it no longer fits the HBM budget (a hit is a hit; it just
        isn't re-cached). Lock order: result_cache → spill, the same
        direction ``put``'s demotion takes."""
        if self.spill is None:
            return None
        ent = self.spill.promote(key)
        if ent is None:
            return None
        if not self.put(key, ent, self.spill.hbm_max_bytes,
                        self.spill.hbm_max_entries):
            # larger than the whole HBM budget: serve it, but park the
            # value back in the host tier instead of losing it
            self.spill.demote(key, ent)
        return ent

    def lookup(self, key: str) -> Optional[CacheEntry]:
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = self._thaw(key)
                if ent is None:
                    self.misses += 1
                    return None
                ent.hits += 1
                self.hits += 1
                return ent
            self._entries.move_to_end(key)
            ent.hits += 1
            self.hits += 1
            return ent

    def note_restored_hit(self) -> None:
        """Counter correction for the session's restored-snapshot
        consult (docs/DURABILITY.md): the first-level ``lookup``
        already counted a miss before the name-keyed index thawed the
        value — a served answer must read as the hit it was."""
        with self._lock:
            self.misses = max(self.misses - 1, 0)
            self.hits += 1

    def probe(self, key: str) -> Optional[CacheEntry]:
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = self._thaw(key)
                if ent is None:
                    return None
                ent.hits += 1
                self.interior_hits += 1
                return ent
            self._entries.move_to_end(key)
            ent.hits += 1
            self.interior_hits += 1
            return ent

    def put(self, key: str, entry: CacheEntry, max_bytes: int,
            max_entries: int = 0) -> bool:
        """Insert (or refresh) an entry, evicting least-recently-used
        entries past ``max_bytes`` — and past ``max_entries`` when > 0:
        the byte budget counts each entry's RESULT, but the pins tuple
        also keeps the query's INPUT matrices alive, so tiny results
        over huge ad-hoc inputs could otherwise retain unbounded device
        memory while staying "within budget"; the count bound caps
        that. Returns False when the entry alone exceeds the whole byte
        budget (never inserted — it would evict everything and then
        itself be the next eviction)."""
        if entry.nbytes > max_bytes:
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            # a fresh result supersedes any stale ghost of the key
            ghost = self._stale.pop(key, None)
            if ghost is not None:
                self._stale_bytes = max(
                    self._stale_bytes - ghost[0].nbytes, 0)
            self._entries[key] = entry
            self._bytes += entry.nbytes
            while self._entries and (
                    self._bytes > max_bytes
                    or (max_entries > 0
                        and len(self._entries) > max_entries)):
                k, dropped = self._entries.popitem(last=False)
                self._bytes -= dropped.nbytes
                self.evicted += 1
                # spill hierarchy: LRU pressure DEMOTES instead of
                # dropping — the value ages HBM → host (→ disk, the
                # manager's call) and a later consult thaws it back
                if self.spill is not None and k != key:
                    self.spill.demote(k, dropped)
            self._bytes = max(self._bytes, 0)
            return True

    def invalidate_deps(self, matrix_ids, keep_stale: bool = False,
                        stale_max: int = 0,
                        stale_max_bytes: int = 0) -> int:
        """Drop every entry whose dep set intersects ``matrix_ids``
        (id() values of LIVE matrices — see module docstring for why
        this comparison is safe). Returns the number dropped.

        ``keep_stale`` moves the invalidated entries into the stale
        graveyard (stamped with the invalidation clock) instead of
        discarding them — the brownout rung-2 substrate — bounded to
        the newest ``stale_max`` entries AND ``stale_max_bytes``
        device bytes (stale results stay device-pinned; the session
        passes the live cache's own byte budget, so ghosts can never
        retain more device memory than the cache itself is allowed).
        The default (False) is bit-identical to the historical drop."""
        ids = frozenset(matrix_ids)
        with self._lock:
            stale = [k for k, e in self._entries.items()
                     if e.dep_ids & ids]
            t = _now()
            for k in stale:
                ent = self._entries.pop(k)
                self._bytes -= ent.nbytes
                if keep_stale and stale_max > 0 \
                        and 0 < ent.nbytes <= stale_max_bytes:
                    old = self._stale.pop(k, None)
                    if old is not None:
                        self._stale_bytes -= old[0].nbytes
                    self._stale[k] = (ent, t)
                    self._stale_bytes += ent.nbytes
                    while self._stale and (
                            len(self._stale) > stale_max
                            or self._stale_bytes > stale_max_bytes):
                        _, (dropped, _t) = self._stale.popitem(
                            last=False)
                        self._stale_bytes -= dropped.nbytes
                    self._stale_bytes = max(self._stale_bytes, 0)
            dropped_n = len(stale)
            # the kill cascades into every tier: a host/disk copy of a
            # rebound-matrix result is exactly as wrong as an HBM one
            if self.spill is not None:
                dropped_n += self.spill.invalidate_deps(ids)
            self.invalidated += dropped_n
            self._bytes = max(self._bytes, 0)
            return dropped_n

    def lookup_stale(self, key: str, max_age_ms: float
                     ) -> Optional[CacheEntry]:
        """Brownout rung-2 consult: the STALE entry for ``key``, iff
        its age since invalidation fits the query's declared
        ``staleness_ms`` tolerance. Entries older than the asking
        query's tolerance stay (a later query may tolerate more);
        the graveyard stays bounded by the insert-side cap."""
        if max_age_ms is None or max_age_ms <= 0:
            return None
        with self._lock:
            got = self._stale.get(key)
            if got is None:
                return None
            ent, t_stale = got
            if (_now() - t_stale) * 1e3 > max_age_ms:
                return None
            self._stale.move_to_end(key)
            self.stale_hits += 1
            return ent

    # -- incremental view maintenance — the ONE sanctioned patch/apply
    # -- seam (docs/IVM.md; matlint ML012 pins entry mutation here) ----

    def items_snapshot(self):
        """(key, entry) pairs in LRU order — the delta plane's (and
        MV113's dynamic check's) read surface. A list copy: the plane
        mutates the cache through the seam while iterating."""
        with self._lock:
            return list(self._entries.items())

    def drop(self, key: str, keep_stale: bool = False,
             stale_max: int = 0, stale_max_bytes: int = 0) -> bool:
        """Invalidate ONE entry by key (the per-entry face of
        ``invalidate_deps`` — same counting, same brownout-graveyard
        semantics) — the delta plane's ineligible-entry fallback, so
        a kill here is indistinguishable from today's rebind kill."""
        with self._lock:
            if self.spill is not None and self.spill.discard(key):
                self.invalidated += 1
            ent = self._entries.pop(key, None)
            if ent is None:
                return False
            self._bytes = max(self._bytes - ent.nbytes, 0)
            self.invalidated += 1
            if keep_stale and stale_max > 0 \
                    and 0 < ent.nbytes <= stale_max_bytes:
                old = self._stale.pop(key, None)
                if old is not None:
                    self._stale_bytes -= old[0].nbytes
                self._stale[key] = (ent, _now())
                self._stale_bytes += ent.nbytes
                while self._stale and (
                        len(self._stale) > stale_max
                        or self._stale_bytes > stale_max_bytes):
                    _, (dropped, _t) = self._stale.popitem(last=False)
                    self._stale_bytes -= dropped.nbytes
                self._stale_bytes = max(self._stale_bytes, 0)
            return True

    def rekey(self, old_key: str, new_key: str) -> bool:
        """Rename a LIVE entry across a delta generation (payload
        untouched; key_hash re-derived so obs/MV107 stamps keep naming
        the key that actually maps to the entry). LRU position is
        preserved by insertion order of the rename pass."""
        with self._lock:
            ent = self._entries.pop(old_key, None)
            if ent is None:
                return False
            self._entries[new_key] = dataclasses.replace(
                ent, key_hash=hashlib.sha1(
                    new_key.encode()).hexdigest()[:16])
            self.rekeyed += 1
            return True

    def apply_patch(self, old_key: str, new_key: str,
                    entry: CacheEntry, max_bytes: int,
                    max_entries: int = 0) -> bool:
        """Replace a cached entry with its delta-PATCHED successor
        under the new generation's key — the in-place maintenance the
        transitive kill used to be. The old slot is removed without
        counting an invalidation (nothing was lost — the value was
        maintained); insertion goes through :meth:`put`, so byte/entry
        budgets and LRU eviction apply to patched entries exactly as
        to fresh ones. Returns False when the patched result no longer
        fits the budget — the OLD entry is then restored untouched, so
        the caller's fallback kill routes it through :meth:`drop` with
        the normal invalidation accounting and brownout-graveyard
        semantics (silently vanishing would undercount ``invalidated``
        and starve rung-2 stale serving of an entry it was owed)."""
        with self._lock:
            old = self._entries.pop(old_key, None)
            if old is not None:
                self._bytes = max(self._bytes - old.nbytes, 0)
            ok = self.put(new_key, entry, max_bytes, max_entries)
            if ok:
                self.patched += 1
            elif old is not None:
                self._entries[old_key] = old
                self._bytes += old.nbytes
            return ok

    def rebuild_stale(self, rename, dep_ids: FrozenSet[int]) -> None:
        """Carry the brownout graveyard across a delta generation:
        ghosts depending on the rebound matrix drop (their values are
        two bindings stale), the rest rename via ``rename(key) ->
        new_key`` so a later brownout can still serve them under the
        new generation's key format."""
        ids = frozenset(dep_ids)
        with self._lock:
            fresh: "OrderedDict[str, tuple]" = OrderedDict()
            for k, (ent, t) in self._stale.items():
                if ent.dep_ids & ids:
                    self._stale_bytes -= ent.nbytes
                    continue
                fresh[rename(k)] = (ent, t)
            self._stale = fresh
            self._stale_bytes = max(self._stale_bytes, 0)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._stale.clear()
            self._bytes = 0
            self._stale_bytes = 0
            if self.spill is not None:
                self.spill.clear()

    def info(self) -> dict:
        """``plan_cache_info``-style observability snapshot. The
        ``spill`` sub-dict appears only when a hierarchy is attached —
        the default dict keeps its historical shape."""
        with self._lock:
            out = {"entries": len(self._entries),
                   "bytes": self._bytes,
                   "hits": self.hits,
                   "misses": self.misses,
                   "interior_hits": self.interior_hits,
                   "evicted": self.evicted,
                   "invalidated": self.invalidated,
                   "stale_entries": len(self._stale),
                   "stale_bytes": self._stale_bytes,
                   "stale_hits": self.stale_hits,
                   "patched": self.patched,
                   "rekeyed": self.rekeyed}
            if self.spill is not None:
                out["spill"] = self.spill.info()
            return out
