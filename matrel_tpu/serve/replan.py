"""Drift-triggered re-planning of live cached plans (docs/COST_MODEL.md).

The closing arc of the cost-model loop: the drift auditor calibrates
coefficients from query events (obs/drift.py), the planner ranks by
them (parallel/coeffs.py + choose_strategy_ex), and THIS controller
makes a firing DRIFT rank-order flag fix the plans it indicts instead
of waiting for a human to read ``history --drift``.

Mechanism, per ``config.coeff_replan_interval`` observed queries:

1. ``rank_flags`` over a bounded window of live samples — the same
   flag logic, same ``RANK_FLAG_MARGIN``, as the offline audit.
2. A firing flag on a non-cooling population re-CALIBRATES: the
   window's samples for the flagged (class, backend) populations merge
   into the drift table (``drift.update_table`` — count-weighted, so
   poisoned priors wash out round by round instead of whiplashing).
3. The table rewrite bumps the coefficient EPOCH
   (``parallel/coeffs.epoch``), which the session embeds in every plan
   key as the ``coeffv:<epoch>|`` prefix — so every affected cached
   plan/MultiPlan is invalidated LAZILY: old entries keep serving
   in-flight queries, new lookups miss and recompile under the
   corrected coefficients. In-flight queries never block.
4. A background daemon thread re-WARMS the affected plans proactively
   (``session._replan_warm`` recompiles cached entries whose decisions
   touch the flagged shape classes, from their pinned root exprs) —
   an optimization over the lazy miss, never a correctness surface.
5. One ``replan`` obs event records the round: flags, classes, old →
   new epoch, plans re-warmed.

Hysteresis (the brownout enter/exit + dwell discipline — the "provably
never oscillates" contract the soak battery checks):

- An actioned population enters a COOLDOWN of
  ``coeff_replan_cooldown`` checks, and its window samples are
  dropped: the loop can never re-fire on the stale evidence it just
  acted on — only on fresh samples measured under the NEW plans.
- A flag that exactly REVERSES this controller's own last action on a
  population (model now prefers what measurement preferred then, and
  vice versa) must fire on two consecutive checks before it actions —
  a single noisy window cannot ping-pong a population.

Default-off contract: ``from_config`` returns None unless
``config.coeff_replan_enable`` — zero controller objects, zero
threads, zero new event kinds (``_CONSTRUCTED`` stays 0, the
mqo/lockdep poisoned-init pattern).
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Optional

from matrel_tpu.utils import lockdep

log = logging.getLogger("matrel_tpu.serve")

#: Construction counter — the structural-zero proof hook (the
#: serve/mqo.py pattern): tests assert it stays 0 for default configs.
_CONSTRUCTED = {"count": 0}

#: Bounded sample window (the metrics reservoir discipline): enough
#: for several check intervals of multi-strategy traffic, never
#: unbounded.
REPLAN_WINDOW = 512


def from_config(config, session=None) -> Optional["ReplanController"]:
    """None unless ``coeff_replan_enable`` — the structural-zero
    constructor gate (brownout/breaker/mqo precedent)."""
    if not getattr(config, "coeff_replan_enable", False):
        return None
    return ReplanController(config, session)


class ReplanController:
    """Watches the query event stream and closes the drift loop."""

    def __init__(self, config, session=None):
        _CONSTRUCTED["count"] += 1
        self._config = config
        self._session = session
        self._lock = lockdep.make_lock("serve.replan")
        self._samples: deque = deque(maxlen=REPLAN_WINDOW)
        self._since_check = 0
        # population (class, backend) -> remaining cooldown checks
        self._cooldown: dict = {}
        # population -> (model_prefers, measured_prefers) of the last
        # action — the reversal-detection memory
        self._last_action: dict = {}
        # population -> True when a reversal flag awaits confirmation
        self._pending: dict = {}
        self._worker: Optional[threading.Thread] = None
        self.checks = 0
        self.replans = 0
        #: Round records (the ``replan`` event payloads), newest last —
        #: the in-memory mirror unit tests and ``info()`` read.
        self.events: list = []

    # -- the observe/check loop -----------------------------------------

    def observe(self, query_record: dict) -> None:
        """Feed one query event record (session._emit_query_event calls
        this after emission). Never raises — the loop must never fail
        the query that fed it."""
        try:
            from matrel_tpu.obs import drift
            rec = dict(query_record)
            rec.setdefault("kind", "query")
            with self._lock:
                for s in drift.iter_samples([rec]):
                    self._samples.append(s)
                self._since_check += 1
                due = (self._since_check
                       >= self._config.coeff_replan_interval)
                if due:
                    self._since_check = 0
            if due:
                self.check()
        except Exception:
            log.warning("replan: observe failed", exc_info=True)

    def check(self) -> Optional[dict]:
        """One drift check: fire flags, re-calibrate, bump the epoch,
        kick the background warm. Returns the round record when a
        re-plan actioned, else None."""
        from matrel_tpu.obs import drift
        from matrel_tpu.parallel import coeffs
        self.checks += 1
        with self._lock:
            samples = list(self._samples)
            for key in [k for k, v in self._cooldown.items() if v > 0]:
                self._cooldown[key] -= 1
        flags = drift.rank_flags(samples)
        fire = []
        pending_next: dict = {}
        for fl in flags:
            key = (fl["class"], fl["backend"])
            if self._cooldown.get(key, 0) > 0:
                continue          # hysteresis: fresh samples first
            last = self._last_action.get(key)
            if (last is not None
                    and (fl["model_prefers"], fl["measured_prefers"])
                    == (last[1], last[0])):
                # exact reversal of our own last action: demand it on
                # two consecutive checks (the brownout dwell) before
                # acting — one noisy window cannot ping-pong a
                # population
                if not self._pending.get(key):
                    pending_next[key] = True
                    continue
            if not any(k == key for k, _ in fire):
                fire.append((key, fl))
        self._pending = pending_next
        if not fire:
            return None
        keys = {k for k, _ in fire}
        calib = drift.calibrate(
            [s for s in samples
             if (s["class"], s["backend"]) in keys])
        path = drift.table_path(self._config)
        old_epoch = coeffs.epoch(path)
        try:
            drift.update_table(path, calib)
        except OSError:
            log.warning("replan: calibration table not persisted",
                        exc_info=True)
            return None
        new_epoch = coeffs.epoch(path)
        with self._lock:
            cooldown = self._config.coeff_replan_cooldown
            for key, fl in fire:
                self._cooldown[key] = cooldown
                self._last_action[key] = (fl["model_prefers"],
                                          fl["measured_prefers"])
            # drop the actioned populations' samples: the next check
            # must see evidence measured under the NEW plans only
            kept = [s for s in self._samples
                    if (s["class"], s["backend"]) not in keys]
            self._samples = deque(kept, maxlen=REPLAN_WINDOW)
        self.replans += 1
        classes = sorted({fl["class"] for _, fl in fire})
        record = {
            "round": self.replans,
            "classes": classes,
            "old_epoch": old_epoch,
            "epoch": new_epoch,
            "flags": [{"class": fl["class"], "backend": fl["backend"],
                       "model_prefers": fl["model_prefers"],
                       "measured_prefers": fl["measured_prefers"],
                       "slowdown": fl["slowdown"]}
                      for _, fl in fire],
        }
        self.events.append(record)
        self._spawn_warm(set(classes), record)
        return record

    # -- background warm --------------------------------------------------

    def _spawn_warm(self, classes: set, record: dict) -> None:
        """Re-warm affected cached plans on a daemon thread, then emit
        the round's ``replan`` event (with the warm census attached).
        One warm in flight at a time: a still-running warm means the
        lazy ``coeffv:`` miss already covers correctness — skipping a
        proactive pass costs latency, never answers."""
        session = self._session
        if session is None:
            record["replanned"] = 0
            return
        if self._worker is not None and self._worker.is_alive():
            record["replanned"] = None    # warm skipped, lazy covers
            session._obs_emit("replan", record)
            return

        def warm():
            try:
                census = session._replan_warm(classes)
                record.update(census)
            except Exception:
                log.warning("replan: background warm failed",
                            exc_info=True)
            try:
                session._obs_emit("replan", record)
            except Exception:
                log.warning("replan: event dropped", exc_info=True)

        t = threading.Thread(target=warm, name="matrel-replan",
                             daemon=True)
        self._worker = t
        t.start()

    def drain(self, timeout: float = 30.0) -> None:
        """Join any in-flight background warm (test/soak hook)."""
        t = self._worker
        if t is not None and t.is_alive():
            t.join(timeout)

    def info(self) -> dict:
        """``plan_cache_info``-style surface."""
        with self._lock:
            return {"checks": self.checks, "replans": self.replans,
                    "window": len(self._samples),
                    "cooling": sum(1 for v in self._cooldown.values()
                                   if v > 0)}
