"""Admission-time multi-query optimization (docs/SERVING.md).

The serve plane dedups whole-query ROOTS (``run_many``'s structural
uniq) and catches interior reuse only AFTER a prior query materialized
it in the result cache — a coalesced batch of near-identical dashboard
queries still computed its shared interior subplans k times on first
contact and paid compile per structural variant. This module is the
MatFast persist/amortization thesis (PAPER.md [P2]) applied ACROSS the
concurrent batch instead of across time, plus the
compile-for-the-observed-workload argument (arXiv:2312.05639) lifted
to the query stream. Two mechanisms, both driven by the session's ONE
structural-key walk (``session._plan_key_spans`` — span-slice joins,
never subtree re-walks):

**Cross-query CSE** (:func:`choose_hoists` / :func:`substitute`): the
interior subtrees shared by >= ``config.cse_min_uses`` occurrences
across a batch are hoisted into a compute-once MultiPlan of their own;
every consumer query re-enters planning with the result substituted as
an already-laid-out leaf carrying a ``cse`` stamp — the result-cache
interior-hit shape, so ``infer_layout``/``comm_cost`` credit the reuse
and ``matmul_decisions`` marks the hoist-fed operands
(``cse_operands``). Hoists happen only at fused-region BOUNDARIES
(kinds outside ``ir/fusion.FUSABLE_KINDS``, i.e. anchors whose output
already crosses a region edge), so per-consumer epilogue chains keep
fusing into their own regions instead of being split by the share.

**Plan-template reuse** (:class:`MqoState` + :func:`template_key`):
queries structurally identical modulo dense-leaf bindings key one
TEMPLATE on the leaf-abstracted structural key — dense leaves emit a
session-independent token carrying exactly the host metadata planning
consults (shape, PartitionSpec, dtype, density, integrality bounds),
so rebinding a new matrix with the same token into the compiled
program is planning-equivalent by construction; sparse/COO leaves keep
their identity tokens (their payloads are baked into the compiled
program as constants — not rebindable). Steady-state dashboard traffic
rebinds leaves into the cached plan via ``plan.run(bindings=...)`` —
the IVM ``ivm_role`` rebinding seam (serve/ivm.py) generalized to
serve traffic — and pays ZERO optimize/trace. The session composes the
``degr:``/``axisw:``/``prec:`` key prefixes onto every template key,
so degrade/topology/SLA isolation is inherited, not re-implemented.

Verification: MV116 (analysis/cse_pass.py) statically checks every
``cse`` stamp against the leaf it rides and dynamically re-executes
recent hoist-substituted batches UNSHARED (the MV113 patched-entry
idiom) — :attr:`MqoState.recent` is the bounded ring it replays.

Zero-overhead contract: ``cse_enable = False`` (the default)
constructs NOTHING from this module — no state, no hoist, no template
(``_CONSTRUCTED`` is the poisoned-init test hook, the FusedRegion
discipline) — and every cache key keeps its historical format.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

#: Test hook (tests/test_cse.py): with ``cse_enable`` off NOTHING in
#: this module is ever constructed — the count stays exactly 0 over
#: the whole default-config suite (the ir/fusion._CONSTRUCTED idiom).
_CONSTRUCTED = {"count": 0}

#: Ring depth of :attr:`MqoState.recent` — what MV116's dynamic half
#: can re-prove without the state pinning unbounded device results.
RECENT_MAX = 8


def _fusable_kinds() -> tuple:
    from matrel_tpu.ir import fusion as fusion_lib
    return fusion_lib.FUSABLE_KINDS


@dataclasses.dataclass
class HoistPlan:
    """One shared interior chosen for compute-once execution: the
    canonical subtree (first occurrence — all occurrences are
    structurally identical by key), its standalone structural key
    (byte-identical to ``_plan_key`` of the subtree — the spans
    contract), and the uid of EVERY occurrence across the batch so
    substitution can replace each consumer site."""

    key: str
    expr: object                  # MatExpr — the canonical occurrence
    uses: int
    uids: Tuple[int, ...]

    def __post_init__(self):
        _CONSTRUCTED["count"] += 1


@dataclasses.dataclass
class TemplateEntry:
    """One compiled plan held rebindable: ``slots`` is, in PLAN-ROOT
    order, each root's (abstract key, dense-leaf uids in pre-order) —
    a probe pairs its own roots to slots by abstract key and binds new
    matrices onto the recorded uids. ``pins`` keeps every id()-keyed
    object of the abstract key alive (sparse payload matrices,
    fn-token globals) so the key can never falsely hit a recycled
    address — the plan-cache ``_cache_pin`` discipline."""

    plan: object
    slots: Tuple[Tuple[str, Tuple[int, ...]], ...]
    pins: Tuple

    def __post_init__(self):
        _CONSTRUCTED["count"] += 1


class MqoState:
    """Per-session multi-query-optimization state: the template cache
    (abstract key -> :class:`TemplateEntry`, LRU-bounded by
    ``config.cse_template_max``), the lifetime counters the serve
    events report as deltas, and the bounded ring of recent
    hoist-substituted executions MV116's dynamic half replays."""

    def __init__(self, config):
        _CONSTRUCTED["count"] += 1
        self.config = config
        self.templates: "OrderedDict[str, TemplateEntry]" = OrderedDict()
        self.cse_hoisted = 0          # lifetime hoisted interiors
        self.cse_batches = 0          # batches that hoisted anything
        self.template_hits = 0        # lifetime template-served queries
        self.template_inserts = 0
        #: (original root expr, substituted expr) of recent CSE-fed
        #: executions — MV116's dynamic-verify feed: executing both
        #: fresh and comparing proves substituted ≡ unshared.
        self.recent: deque = deque(maxlen=RECENT_MAX)
        #: abstract keys restored from a ``save_state()`` snapshot
        #: (serve/spill.py) — KEYS ONLY: a compiled plan holds device
        #: buffers and traced closures no snapshot can carry, so
        #: programs recompile lazily on first probe and the seeded set
        #: just tracks which pre-restart templates have come back
        #: (``templates_rewarmed``). Bookkeeping, never a plan source.
        self.seeded: set = set()
        self.templates_rewarmed = 0

    def info(self) -> dict:
        """``plan_cache_info``-style surface."""
        return {"templates": len(self.templates),
                "template_hits": self.template_hits,
                "template_inserts": self.template_inserts,
                "cse_hoisted": self.cse_hoisted,
                "cse_batches": self.cse_batches,
                "seeded_templates": len(self.seeded),
                "templates_rewarmed": self.templates_rewarmed}

    def remember(self, orig, substituted) -> None:
        self.recent.append((orig, substituted))

    def put_template(self, key: str, entry: TemplateEntry) -> None:
        # canonical structural key only (matlint ML016): the template
        # cache must never key off id()/uid/spec-repr shortcuts — a
        # recycled address or a re-created same-layout leaf would
        # alias two distinct plans
        self.templates[key] = entry
        self.templates.move_to_end(key)
        if key in self.seeded:
            self.seeded.discard(key)
            self.templates_rewarmed += 1
        while len(self.templates) > self.config.cse_template_max:
            self.templates.popitem(last=False)

    def get_template(self, key: str) -> Optional[TemplateEntry]:
        ent = self.templates.get(key)
        if ent is not None:
            self.templates.move_to_end(key)
        return ent

    def template_keys(self) -> list:
        """LRU-ordered abstract keys (coldest first) for
        ``save_state()`` — plus any still-unrewarmed seeded keys, so
        a restart-of-a-restart does not forget the original hot set."""
        out = sorted(self.seeded)
        out.extend(k for k in self.templates if k not in self.seeded)
        return out

    def seed_templates(self, keys) -> int:
        """Install a snapshot's template keys (``restore()``'s seam)
        — see ``seeded``. Bounded by ``cse_template_max``; non-string
        rows are skipped (a snapshot is never a correctness
        surface)."""
        installed = 0
        for k in keys:
            if len(self.seeded) >= self.config.cse_template_max:
                break
            if isinstance(k, str) and k not in self.templates:
                self.seeded.add(k)
                installed += 1
        return installed


# -- leaf-abstracted structural keys (plan templates) -------------------


def template_key(e) -> Tuple[str, list, list]:
    """(abstract key, pins, dense leaves in pre-order) for one root.

    Dense leaves emit a session-independent token carrying EXACTLY the
    host metadata the planner consults about a leaf — shape and
    PartitionSpec (``_layout_of``/``infer_layout``), dtype (HBM gates,
    autotune classes), density (``comm_cost``), integrality flag and
    bound (``infer_integral``/``integral_abs_bound`` — the precision
    tier chooser) — PLUS the leaf's identity CLASS (first-occurrence
    numbering of the matrix object within this root): the optimizer
    consults which leaves hold the SAME matrix (``t(X) @ X`` dedupes
    its two leaves into one Gram operand; ``t(X) @ Y`` cannot), so the
    equality pattern is part of what determines the compiled program
    and must be part of the key — ``#0/#0`` and ``#0/#1`` never share
    a template. With metadata and pattern both encoded, any tree with
    the same token sequence optimizes to the identical program modulo
    leaf bindings (the optimizer never reads leaf VALUES), and
    rebinding is as safe as re-running the plan. Sparse/COO leaves
    keep their identity tokens (payloads are trace constants in the
    compiled program — not rebindable) and are pinned. Interior tokens
    come byte-identical from the session's one structural-walk
    implementation. Raises ``KeyError`` when the tree is ineligible
    (the ``_plan_key_spans`` leaf-token contract)."""
    from matrel_tpu import session as session_mod

    pins: list = []
    leaves: list = []
    classes: dict = {}

    def tok(n):
        m = n.attrs.get("matrix")
        if n.kind == "leaf":
            leaves.append(n)
            cls = classes.setdefault(id(m), len(classes))
            return ("tleaf#{}:{}:{}:{}:{}:{}:{}".format(
                cls, m.shape, m.spec, np.dtype(m.dtype),
                getattr(m, "density", None),
                bool(getattr(m, "integral", False)),
                getattr(m, "int_abs_max", None)))
        # sparse payloads are compiled-in constants — identity-keyed
        # and pinned, exactly like the concrete key
        pins.append(m)
        return f"{n.kind}:{id(m)}:{m.shape}"

    parts, wpins, _spans = session_mod._plan_key_spans(e, leaf_token=tok)
    return "|".join(parts), pins + wpins, leaves


def rebindable(entry: TemplateEntry) -> bool:
    """A template is rebindable iff every DENSE leaf of its compiled
    program is a leaf its abstract key recorded — a program leaf the
    key never saw (an optimizer rewrite that re-created the node with
    a fresh uid) would silently keep its compiled-in matrix on a
    rebind: stale data, the one failure mode this guard exists for.
    Recorded leaves the program DROPPED (``t(X) @ X`` dedup, algebraic
    elimination) are fine: their bindings are simply ignored, and the
    identity classes in the abstract key guarantee the new batch's
    leaves dedupe the same way."""
    plan = entry.plan
    plan_uids = {l.uid for l in plan.leaf_order if l.kind == "leaf"}
    recorded = {u for _k, uids in entry.slots for u in uids}
    return plan_uids <= recorded


# -- cross-query CSE ----------------------------------------------------


def choose_hoists(entries, min_uses: int = 2) -> List[HoistPlan]:
    """Pick the shared interiors of one batch, top-down maximal.

    ``entries`` is ``[(root expr, parts, spans), ...]`` — each root's
    single ``_plan_key_spans`` walk. A node is a hoist CANDIDATE when
    it is a proper interior (not a leaf, not its query's root — whole-
    root sharing is the MultiPlan uniq's job), its kind lies outside
    ``FUSABLE_KINDS`` (the hoist boundary must coincide with a fused-
    region edge so epilogue fusion composes instead of splitting), and
    its subtree carries at least one matmul (a shared transpose-of-a-
    leaf is not worth a dispatch). Candidates group by their standalone
    span key; groups with >= ``min_uses`` occurrences hoist. Marking
    is top-down: inside a hoisted subtree nothing is re-considered —
    the interior computes once either way."""
    fusable = _fusable_kinds()
    counts: Dict[str, int] = {}
    canon: Dict[str, object] = {}

    def candidate(n, is_root: bool) -> bool:
        return (not is_root and bool(n.children)
                and n.kind not in fusable and _has_matmul(n))

    for e, parts, spans in entries:
        for n, is_root in _walk_interiors(e):
            if not candidate(n, is_root):
                continue
            s, t = spans[n.uid]
            k = "|".join(parts[s:t])
            counts[k] = counts.get(k, 0) + 1
            canon.setdefault(k, n)

    shared = {k for k, c in counts.items() if c >= min_uses}
    if not shared:
        return []
    hoists: Dict[str, List[int]] = {}

    def mark(n, parts, spans, is_root: bool):
        if n.uid in spans and not is_root and bool(n.children):
            s, t = spans[n.uid]
            k = "|".join(parts[s:t])
            if k in shared and n.kind not in fusable \
                    and _has_matmul(n):
                hoists.setdefault(k, []).append(n.uid)
                return                      # top-down maximal
        for c in n.children:
            mark(c, parts, spans, False)

    for e, parts, spans in entries:
        mark(e, parts, spans, True)
    return [HoistPlan(key=k, expr=canon[k], uses=len(uids),
                      uids=tuple(uids))
            for k, uids in sorted(hoists.items())]


def _walk_interiors(e):
    """Yield (node, is_root) for every interior node, pre-order."""
    out = []

    def walk(n, is_root):
        if not n.children:
            return
        out.append((n, is_root))
        for c in n.children:
            walk(c, False)

    walk(e, True)
    return out


def _has_matmul(n) -> bool:
    if n.kind == "matmul":
        return True
    return any(_has_matmul(c) for c in n.children)


def substitute(e, leaf_of: Dict[int, object]):
    """Rebuild ``e`` with every uid in ``leaf_of`` replaced by its
    compute-once leaf — the ``_rc_substitute`` shape, but keyed on the
    exact occurrence uids ``choose_hoists`` marked (no re-probing)."""
    hit = leaf_of.get(e.uid)
    if hit is not None:
        return hit
    if not e.children:
        return e
    new_children = tuple(substitute(c, leaf_of) for c in e.children)
    if all(nc is c for nc, c in zip(new_children, e.children)):
        return e
    return e.with_children(new_children)
