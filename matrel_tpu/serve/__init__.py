"""Serving-oriented throughput layer (matrel_tpu/serve/).

The reference gets its headline wins from in-memory reuse of
distributed intermediates — the Spark ``persist``/RDD-cache discipline
MatFast (ICDE 2017) is built on. This package is the TPU rebuild's
serving analogue, three coordinated pieces the session wires together:

  result_cache  cross-query materialized-result cache: executed query
                results kept on device, keyed by the CANONICAL
                STRUCTURAL plan key (session._plan_key — never id()-
                keyed), byte-budgeted LRU, catalog-rebind invalidation
                (``config.result_cache_max_bytes``; 0 = off,
                bit-identical to the uncached behaviour).
  pipeline      micro-batched admission + async execution:
                ``session.submit`` returns a future; an admission loop
                coalesces concurrent queries into one MultiPlan and
                overlaps host planning of batch N+1 with device
                execution of batch N, bounded by
                ``config.serve_max_inflight``.
  admission     per-tenant weighted-fair admission queue (round 13,
                docs/OVERLOAD.md): stride-scheduled tenant queues,
                quota sheds typed BEFORE the global bound, deadline-
                expired entries purged at the shed decision points.
                With no tenant weights configured it is bit-identical
                to the historical FIFO.
  fleet         multi-slice serving fleet (round 16, docs/FLEET.md):
                ``config.fleet_slices`` partitions the mesh into
                serving slices — per-slice queues/workers/brownout/
                result caches, a global structural-key directory
                (hit anywhere avoids recompute), reshard-priced
                hot-entry replication, typed cross-slice failover.
  placement     the fleet's routing policy: slice-local vs full-mesh
                span by the topology byte model, drift-calibrated
                per-(class, backend, tier) cost coefficients ahead
                of the analytic closed forms.

``session.run_many`` is the synchronous batch surface (one MultiPlan,
session-plan-cached); ``session.submit`` the asynchronous one. See
docs/SERVING.md for cache semantics, invalidation rules and the QPS
methodology, and docs/OVERLOAD.md for the overload control plane
(tenants, brownout, circuit breakers, the traffic harness).
"""

from matrel_tpu.serve.admission import AdmissionQueue  # noqa: F401
from matrel_tpu.serve.result_cache import CacheEntry, ResultCache  # noqa: F401
