"""Result-cache spill hierarchy + durable-state snapshots
(docs/DURABILITY.md; ROADMAP item 1).

Two jobs, one seam:

* **Tiering.** :class:`SpillManager` is the lower half of the
  result cache's memory hierarchy: entries the HBM LRU evicts DEMOTE
  here (host RAM as host-resident numpy, then the checkpoint layer's
  sha1-verified ``.npy`` artifacts as the disk tier) instead of being
  recomputed later, and an HBM miss falls through
  (``ResultCache._thaw``) to PROMOTE them back — a lower-tier hit
  recomputes nothing; it pays only the priced transfer legs
  (``parallel/reshard.spill_plan`` stages the move in the ``host``/
  ``disk`` step vocabulary, ``parallel/coeffs.spill_cost_ms`` prices
  it from the drift-calibrated ``spill:<leg>`` rows). The demotion
  policy is LRU pressure + expected reuse: everything evicted ages to
  host RAM; host entries past ``config.spill_host_max_bytes`` age to
  disk only when their lifetime ``hits`` clear
  ``config.spill_disk_hits`` (cold entries drop — writing a
  never-reused result to disk buys nothing).

* **Durability.** :func:`save_state` / :func:`load_snapshot` persist
  the fleet's learned state — catalog bindings (the checkpoint step
  format), the result-cache index (every entry with a catalog-NAME
  computable key, written as disk-tier artifacts), the fleet
  directory, MQO template keys, and the autotune/drift tables — so a
  restarted ``MatrelSession.restore()`` comes back serving warm:
  restored entries sit in a name-keyed index (``fleet_key``'s
  session-independent token format — raw structural keys embed
  ``id()``s and mean nothing across processes) and thaw lazily on
  first consult, with dep NAMES re-resolved against the live catalog
  so invalidation keeps working.

Corruption discipline: a disk artifact failing its stored sha1 raises
the typed :class:`SnapshotCorruption` INTERNALLY and is handled as a
cache miss (drop + count + warn — the query recomputes; the answer is
never wrong); a corrupt/truncated snapshot warns and cold-starts
(PR 8's corrupt-table discipline — restore never crashes a restart).

This module is also matlint ML019's sanctioned seam: file IO under
``matrel_tpu/serve/`` lives HERE (delegating to utils/checkpoint
primitives), nowhere else.

Structural-zero contract: the default config (``spill_enable=False``)
constructs NO SpillManager — ``_CONSTRUCTED`` stays 0, poisoned-init
test-enforced, plan snapshots bit-identical.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional

import numpy as np

from matrel_tpu.resilience.errors import SnapshotCorruption
from matrel_tpu.utils import lockdep

_log = logging.getLogger("matrel_tpu.serve")

#: Structural-zero hook (the mqo/replan idiom): tests poison
#: SpillManager.__init__ bookkeeping by asserting this counter stays 0
#: under the default config.
_CONSTRUCTED = {"count": 0}

#: Snapshot state-dict schema (bump on reader-visible change — the
#: events.py versioning discipline; foreign schemas cold-start).
SNAPSHOT_SCHEMA = 1


def _now_ms() -> float:
    return time.perf_counter() * 1e3  # matlint: disable=ML006 spill-leg transfer samples ARE the drift loop's measurement — they land in the spill event log, exactly the ML006 destination


@dataclasses.dataclass
class TierEntry:
    """One lower-tier resident. ``meta`` is the JSON-able record the
    snapshot persists (shape/spec/dtype/layout/prec/delta provenance/
    dep_names); the object-valued fields (expr, pins, dep_ids, …)
    exist only for SAME-PROCESS demotions — a restored entry has
    ``dep_names`` in meta instead and re-resolves them at thaw."""

    tier: str                        # "host" / "disk" / "restored"
    meta: dict
    nbytes: int
    hits: int = 0
    array: Optional[np.ndarray] = None   # host tier only
    file: Optional[str] = None           # disk/restored tiers
    sha1: Optional[str] = None
    dep_ids: frozenset = frozenset()
    pins: tuple = ()
    expr: Optional[object] = None
    fleet: Optional[dict] = None
    provenance: Optional[dict] = None
    ivm_id: Optional[int] = None


def _entry_meta(ent) -> dict:
    """CacheEntry + its BlockMatrix → the JSON-able tier metadata."""
    from matrel_tpu.utils.checkpoint import _spec_to_json
    bm = ent.result
    return {
        "key_hash": ent.key_hash,
        "shape": list(bm.shape),
        "spec": _spec_to_json(bm.spec),
        "nnz": bm.nnz,
        "block_size": bm.block_size,
        "integral": bm.integral,
        "int_abs_max": bm.int_abs_max,
        "layout": ent.layout,
        "dtype": ent.dtype,
        "nbytes": ent.nbytes,
        "prec": ent.prec,
        "err_bound": ent.err_bound,
        "delta_gen": ent.delta_gen,
        "delta_rule": ent.delta_rule,
    }


class SpillManager:
    """The host/disk tiers under one session's ResultCache, plus the
    restored-entry index a snapshot load seeds. Lock order:
    ``serve.result_cache`` → ``serve.spill`` (demotions run inside the
    cache's eviction loop; promotions inside its miss path) — this
    manager never calls back into the cache."""

    def __init__(self, session):
        _CONSTRUCTED["count"] += 1
        self._session = session
        self.config = session.config
        self.mesh = session.mesh
        self._lock = lockdep.make_rlock("serve.spill")
        self._host: "OrderedDict[str, TierEntry]" = OrderedDict()
        self._host_bytes = 0
        self._disk: Dict[str, TierEntry] = {}
        self._disk_bytes = 0
        # name-keyed (fleet_key format) entries from a loaded
        # snapshot, thawed lazily by the session's restored consult
        self._restored: Dict[str, TierEntry] = {}
        self._dir = (os.path.join(self.config.state_dir, "spill")
                     if self.config.state_dir else None)
        # wired by the session to _emit_spill_event; never required
        self.emit: Optional[Callable[[dict], None]] = None
        self.demoted_host = 0
        self.demoted_disk = 0
        self.promoted = 0
        self.thawed_restored = 0
        self.dropped = 0          # cold host entries aged past budget
        self.corrupt = 0          # artifacts that failed their sha1

    # -- ResultCache-facing contract (attach_spill consumers) ---------------

    @property
    def hbm_max_bytes(self) -> int:
        return self.config.result_cache_max_bytes

    @property
    def hbm_max_entries(self) -> int:
        return self.config.result_cache_max_entries

    def demote(self, key: str, ent) -> None:
        """Age one HBM-evicted entry into the host tier (d2h — the
        ``spill_plan("hbm", "host")`` leg), then age host entries past
        ``spill_host_max_bytes`` to disk or drop them by the
        expected-reuse gate. Never raises into the eviction loop: a
        failed demotion degrades to exactly the historical drop."""
        try:
            self._demote(key, ent)
        except Exception:
            self.dropped += 1
            _log.warning("spill: demotion of %s failed; entry dropped "
                         "(the historical eviction)", ent.key_hash,
                         exc_info=True)

    def _demote(self, key: str, ent) -> None:
        t0 = _now_ms()
        arr = np.asarray(ent.result.data)     # the d2h leg
        d2h_ms = _now_ms() - t0
        te = TierEntry(
            tier="host", meta=_entry_meta(ent), nbytes=ent.nbytes,
            hits=ent.hits, array=arr, dep_ids=ent.dep_ids,
            pins=ent.pins, expr=ent.expr, fleet=ent.fleet,
            provenance=ent.provenance, ivm_id=ent.ivm_id)
        legs = [{"leg": "d2h", "bytes": float(ent.nbytes),
                 "ms": round(d2h_ms, 4)}]
        with self._lock:
            old = self._host.pop(key, None)
            if old is not None:
                self._host_bytes -= old.nbytes
            self._host[key] = te
            self._host_bytes += te.nbytes
            self.demoted_host += 1
            aged = self._age_host(legs)
        self._emit("demote", te.meta, "host", legs,
                   aged_to_disk=aged)

    def _age_host(self, legs: list) -> int:
        """Host-tier pressure (caller holds the lock): LRU entries
        past the host byte budget age to disk when a disk tier exists
        AND their lifetime hits clear the expected-reuse gate;
        otherwise they drop — the value was never re-used, so pushing
        it down a slower tier buys nothing."""
        aged = 0
        while (self._host
               and self._host_bytes > self.config.spill_host_max_bytes):
            k, te = self._host.popitem(last=False)
            self._host_bytes -= te.nbytes
            if (self._dir is not None
                    and te.hits >= self.config.spill_disk_hits):
                t0 = _now_ms()
                file, sha1 = self._write_artifact(
                    te.meta["key_hash"], te.array)
                ms = _now_ms() - t0
                legs.append({"leg": "disk_write",
                             "bytes": float(te.nbytes),
                             "ms": round(ms, 4)})
                self._disk[k] = dataclasses.replace(
                    te, tier="disk", array=None, file=file, sha1=sha1)
                self._disk_bytes += te.nbytes
                self.demoted_disk += 1
                aged += 1
            else:
                self.dropped += 1
        self._host_bytes = max(self._host_bytes, 0)
        return aged

    def promote(self, key: str):
        """Thaw one lower-tier entry back into a device-resident
        CacheEntry (the ``ResultCache._thaw`` consult), or None. The
        entry leaves its tier — the cache re-inserts it at HBM. A
        disk artifact failing its sha1 is a MISS (dropped + counted +
        warned), never a wrong answer, never an exception out."""
        with self._lock:
            te = self._host.pop(key, None)
            if te is not None:
                self._host_bytes = max(self._host_bytes - te.nbytes, 0)
                return self._thaw(key, te, src_tier="host")
            te = self._disk.pop(key, None)
            if te is not None:
                self._disk_bytes = max(self._disk_bytes - te.nbytes, 0)
                return self._thaw(key, te, src_tier="disk")
        return None

    def _thaw(self, key: str, te: TierEntry, src_tier: str):
        """TierEntry → CacheEntry: read (disk) + h2d, stamped with the
        priced legs so MV117 can re-check the move against the plan
        vocabulary."""
        from matrel_tpu.serve.result_cache import CacheEntry
        legs = []
        arr = te.array
        if arr is None:
            try:
                t0 = _now_ms()
                arr = self._read_artifact(te)
                legs.append({"leg": "disk_read",
                             "bytes": float(te.nbytes),
                             "ms": round(_now_ms() - t0, 4)})
            except SnapshotCorruption as e:
                self.corrupt += 1
                _log.warning("spill: %s — treating as a cache miss "
                             "(the query recomputes)", e)
                return None
        t0 = _now_ms()
        bm = self._to_device(arr, te.meta)
        legs.append({"leg": "h2d", "bytes": float(te.nbytes),
                     "ms": round(_now_ms() - t0, 4)})
        stamp = self._price_stamp(src_tier, te, legs)
        ent = CacheEntry(
            key_hash=te.meta["key_hash"], result=bm, pins=te.pins,
            dep_ids=te.dep_ids, layout=te.meta["layout"],
            dtype=te.meta["dtype"], nbytes=te.nbytes, expr=te.expr,
            prec=te.meta.get("prec", ""),
            err_bound=te.meta.get("err_bound", 0.0),
            delta_gen=te.meta.get("delta_gen", 0),
            delta_rule=te.meta.get("delta_rule"),
            ivm_id=te.ivm_id, fleet=te.fleet,
            provenance=te.provenance, hits=te.hits, spill=stamp)
        self.promoted += 1
        self._emit("promote", te.meta, src_tier, legs,
                   est_ms=stamp["est_ms"], cost=stamp["cost"])
        return ent

    def _price_stamp(self, src_tier: str, te: TierEntry,
                     legs: list) -> dict:
        """The ``entry.spill`` provenance stamp: the staged plan's leg
        tokens (reshard vocabulary), its coefficient-priced bill, and
        whether the device transient fit the peak-HBM budget — what
        MV117 re-checks."""
        from matrel_tpu.obs import drift
        from matrel_tpu.parallel import coeffs, reshard
        # restored entries ARE disk-tier entries (the snapshot's index
        # just keys them by name); the plan prices the same legs
        plan = reshard.spill_plan(
            "disk" if src_tier == "restored" else src_tier,
            "hbm", te.nbytes)
        leg_names = [reshard.spill_leg(s) for s in plan.steps]
        est_ms, cost = coeffs.spill_cost_ms(
            leg_names, te.nbytes, drift.shape_class(te.meta["shape"]),
            self._backend(), drift.table_path(self.config))
        return {"tier": src_tier, "legs": leg_names,
                "est_ms": round(est_ms, 4), "cost": cost,
                "fits": plan.fits(
                    float(self.config.reshard_peak_budget_bytes)),
                "measured": legs}

    # -- restored-entry index (the warm-restart face) -----------------------

    def seed_restored(self, entries: Dict[str, TierEntry]) -> int:
        """Install a loaded snapshot's name-keyed disk-tier index
        (load_snapshot's seam). Returns the count installed."""
        with self._lock:
            self._restored.update(entries)
            return len(entries)

    def restored_count(self) -> int:
        with self._lock:
            return len(self._restored)

    def thaw_restored(self, name_key: str, prec: str, resolve):
        """Thaw one RESTORED entry by its session-independent name key
        iff its precision tier matches the asking query's and every
        dep NAME still resolves in the live catalog (``resolve: name
        -> matrix-or-None``). The thawed entry's dep ids/pins rebind
        to the LIVE catalog objects, so rebind invalidation works on
        it exactly like a locally-computed entry. None on any failure
        — a restored entry never answers a query it cannot prove it
        belongs to."""
        with self._lock:
            te = self._restored.get(name_key)
            if te is None or te.meta.get("prec", "") != prec:
                return None
            deps = []
            for nm in te.meta.get("dep_names") or ():
                m = resolve(nm)
                if m is None:
                    # the name is gone/unbound: the entry can never be
                    # proven current — drop it for good
                    self._restored.pop(name_key, None)
                    self.dropped += 1
                    return None
                deps.append(m)
            te = self._restored.pop(name_key)
            te = dataclasses.replace(
                te, dep_ids=frozenset(id(m) for m in deps),
                pins=tuple(deps))
            ent = self._thaw(name_key, te, src_tier="restored")
            if ent is not None:
                self.thawed_restored += 1
            return ent

    # -- invalidation cascades ---------------------------------------------

    def invalidate_deps(self, matrix_ids) -> int:
        """The rebind kill, cascaded: drop every host/disk entry whose
        dep ids intersect (ResultCache.invalidate_deps calls here)."""
        ids = frozenset(matrix_ids)
        n = 0
        with self._lock:
            for k in [k for k, te in self._host.items()
                      if te.dep_ids & ids]:
                te = self._host.pop(k)
                self._host_bytes = max(self._host_bytes - te.nbytes, 0)
                n += 1
            for k in [k for k, te in self._disk.items()
                      if te.dep_ids & ids]:
                te = self._disk.pop(k)
                self._disk_bytes = max(self._disk_bytes - te.nbytes, 0)
                self._remove_artifact(te)
                n += 1
        return n

    def invalidate_names(self, names) -> int:
        """The rebind kill for RESTORED entries, which carry dep NAMES
        instead of ids (session.register routes rebinds here when a
        restored index exists)."""
        names = frozenset(names)
        n = 0
        with self._lock:
            for k in [k for k, te in self._restored.items()
                      if names & frozenset(te.meta.get("dep_names")
                                           or ())]:
                self._restored.pop(k)
                n += 1
        return n

    def discard(self, key: str) -> bool:
        """Drop one entry from whichever tier holds it
        (ResultCache.drop's cascade)."""
        with self._lock:
            te = self._host.pop(key, None)
            if te is not None:
                self._host_bytes = max(self._host_bytes - te.nbytes, 0)
                return True
            te = self._disk.pop(key, None)
            if te is not None:
                self._disk_bytes = max(self._disk_bytes - te.nbytes, 0)
                self._remove_artifact(te)
                return True
            return self._restored.pop(key, None) is not None

    def clear(self) -> None:
        with self._lock:
            for te in self._disk.values():
                self._remove_artifact(te)
            self._host.clear()
            self._disk.clear()
            self._restored.clear()
            self._host_bytes = 0
            self._disk_bytes = 0

    def info(self) -> dict:
        with self._lock:
            return {"host_entries": len(self._host),
                    "host_bytes": self._host_bytes,
                    "disk_entries": len(self._disk),
                    "disk_bytes": self._disk_bytes,
                    "restored_entries": len(self._restored),
                    "demoted_host": self.demoted_host,
                    "demoted_disk": self.demoted_disk,
                    "promoted": self.promoted,
                    "thawed_restored": self.thawed_restored,
                    "dropped": self.dropped,
                    "corrupt": self.corrupt}

    def items_for_snapshot(self):
        """(key, TierEntry) pairs across host+disk tiers plus the
        still-frozen restored index — save_state's read surface (a
        list copy, the items_snapshot discipline)."""
        with self._lock:
            return (list(self._host.items()), list(self._disk.items()),
                    dict(self._restored))

    # -- IO primitives (ML019: the one place serve/ touches files) ----------

    def _backend(self) -> str:
        import jax
        return jax.default_backend()

    def _to_device(self, arr: np.ndarray, meta: dict):
        """Host array + tier metadata → the device-resident
        BlockMatrix a thawed CacheEntry serves (the h2d leg). Bit
        exact: numpy round-trips preserve every payload bit, so int
        paths stay int."""
        import jax
        from jax.sharding import NamedSharding
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.utils.checkpoint import _spec_from_json
        spec = _spec_from_json(meta["spec"])
        data = jax.device_put(arr, NamedSharding(self.mesh, spec))  # matlint: disable=ML008 the h2d promotion leg IS priced — spill_plan stages it and coeffs.spill_cost_ms bills it from the calibrated spill:h2d row
        return BlockMatrix(
            data=data, shape=tuple(meta["shape"]), mesh=self.mesh,
            spec=spec, nnz=meta.get("nnz"),
            block_size=meta.get("block_size") or 512,
            integral=bool(meta.get("integral")),
            int_abs_max=meta.get("int_abs_max"))

    def _write_artifact(self, key_hash: str, arr: np.ndarray,
                        directory: Optional[str] = None):
        """One sha1-verified ``.npy`` artifact (the checkpoint
        format's atomic tmp+rename and streamed-checksum discipline,
        per entry). Returns (path, sha1)."""
        from matrel_tpu.utils.checkpoint import (_check_name,
                                                 _file_sha1)
        d = directory or self._dir
        if d is None:
            raise ValueError("spill: no disk tier (state_dir unset)")
        _check_name(key_hash)
        os.makedirs(d, exist_ok=True)
        final = os.path.join(d, f"{key_hash}.npy")
        tmp = f"{final}.tmp{os.getpid()}"
        # an open handle, not a path: np.save appends ".npy" to a bare
        # path, which would break the atomic tmp -> final rename
        with open(tmp, "wb") as f:
            np.save(f, arr)
        sha1 = _file_sha1(tmp)
        os.replace(tmp, final)
        return final, sha1

    def _read_artifact(self, te: TierEntry) -> np.ndarray:
        """Read + sha1-verify one disk-tier artifact; raises the typed
        SnapshotCorruption on mismatch/unreadability (callers treat it
        as a miss — never a wrong answer)."""
        from matrel_tpu.utils.checkpoint import _file_sha1
        try:
            got = _file_sha1(te.file)
        except OSError as e:
            raise SnapshotCorruption(te.file or "?", str(e)) from e
        if te.sha1 is not None and got != te.sha1:
            raise SnapshotCorruption(
                te.file, f"sha1 mismatch (stored {te.sha1[:12]}…, "
                         f"computed {got[:12]}…)")
        try:
            return np.load(te.file)
        except (OSError, ValueError) as e:
            raise SnapshotCorruption(te.file, str(e)) from e

    def _remove_artifact(self, te: TierEntry) -> None:
        """Best-effort unlink of an invalidated disk-tier artifact —
        never let a bad disk fail an invalidation (the value is
        already unreachable through the index)."""
        if te.file:
            try:
                os.remove(te.file)
            except OSError:
                pass

    def _emit(self, op: str, meta: dict, tier: str, legs: list,
              **extra) -> None:
        """One ``spill`` obs record per demote/promote/thaw (the
        drift auditor ingests the measured legs as ``spill:<leg>``
        calibration samples — obs/drift.iter_samples). Never fails
        the cache operation."""
        if self.emit is None:
            return
        try:
            rec = {"op": op, "tier": tier,
                   "key_hash": meta.get("key_hash"),
                   "nbytes": meta.get("nbytes"),
                   "dims": list(meta.get("shape") or ()),
                   "legs": legs, "backend": self._backend()}
            rec.update(extra)
            self.emit(rec)
        except Exception:
            _log.warning("obs: spill event dropped", exc_info=True)


# ---------------------------------------------------------------------------
# Durable-state snapshots — save_state / load_snapshot
# ---------------------------------------------------------------------------


def _names_by_id(catalog: dict) -> Dict[int, str]:
    return {id(m): name for name, m in catalog.items()}


def _dep_names(dep_ids, names_by_id) -> Optional[list]:
    """dep id set → sorted catalog names, or None when any dep is an
    ad-hoc (unnamed) matrix — such an entry cannot be re-proven
    against a restored catalog and is skipped at save."""
    out = []
    for i in dep_ids:
        nm = names_by_id.get(i)
        if nm is None:
            return None
        out.append(nm)
    return sorted(out)


def save_state(session, directory: Optional[str] = None) -> dict:
    """Snapshot one session's durable state under ``directory``
    (default ``config.state_dir``): catalog matrices + the state dict
    via the checkpoint step format at ``<dir>/state``, result-cache
    entries as sha1-verified artifacts under ``<dir>/spill`` indexed
    by their session-independent NAME keys, the fleet directory, MQO
    template keys, and the autotune/drift tables. Returns the summary
    (also what the ``restart`` history line rolls up). Entries whose
    key or deps touch unnamed matrices are skipped (counted) — they
    cannot be re-proven against a restored catalog."""
    from matrel_tpu.serve import placement as placement_lib
    from matrel_tpu.utils.checkpoint import CheckpointManager

    root = directory or session.config.state_dir
    if not root:
        raise ValueError(
            "save_state needs a directory: pass one or set "
            "config.state_dir (docs/DURABILITY.md)")
    t0 = _now_ms()
    spill_dir = os.path.join(root, "spill")
    names = _names_by_id(session.catalog)
    index = []
    skipped = 0

    def _index_entry(nk, te: TierEntry, file: str, sha1: str,
                     dep_names: list) -> None:
        meta = dict(te.meta)
        meta["dep_names"] = dep_names
        index.append({"nk": nk, "file": os.path.relpath(file, root),
                      "sha1": sha1, "nbytes": te.nbytes,
                      "hits": te.hits, "meta": meta})

    mgr = None
    if session._spill is not None:
        mgr = session._spill

    def _freeze(nk, te: TierEntry, dep_names) -> None:
        nonlocal skipped
        if te.array is not None:
            writer = mgr._write_artifact if mgr is not None else None
            if writer is None:
                skipped += 1
                return
            file, sha1 = writer(te.meta["key_hash"], te.array,
                                directory=spill_dir)
            _index_entry(nk, te, file, sha1, dep_names)
        elif te.file:
            file = te.file
            inside = os.path.abspath(file).startswith(
                os.path.abspath(root) + os.sep)
            if not inside:
                # snapshot must be self-contained: a disk-tier
                # artifact living outside this snapshot root is
                # copied in (saving to the default state_dir never
                # takes this branch — the tiers already live there)
                import shutil
                os.makedirs(spill_dir, exist_ok=True)
                dst = os.path.join(spill_dir, os.path.basename(file))
                shutil.copy2(file, dst)
                file = dst
            _index_entry(nk, te, file, te.sha1, dep_names)
        else:
            skipped += 1

    # HBM entries: freeze through the same artifact writer
    for key, ent in session._result_cache.items_snapshot():
        nk = (placement_lib.fleet_key(ent.expr, names)
              if ent.expr is not None else None)
        dn = _dep_names(ent.dep_ids, names)
        if nk is None or dn is None or mgr is None:
            skipped += 1
            continue
        arr = np.asarray(ent.result.data)
        te = TierEntry(tier="host", meta=_entry_meta(ent),
                       nbytes=ent.nbytes, hits=ent.hits, array=arr)
        _freeze(nk, te, dn)
    if mgr is not None:
        host_items, disk_items, restored = mgr.items_for_snapshot()
        for _key, te in host_items:
            nk = (placement_lib.fleet_key(te.expr, names)
                  if te.expr is not None else None)
            dn = _dep_names(te.dep_ids, names)
            if nk is None or dn is None:
                skipped += 1
                continue
            _freeze(nk, te, dn)
        for _key, te in disk_items:
            nk = (placement_lib.fleet_key(te.expr, names)
                  if te.expr is not None else None)
            dn = _dep_names(te.dep_ids, names)
            if nk is None or dn is None:
                skipped += 1
                continue
            _freeze(nk, te, dn)
        # a not-yet-thawed restored index carries forward verbatim —
        # its entries already hold name keys + dep names
        for nk, te in restored.items():
            _freeze(nk, te, list(te.meta.get("dep_names") or ()))

    state = {
        "spill_schema": SNAPSHOT_SCHEMA,
        "rc_index": index,
        "rc_skipped": skipped,
        "fleet": _export_fleet(session),
        "mqo_templates": _export_templates(session),
        "tables": _export_tables(session.config),
    }
    ckpt = CheckpointManager(os.path.join(root, "state"),
                             config=session.config)
    step = ckpt.next_step()
    path = ckpt.save(step, matrices=dict(session.catalog), state=state)
    summary = {"path": path, "step": step,
               "catalog": len(session.catalog),
               "rc_entries": len(index), "rc_skipped": skipped,
               "ms": round(_now_ms() - t0, 3)}
    return summary


def _export_fleet(session):
    """Name-keyed fleet-directory records, or None. Affinity hints
    only ('never a correctness surface' — serve/fleet.py): a restored
    directory warms routing, it proves nothing."""
    if session._fleet is None:
        return None
    try:
        return session._fleet.export_directory()
    except Exception:
        _log.warning("save_state: fleet directory not exported",
                     exc_info=True)
        return None


def _export_templates(session):
    """MQO template KEYS only: compiled programs hold device buffers
    and traced closures no snapshot can carry — the restored index
    warms the template bookkeeping, programs recompile lazily on
    first rebind (docs/DURABILITY.md is explicit about this)."""
    if session._mqo is None:
        return None
    try:
        return session._mqo.template_keys()
    except Exception:
        _log.warning("save_state: mqo templates not exported",
                     exc_info=True)
        return None


def _export_tables(config) -> dict:
    """The learned-state tables, embedded as parsed JSON (not paths:
    a snapshot must be self-contained across machines)."""
    out = {}
    from matrel_tpu.obs import drift
    from matrel_tpu.parallel import autotune
    for name, path in (("autotune", autotune._table_path(config)),
                       ("drift", drift.table_path(config))):
        try:
            with open(path) as f:
                out[name] = json.load(f)
        except (OSError, ValueError):
            out[name] = None
    return out


def load_snapshot(session, directory: Optional[str] = None) -> dict:
    """Restore a :func:`save_state` snapshot into a fresh session —
    the warm-restart path. EVERY component is robust-read: a corrupt/
    truncated snapshot (or any single bad component) warns and
    cold-starts that component, never crashes the restore (PR 8's
    corrupt-table discipline; a disk-tier entry that later fails its
    sha1 surfaces as a per-entry miss via SnapshotCorruption
    handling). Returns the restore summary."""
    from matrel_tpu.resilience.errors import CheckpointCorruption
    from matrel_tpu.utils.checkpoint import CheckpointManager

    root = directory or session.config.state_dir
    if not root:
        raise ValueError(
            "restore needs a directory: pass one or set "
            "config.state_dir (docs/DURABILITY.md)")
    t0 = _now_ms()
    out = {"restored": False, "catalog": 0, "rc_entries": 0,
           "fleet": 0, "mqo_templates": 0, "tables": []}
    try:
        got = CheckpointManager(
            os.path.join(root, "state"),
            config=session.config).restore(session.mesh)
    except (CheckpointCorruption, OSError, ValueError) as e:
        _log.warning("restore: snapshot at %s unreadable (%s); "
                     "cold-starting", root, e)
        out["reason"] = str(e)
        return out
    if got is None:
        out["reason"] = "no snapshot"
        return out
    step, mats, _arrays, state = got
    if not isinstance(state, dict) \
            or state.get("spill_schema") != SNAPSHOT_SCHEMA:
        _log.warning("restore: snapshot at %s has foreign schema %r; "
                     "cold-starting", root,
                     (state or {}).get("spill_schema"))
        out["reason"] = "foreign schema"
        return out
    out["restored"] = True
    out["step"] = step
    # catalog — through register(), the load_catalog discipline
    for name in sorted(mats):
        try:
            session.register(name, mats[name])
            out["catalog"] += 1
        except Exception:
            _log.warning("restore: catalog entry %r skipped", name,
                         exc_info=True)
    out["tables"] = _restore_tables(session.config,
                                    state.get("tables") or {})
    out["rc_entries"] = _restore_rc_index(session, root,
                                          state.get("rc_index") or ())
    out["fleet"] = _restore_fleet(session, state.get("fleet"))
    out["mqo_templates"] = _restore_templates(
        session, state.get("mqo_templates"))
    out["ms"] = round(_now_ms() - t0, 3)
    return out


def _restore_rc_index(session, root: str, rc_index) -> int:
    """Seed the spill manager's restored index from the snapshot's
    name-keyed entry records. Requires an attached spill hierarchy
    (``spill_enable``) — without one there is no thaw path, so the
    entries are skipped (the zero-object default stays zero)."""
    if session._spill is None:
        if rc_index:
            _log.warning(
                "restore: %d cached result(s) in the snapshot but "
                "spill_enable is off — skipped (repeats recompute)",
                len(rc_index))
        return 0
    entries = {}
    for rec in rc_index:
        try:
            meta = dict(rec["meta"])
            entries[rec["nk"]] = TierEntry(
                tier="restored", meta=meta,
                nbytes=int(rec["nbytes"]),
                hits=int(rec.get("hits") or 0),
                file=os.path.join(root, rec["file"]),
                sha1=rec.get("sha1"))
        except (KeyError, TypeError, ValueError):
            _log.warning("restore: malformed rc index record skipped",
                         exc_info=True)
    return session._spill.seed_restored(entries)


def _restore_tables(config, tables: dict) -> list:
    """Write the embedded autotune/drift tables to their configured
    paths IF ABSENT — a live table on the restore host is newer truth
    than the snapshot; never clobber it. Returns the names written."""
    from matrel_tpu.obs import drift
    from matrel_tpu.parallel import autotune
    written = []
    for name, path in (("autotune", autotune._table_path(config)),
                       ("drift", drift.table_path(config))):
        payload = tables.get(name)
        if not isinstance(payload, dict) or os.path.exists(path):
            continue
        try:
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1)
            os.replace(tmp, path)
            written.append(name)
        except OSError:
            _log.warning("restore: %s table not written", name,
                         exc_info=True)
    return written


def _restore_fleet(session, records) -> int:
    if not records or session.config.fleet_slices < 1:
        return 0
    try:
        session._ensure_fleet()
    except Exception:
        return 0
    if session._fleet is None:
        return 0
    try:
        return session._fleet.seed_directory(records)
    except Exception:
        _log.warning("restore: fleet directory not seeded",
                     exc_info=True)
        return 0


def _restore_templates(session, keys) -> int:
    if not keys or not session._cse_on():
        return 0
    try:
        return session._mqo_state().seed_templates(keys)
    except Exception:
        _log.warning("restore: mqo templates not seeded",
                     exc_info=True)
        return 0
