"""Query placement for the multi-slice serving fleet (docs/FLEET.md).

Every mechanism here answers one question per submitted query: run it
WHOLE on one serving slice (data parallel over the query stream — no
DCN traffic, fewer devices), or SPAN it across the full mesh (every
device on one program — the dominant collective crosses the slice
boundary and rides DCN)? The MPMD pipeline-parallelism exemplar
(arXiv:2412.14374) places heterogeneous programs over slices by
exactly this trade; here the decision is a closed-form byte/FLOP
model weighted by the PR 4 topology weights, so DCN-crossing only
happens when the byte model says it pays.

Cost model (the two closed forms ``decide`` compares)::

    est_span_ms  = cg * GF / P_total + cm * MiB_dominant * w_dcn
    est_slice_ms = cg * GF / P_slice + cm * MiB_dominant * w_ici

where ``GF`` is the query's estimated GFLOPs (``ir/delta.
estimate_flops`` — the IVM pricing walk, reused), ``MiB_dominant``
the dominant collective's bytes (largest operand + output — the
gather/reduce a distributed matmul cannot avoid), ``w_ici`` the min
topology axis weight, and ``cg``/``cm`` the ms/GFLOP and ms/MiB
coefficients.

``w_dcn`` is the EFFECTIVE cross-slice weight
(:func:`effective_dcn_weight`): the max topology axis weight when the
mesh is weighted (configured calibration or detected slice
boundaries — trust it), else ``mesh.DCN_AXIS_WEIGHT`` — a fleet
partition DEFINES a slice boundary, and pricing the cut as free would
span every query across a boundary nobody measured. Calibrating
``config.axis_cost_weights`` (e.g. ``(1, 1.5)`` on a fast-DCN fabric)
is exactly how an operator tells the fleet spanning is cheap — the
same knob, same semantics as the planner's comm model
(docs/TOPOLOGY.md).

The coefficients are the drift-calibration feedback loop's first
consumer (ROADMAP item 4): when ``config.fleet_placement_calibration``
is on and the drift table (obs/drift.py, ``.matrel_drift.json``) has
rows for the query's (shape-class, backend, tier), the MEASURED
median ms/GFLOP + ms/est-MiB override the analytic defaults —
provenance-stamped ``"measured"`` exactly like autotune winners, so
MV114/obs can always say which model priced a decision. Cold classes
fall back to the analytic constants (``"analytic"``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

#: Analytic fallback coefficients — re-exported from the coefficient
#: seam (parallel/coeffs.py, where the whole pattern now lives after
#: the round-19 promotion) for the existing import sites: ~1 TFLOP/s
#: effective per device and ~50 GB/s effective collective bandwidth.
#: A drift-calibrated row replaces both the moment one exists; these
#: only ever decide the span/slice trade, never numerics.
from matrel_tpu.parallel.coeffs import (ANALYTIC_MS_PER_GFLOP,
                                        ANALYTIC_MS_PER_MIB)

#: Precision-SLA -> calibration-tier suffix for coefficient lookup
#: (the drift table keys tiered rows ``strategy@tier``). Default/exact
#: SLAs calibrate against untier rows (empty suffix — the historical
#: key format).
SLA_TIER = {"fast": "bf16x1", "high": "bf16x3", "bfloat16": "bf16x1",
            "bf16x3": "bf16x3", "int32": "int32", "int8": "int8"}


@dataclasses.dataclass(frozen=True)
class PlacementDecision:
    """One query's routing verdict.

    mode: ``"slice"`` (whole query on ``slice_id``) or ``"span"``
      (one program over the full mesh, DCN included).
    slice_id: target slice for ``"slice"`` mode; the least-loaded
      live slice (round-robin tie-break) — also recorded for
      ``"span"`` as the slice that WOULD have been chosen.
    est_slice_ms / est_span_ms: the two closed-form estimates.
    coeff_source: ``"measured"`` (drift-calibrated coefficients) or
      ``"analytic"`` (closed-form constants) — the provenance stamp.
    reason: why this mode won — ``"cost"`` (the model), ``"pinned"``
      (un-rebindable leaves force the full-mesh session), or
      ``"solo"`` (single-slice fleet: nothing to place between).
    weights: the (wx, wy) topology weights the estimates used.
    dcn_axis: index of the axis the span estimate billed as DCN (the
      max-weight axis) — what MV114 re-checks.
    """

    mode: str
    slice_id: int
    est_slice_ms: float
    est_span_ms: float
    coeff_source: str
    reason: str
    weights: Tuple[float, float]
    dcn_axis: int

    def stamp(self) -> dict:
        """The plan-attr stamp a span-placed query carries
        (``expr.with_attrs(placement=...)``) — what MV114 verifies
        against the mesh it finds the plan on. KEY-STABLE fields
        only: the stamp lands in expr attrs, which feed the plan and
        result-cache structural keys, so anything that drifts between
        submissions of the same query (the cost estimates, the
        measured/analytic coefficient provenance — both change
        whenever the drift table gains rows) would shatter every
        span-placed query's cache keys on a long-lived host (the
        PR 12 brownout-rung plan-key-shatter class). The estimates
        and ``coeff_source`` ride the ``placement`` obs event
        instead."""
        return {"mode": self.mode,
                "weights": list(self.weights),
                "dcn_axis": self.dcn_axis,
                "dcn_weight": effective_dcn_weight(self.weights)}


# ---------------------------------------------------------------------------
# Fleet structural keys — catalog-name-based, stable across slices
# ---------------------------------------------------------------------------


def fleet_key(e, names_by_id: Dict[int, str],
              prefix: str = "") -> Optional[str]:
    """The fleet directory's cross-slice structural key: the session
    plan key's exact interior walk with each leaf keyed by its CATALOG
    NAME instead of its ``id()`` — two slices holding replicas of the
    same named tables produce the SAME key for the same query, which
    is what lets one global directory map keys to owning slices.
    ``None`` when any leaf is unnamed (an ad-hoc matrix the fleet
    cannot rebind): the query still places, it just never enters the
    directory."""
    from matrel_tpu.session import _plan_key_spans

    def tok(n):
        name = names_by_id.get(id(n.attrs["matrix"]))
        if name is None:
            return None
        return f"{n.kind}:@{name}:{n.attrs['matrix'].shape}"

    try:
        parts, _pins, _spans = _plan_key_spans(e, leaf_token=tok)
    except KeyError:
        return None
    return prefix + "|".join(parts)


# ---------------------------------------------------------------------------
# Drift-calibrated coefficients (ROADMAP item 4's feedback loop)
# ---------------------------------------------------------------------------


def placement_coefficients(path: str) -> Dict[Tuple[str, str, str],
                                              dict]:
    """The per-(shape-class, backend, tier) coefficient blend the
    placement model consults ahead of its closed forms — since round
    19 served from the ONE coefficient seam
    (parallel/coeffs.class_coefficients, matlint ML018): this module
    introduced the pattern in PR 15, the main planner now shares it,
    and both read the same memoised view of the drift table. Rows:
    ``{"ms_per_gflop", "ms_per_mib", "count", "source": "measured"}``;
    absent keys mean "cold class" and the caller falls back to the
    analytic model."""
    from matrel_tpu.parallel import coeffs
    return coeffs.class_coefficients(path)


def reset_coefficient_cache() -> None:
    """Test hook: drop the seam's stat-signature memo (kept under the
    historical name — tests and operators call it here)."""
    from matrel_tpu.parallel import coeffs
    coeffs.reset_coefficient_cache()


# ---------------------------------------------------------------------------
# The decision
# ---------------------------------------------------------------------------


def pick_slice(slice_loads, rr_tick: int = 0) -> int:
    """The slice a slice-placed query would land on: least-loaded
    (``slice_loads`` maps slice_id -> queue depth for LIVE slices
    only), ties broken round-robin on ``rr_tick`` so an idle fleet
    still spreads a stream. ONE helper shared by :func:`decide` and
    the fleet's directory fast path, so a hit's replica preference
    agrees with where placement would have routed the miss."""
    ids = sorted(slice_loads)
    if not ids:
        raise ValueError("placement needs at least one live slice")
    min_load = min(slice_loads[i] for i in ids)
    tied = [i for i in ids if slice_loads[i] == min_load]
    return tied[rr_tick % len(tied)]


def effective_dcn_weight(weights: Tuple[float, float]) -> float:
    """The weight a span-placed query's dominant collective is billed
    at for crossing the slice cut: the max topology axis weight when
    the mesh is weighted (calibrated OR detected — anything but the
    homogeneous (1.0, 1.0) default, matching the config contract
    that any non-default ``axis_cost_weights`` overrides detection,
    fast-DCN calibrations <= 1.0 included), else the DCN default —
    the fleet partition IS a boundary even when nothing detected one
    (virtual slices), and an unpriced cut would make spanning always
    win. ONE helper shared by ``decide`` and MV114, so the verifier
    re-checks exactly what the placer billed."""
    from matrel_tpu.core.mesh import DCN_AXIS_WEIGHT
    w = tuple(float(x) for x in weights)
    return max(w) if w != (1.0, 1.0) else float(DCN_AXIS_WEIGHT)


def query_footprint(e, config=None) -> Tuple[float, float, tuple]:
    """(flops, dominant_bytes, dims) of one query: estimated FLOPs via
    the IVM pricing walk (one estimate feeding both patch pricing and
    placement — the engine keeps one FLOP model), dominant collective
    bytes as largest-leaf + output bytes (the gather/reduce a
    distributed execution cannot avoid), and the root dims the shape
    class buckets on."""
    from matrel_tpu.ir.delta import estimate_flops
    flops = float(estimate_flops(e, config))
    itemsize = 4.0
    biggest = 0.0

    def walk(n):
        nonlocal biggest
        if not n.children:
            biggest = max(biggest,
                          float(n.shape[0]) * float(n.shape[1]))
            return
        for c in n.children:
            walk(c)

    walk(e)
    out_elems = float(e.shape[0]) * float(e.shape[1])
    dominant = (biggest + out_elems) * itemsize
    return flops, dominant, tuple(e.shape)


def decide(e, config, weights: Tuple[float, float],
           total_devices: int, slice_devices: int,
           slice_loads, backend: str = "cpu",
           sla: str = "default",
           eligible: bool = True,
           rr_tick: int = 0) -> PlacementDecision:
    """Place one query: pick the least-loaded live slice (``
    slice_loads`` maps slice_id -> queue depth for LIVE slices only;
    ties break round-robin on ``rr_tick`` so an idle fleet still
    spreads a stream), then compare the two closed forms under the
    topology weights. ``eligible=False`` (un-rebindable leaves) pins
    the query to the full-mesh session — span by necessity, recorded
    as such."""
    target = pick_slice(slice_loads, rr_tick)
    w_dcn = effective_dcn_weight(weights)
    w_ici = min(weights)
    dcn_axis = 0 if weights[0] >= weights[1] else 1
    flops, dom_bytes, dims = query_footprint(e, config)
    cg, cm = ANALYTIC_MS_PER_GFLOP, ANALYTIC_MS_PER_MIB
    source = "analytic"
    if getattr(config, "fleet_placement_calibration", False):
        from matrel_tpu.obs import drift
        coeffs = placement_coefficients(drift.table_path(config))
        row = coeffs.get((drift.shape_class(dims), backend,
                          SLA_TIER.get(sla, "")))
        if row is not None:
            if row["ms_per_gflop"] is not None:
                cg = float(row["ms_per_gflop"])
            if row["ms_per_mib"] is not None:
                cm = float(row["ms_per_mib"])
            source = "measured"
    gf = flops / 1e9
    mib = dom_bytes / (1 << 20)
    est_span = cg * gf / max(total_devices, 1) + cm * mib * w_dcn
    est_slice = cg * gf / max(slice_devices, 1) + cm * mib * w_ici
    if not eligible:
        mode, reason = "span", "pinned"
    elif len(slice_loads) < 2 and slice_devices >= total_devices:
        mode, reason = "slice", "solo"
    elif est_span < est_slice * float(
            getattr(config, "fleet_span_margin", 1.0)):
        mode, reason = "span", "cost"
    else:
        mode, reason = "slice", "cost"
    return PlacementDecision(mode=mode, slice_id=target,
                             est_slice_ms=est_slice,
                             est_span_ms=est_span,
                             coeff_source=source, reason=reason,
                             weights=(float(weights[0]),
                                      float(weights[1])),
                             dcn_axis=dcn_axis)
