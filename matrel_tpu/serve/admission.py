"""Per-tenant admission: weighted-fair queuing + quota sheds for the
serve pipeline (docs/OVERLOAD.md).

The PR 5/8 admission queue was one FIFO with one global bound — under
mixed-tenant overload that is a bully's charter: whoever submits
fastest owns the queue, the MultiPlans formed from it, and everyone
else's deadline budget. This module replaces it with the fair-
scheduler discipline of the reference's multi-tenant Spark operating
point (PAPER.md [P1]) as explicit single-process mechanisms:

- **Per-tenant queues, stride-scheduled.** Each tenant named by
  ``config.serve_tenant_weights`` (plus one implicit queue for
  everyone else) holds its own deque; ``get`` pops from the non-empty
  tenant with the smallest stride *pass* value, advancing that pass by
  ``STRIDE_BASE / weight`` — over any backlogged interval tenant
  service is proportional to weight, and batch FORMATION inherits the
  same fairness because the worker's coalescing loop is just repeated
  pops (one chatty tenant cannot monopolize a MultiPlan). A tenant
  going active re-enters at the current virtual time, so an idle
  tenant banks no credit. With no weights configured every entry lands
  in the one implicit queue and pop order is EXACTLY the historical
  FIFO — bit-identical, test-pinned.
- **Quota shed before global shed.** A tenant at its
  ``serve_tenant_queue_max`` quota sheds typed
  ``AdmissionShed(tenant=..., scope="tenant")`` BEFORE the global
  ``serve_queue_max`` bound is consulted: the quota protects every
  other tenant's share of the queue, the global bound protects the
  host.
- **Expired-entry purge at the shed decision point.** A queue full of
  deadline-expired entries used to shed LIVE traffic while dead
  entries held the slots until the worker reached them; now both shed
  checks first purge expired entries (resolving their futures typed)
  and re-check the bound — a full-of-expired queue admits a fresh
  query (regression-pinned).

Thread-safety and the drain contract: one lock backs everything; the
``all_tasks_done``/``unfinished_tasks``/``task_done`` surface mirrors
``queue.Queue`` exactly (the pipeline's ``drain`` waits on the same
condition it always did), and ``get``/``get_nowait`` raise
``queue.Empty`` so the worker loop's except clauses are unchanged.
"""

from __future__ import annotations

import queue
import threading
from collections import OrderedDict, deque
from typing import Dict, Optional

from matrel_tpu.config import parse_tenant_weights
from matrel_tpu.resilience.errors import AdmissionShed, DeadlineExceeded
from matrel_tpu.resilience.retry import now as _now
from matrel_tpu.utils import lockdep

#: Stride-scheduling numerator: pass advances by BASE/weight per pop,
#: so a weight-4 tenant is popped 4x as often as a weight-1 tenant
#: over any backlogged interval.
STRIDE_BASE = 1024.0

#: Minimum seconds between purge SCANS at the shed decision points.
#: Under sustained overload thousands of sheds/s would each rescan the
#: full queue while holding the lock the worker needs to pop — a
#: deadline only expires on a wall-clock timescale, so one scan per
#: few milliseconds bounds the cost without changing the contract
#: (a queue sitting full of expired entries is always past the
#: throttle by the time a fresh submission tests it).
PURGE_INTERVAL_S = 0.005


class AdmissionQueue:
    """Weighted-fair multi-tenant admission queue (see module
    docstring). Entries are the pipeline's tuples; the queue only ever
    inspects ``entry[1]`` (the future) and ``entry[4]`` (the deadline)
    — both present from the 5-tuple shape on."""

    def __init__(self, config, slo=None):
        self.weights: Dict[str, float] = parse_tenant_weights(
            getattr(config, "serve_tenant_weights", ""))
        self.global_max = int(getattr(config, "serve_queue_max", 0))
        self.tenant_max = int(getattr(config,
                                      "serve_tenant_queue_max", 0))
        # SLO feed (obs/slo.py; None when off — zero per-event cost):
        # typed sheds and purged-expired entries are availability
        # budget burn, reported per tenant OUTSIDE the queue lock
        # (the monitor's emit callback does event-log I/O)
        self.slo = slo
        self._lock = lockdep.make_lock("serve.admission")
        self._not_empty = threading.Condition(self._lock)
        # queue.Queue-compatible drain surface (pipeline.drain waits
        # on these exact names)
        self.all_tasks_done = threading.Condition(self._lock)
        self.unfinished_tasks = 0
        # tenant -> deque, created on first submission; deques are
        # bounded by the shed checks in put(), not by maxlen — a
        # maxlen deque DROPS silently, and the whole point here is
        # that refusal is typed
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._pass: Dict[str, float] = {}
        self._vtime = 0.0
        self._size = 0
        # lifetime counters (the overload event emitter reads these
        # and turns them into per-cycle deltas)
        self.sheds: Dict[str, int] = {}
        self.purged_expired = 0
        self._last_purge = 0.0

    # -- weights -----------------------------------------------------------

    def weight(self, tenant: Optional[str]) -> float:
        return self.weights.get(tenant or "", 1.0)

    def lowest_weight_tenant(self, tenant: Optional[str]) -> bool:
        """True when ``tenant`` sits at the bottom of the configured
        weight order — the rung-3 brownout shed set. With no weights
        (or all weights equal) NOBODY is lowest: a single implicit
        tenant has no one to yield to."""
        if not self.weights:
            return False
        values = set(self.weights.values())
        if len(values) < 2:
            return False
        return self.weight(tenant) <= min(values)

    # -- producer side -----------------------------------------------------

    def put(self, entry, tenant: Optional[str] = None) -> None:
        """Admit one entry for ``tenant`` (None/"" = the implicit
        tenant). Sheds typed — per-tenant quota FIRST, then the global
        bound — after purging deadline-expired entries at each
        decision point. Purged futures resolve AFTER the lock drops:
        ``set_exception`` runs done-callbacks inline, and a callback
        that touches this queue (a resubmit, a qsize read) from inside
        the lock would deadlock the submitting thread."""
        key = tenant if tenant is not None else self._entry_tenant(
            entry)
        to_fail: list = []
        shed = False
        try:
            with self._lock:
                dq = self._queues.get(key)
                if dq is None:
                    dq = self._queues[key] = deque()  # matlint: disable=ML011 bounded by the typed shed checks below — a maxlen deque would DROP silently instead of refusing typed
                    self._pass[key] = self._vtime
                if self.tenant_max > 0 and len(dq) >= self.tenant_max:
                    self._purge_expired_locked(key, to_fail)
                    if len(dq) >= self.tenant_max:
                        self.sheds[key] = self.sheds.get(key, 0) + 1
                        shed = True
                        raise AdmissionShed(self.tenant_max,
                                            tenant=key or None,
                                            scope="tenant")
                if self.global_max > 0 \
                        and self._size >= self.global_max:
                    self._purge_expired_locked(None, to_fail)
                    if self._size >= self.global_max:
                        self.sheds[key] = self.sheds.get(key, 0) + 1
                        shed = True
                        raise AdmissionShed(self.global_max,
                                            tenant=key or None,
                                            scope="queue")
                # a tenant going active re-enters at the current
                # virtual time: no banked credit from idling
                # (standard stride)
                if not dq:
                    self._pass[key] = max(self._pass.get(key, 0.0),
                                          self._vtime)
                dq.append(entry)
                self._size += 1
                self.unfinished_tasks += 1
                self._not_empty.notify()
        finally:
            for fut, ex, _t in to_fail:
                # RUNNING first (the worker's own discipline): a
                # future the caller cancelled concurrently drops out
                # instead of racing set_exception
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(ex)
            # SLO burn (outside the lock — the monitor's alert
            # emission does I/O): a typed shed and every purged
            # expired entry are availability bad events
            if self.slo is not None:
                if shed:
                    self.slo.record_shed(key or None)
                for _f, _ex, t in to_fail:
                    self.slo.record_miss(t or None)

    # queue.Queue compat (tests enqueue legacy short tuples directly)
    put_nowait = put

    def record_shed(self, tenant: Optional[str]) -> None:
        """Count a shed decided OUTSIDE the bounds (the brownout
        rung-3 tenant shed happens in the pipeline, before put)."""
        key = tenant or ""
        with self._lock:
            self.sheds[key] = self.sheds.get(key, 0) + 1
        if self.slo is not None:
            self.slo.record_shed(tenant)

    @staticmethod
    def _entry_tenant(entry) -> str:
        return (entry[5] or "") if len(entry) > 5 else ""

    @staticmethod
    def entry_provenance(entry) -> dict:
        """Project one queue tuple for a lineage record (the answer
        provenance ledger, obs/provenance.py) — keeps the tuple-layout
        knowledge here with the rest of the entry accessors, so the
        capture sites never index the 7-tuple directly."""
        return {
            "tenant": (entry[5] or None) if len(entry) > 5 else None,
            "sla": entry[3] if len(entry) > 3 else None,
            "staleness_ms": entry[6] if len(entry) > 6 else None,
        }

    def _purge_expired_locked(self, tenant: Optional[str],
                              to_fail: list) -> int:
        """Drop every queued entry whose deadline already expired —
        from one tenant's queue or all of them — collecting
        (future, typed error, tenant) triples into ``to_fail`` for the
        caller to resolve OUTSIDE the lock. Runs at the shed decision
        points so dead entries can never hold slots against live
        traffic."""
        t = _now()
        if t - self._last_purge < PURGE_INTERVAL_S:
            return 0
        self._last_purge = t
        purged = 0
        keys = (tenant,) if tenant is not None else tuple(self._queues)
        for key in keys:
            dq = self._queues.get(key)
            if not dq:
                continue
            keep: deque = deque()  # matlint: disable=ML011 transient rebuild buffer for one purge pass, bounded by the queue it rebuilds
            for it in dq:
                dl = it[4] if len(it) > 4 else None
                if dl is not None and dl.expired():
                    to_fail.append((it[1], DeadlineExceeded(
                        dl.budget_ms, dl.elapsed_ms(),
                        context="queued query (purged)"), key))
                    purged += 1
                    self._size -= 1
                    self.unfinished_tasks -= 1
                else:
                    keep.append(it)
            if purged:
                dq.clear()
                dq.extend(keep)
        if purged:
            self.purged_expired += purged
            if self.unfinished_tasks <= 0:
                self.all_tasks_done.notify_all()
        return purged

    # -- consumer side (the worker) ----------------------------------------

    def _pop_locked(self):
        """Weighted-fair pop: the non-empty tenant with the smallest
        stride pass value wins (ties break by tenant creation order —
        deterministic); its pass advances by BASE/weight. One implicit
        tenant degenerates to popleft — the historical FIFO."""
        best = None
        for key, dq in self._queues.items():
            if not dq:
                continue
            p = self._pass.get(key, 0.0)
            if best is None or p < best[1]:
                best = (key, p)
        if best is None:
            raise queue.Empty
        key, p = best
        self._vtime = p
        self._pass[key] = p + STRIDE_BASE / self.weight(key)
        self._size -= 1
        return self._queues[key].popleft()

    def get(self, timeout: Optional[float] = None):
        with self._not_empty:
            if self._size == 0:
                self._not_empty.wait(timeout)
            return self._pop_locked()   # raises queue.Empty when dry

    def get_nowait(self):
        with self._lock:
            return self._pop_locked()

    def task_done(self) -> None:
        with self.all_tasks_done:
            self.unfinished_tasks -= 1
            if self.unfinished_tasks <= 0:
                self.all_tasks_done.notify_all()

    # -- failover (serve/fleet.py is the ONE caller) -----------------------

    def steal_entries(self) -> list:
        """Remove and return every queued entry as ``(entry, tenant)``
        pairs — the dead/wedged-slice failover surface (docs/FLEET.md):
        the fleet re-admits the stolen entries onto surviving slices
        with their futures, deadlines and tenant attribution intact.
        Unfinished-task accounting is released for the stolen entries
        (their completion is now another queue's business), so a drain
        against the dead pipeline never waits on work that moved."""
        with self._lock:
            out = []
            for key, dq in self._queues.items():
                while dq:
                    out.append((dq.popleft(), key))
            self._size = 0
            self.unfinished_tasks = max(
                self.unfinished_tasks - len(out), 0)
            if self.unfinished_tasks <= 0:
                self.all_tasks_done.notify_all()
            return out

    # -- observability -----------------------------------------------------

    def qsize(self) -> int:
        with self._lock:
            return self._size

    def tenant_depths(self) -> Dict[str, int]:
        with self._lock:
            return {k: len(dq) for k, dq in self._queues.items()
                    if dq}

    def counters(self) -> dict:
        """Cumulative shed/purge counters (the overload event emitter
        diffs successive snapshots into per-cycle deltas)."""
        with self._lock:
            return {"sheds": dict(self.sheds),
                    "purged_expired": self.purged_expired}
