"""Padding + canonical sharding rules shared by BlockMatrix and the executor.

Logical dims are padded up to a multiple of the total device count so every
sharding used anywhere in the system (P(x,y), P((x,y),None), P(None,(x,y)),
and the shard_map in_specs of the matmul strategies) divides evenly.
Size-1 dims (vectors from rowSum/colSum, scalars from sum/trace) are NOT
padded — they stay 1 and are replicated on that axis, which keeps matvec
shapes natural and avoids degenerate shards.

Invariant maintained by the executor: every padded array is exactly zero
outside its logical region, so matmul/add/elementwise-multiply compose
without masks; ops that break the invariant re-mask (see executor.py).
"""

from __future__ import annotations

import math
from typing import Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from matrel_tpu.core import mesh as mesh_lib


def pad_dim(d: int, total_devices: int) -> int:
    if d <= 1:
        return max(d, 1)
    return int(math.ceil(d / total_devices) * total_devices)


def padded_shape(shape: Tuple[int, int], mesh: Mesh) -> Tuple[int, int]:
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    total = gx * gy
    return pad_dim(shape[0], total), pad_dim(shape[1], total)


def canonical_spec(pshape: Tuple[int, int], mesh: Mesh) -> P:
    """2D sharding where divisible, replicated where not (size-1 dims)."""
    x, y = mesh.axis_names
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    row = x if pshape[0] % gx == 0 and pshape[0] >= gx and gx > 1 else None
    col = y if pshape[1] % gy == 0 and pshape[1] >= gy and gy > 1 else None
    return P(row, col)


def canonical_sharding(pshape: Tuple[int, int], mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, canonical_spec(pshape, mesh))
