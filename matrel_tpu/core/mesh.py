"""Device-mesh construction — the TPU analogue of MatRel's Spark cluster.

In the reference, ``MatfastSession`` rides a SparkSession whose executors form
the "device grid" and whose partitioners (RowPartitioner / ColumnPartitioner /
BlockCyclicPartitioner, SURVEY.md §2 "Partitioners") map block indices onto
executors. On TPU the grid is explicit: a 2D ``jax.sharding.Mesh`` over ICI,
and the partitioner-equivalents are ``NamedSharding`` PartitionSpecs
(see shardings.py).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _near_square_factors(n: int) -> Tuple[int, int]:
    """Factor n into (a, b) with a*b == n and a <= b, a as large as possible."""
    a = int(math.isqrt(n))
    while a > 1 and n % a != 0:
        a -= 1
    return a, n // a


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up — the executor-registration analogue
    (SURVEY.md §3.1: "mesh construction replaces executor registration").

    On a multi-host TPU slice, call once per host before make_mesh();
    jax.devices() then spans the full slice and the 2D mesh lays out over
    ICI within a slice and DCN across slices. No-op when JAX is already
    initialized or args are absent (single-process dev loop, tests, CI).
    """
    if coordinator_address is None:
        return
    import jax.distributed
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)


def make_mesh(
    shape: Optional[Tuple[int, int]] = None,
    axis_names: Tuple[str, str] = ("x", "y"),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a 2D device mesh.

    ``shape=None`` derives a near-square 2D grid from the available devices —
    the analogue of MatRel defaulting its block-cyclic grid to the executor
    count. A single device yields a 1x1 mesh, so all code paths are
    mesh-uniform even on one chip.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if shape is None:
        shape = _near_square_factors(n)
    r, c = shape
    if r * c != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    grid = np.asarray(devs, dtype=object).reshape(r, c)
    return Mesh(grid, axis_names)


def mesh_grid_shape(mesh: Mesh) -> Tuple[int, int]:
    names = mesh.axis_names
    return mesh.shape[names[0]], mesh.shape[names[1]]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharding_2d(mesh: Mesh) -> NamedSharding:
    """Both matrix dims sharded: the 2D block-cyclic analogue."""
    x, y = mesh.axis_names
    return NamedSharding(mesh, P(x, y))


def sharding_row(mesh: Mesh) -> NamedSharding:
    """Row-sharded over the whole mesh (both axes on dim 0) — the
    RowPartitioner analogue."""
    x, y = mesh.axis_names
    return NamedSharding(mesh, P((x, y), None))


def sharding_col(mesh: Mesh) -> NamedSharding:
    """Column-sharded over the whole mesh — the ColumnPartitioner analogue."""
    x, y = mesh.axis_names
    return NamedSharding(mesh, P(None, (x, y)))
