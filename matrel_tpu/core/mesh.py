"""Device-mesh construction — the TPU analogue of MatRel's Spark cluster.

In the reference, ``MatfastSession`` rides a SparkSession whose executors form
the "device grid" and whose partitioners (RowPartitioner / ColumnPartitioner /
BlockCyclicPartitioner, SURVEY.md §2 "Partitioners") map block indices onto
executors. On TPU the grid is explicit: a 2D ``jax.sharding.Mesh`` over ICI,
and the partitioner-equivalents are ``NamedSharding`` PartitionSpecs
(see shardings.py).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _near_square_factors(n: int) -> Tuple[int, int]:
    """Factor n into (a, b) with a*b == n and a <= b, a as large as possible."""
    a = int(math.isqrt(n))
    while a > 1 and n % a != 0:
        a -= 1
    return a, n // a


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host bring-up — the executor-registration analogue
    (SURVEY.md §3.1: "mesh construction replaces executor registration").

    On a multi-host TPU slice, call once per host before make_mesh();
    jax.devices() then spans the full slice and the 2D mesh lays out over
    ICI within a slice and DCN across slices. No-op when JAX is already
    initialized or args are absent (single-process dev loop, tests, CI).
    """
    if coordinator_address is None:
        return
    import jax.distributed
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)


def make_mesh(
    shape: Optional[Tuple[int, int]] = None,
    axis_names: Tuple[str, str] = ("x", "y"),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a 2D device mesh.

    ``shape=None`` derives a near-square 2D grid from the available devices —
    the analogue of MatRel defaulting its block-cyclic grid to the executor
    count. A single device yields a 1x1 mesh, so all code paths are
    mesh-uniform even on one chip.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if shape is None:
        shape = _near_square_factors(n)
    r, c = shape
    if r * c != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    grid = np.asarray(devs, dtype=object).reshape(r, c)
    return Mesh(grid, axis_names)


def mesh_grid_shape(mesh: Mesh) -> Tuple[int, int]:
    names = mesh.axis_names
    return mesh.shape[names[0]], mesh.shape[names[1]]


# -- mesh topology (hierarchical ICI/DCN fabric description) ----------------

#: Default relative inverse-bandwidth of a mesh axis whose hops cross a
#: slice boundary (DCN) versus an in-slice (ICI) axis. v5e ICI sustains
#: ~200 GB/s per link against ~25 GB/s of per-host DCN, so a byte over
#: the cross-slice axis costs ~8 in-slice bytes of time. Order of
#: magnitude is what matters — the planner needs "much more expensive",
#: and ``config.axis_cost_weights`` is the calibration hook for the
#: exact ratio of a given fabric (docs/TOPOLOGY.md).
DCN_AXIS_WEIGHT = 8.0


@dataclasses.dataclass(frozen=True)
class MeshTopology:
    """Per-axis interconnect description of a 2D device mesh.

    ``axis_weights[i]`` is the RELATIVE inverse bandwidth of mesh axis i
    (axis_names order): the planner's comm model bills a collective leg
    that moves data over axis i at bytes × axis_weights[i], so a
    reduce-scatter riding a slow DCN axis stops looking as cheap as the
    same bytes over ICI. (1.0, 1.0) is the homogeneous (single-slice)
    mesh — every cost reduces to the flat byte model, bit-identically.

    ``source`` records where the weights came from, for explain/obs:
    "config" (explicit ``config.axis_cost_weights``), "detected"
    (slice boundaries found via ``device.slice_index``), or "default"
    (homogeneous — nothing configured, nothing detected).
    """

    axis_weights: Tuple[float, float] = (1.0, 1.0)
    source: str = "default"

    @property
    def uniform(self) -> bool:
        return self.axis_weights[0] == self.axis_weights[1]


def detect_slice_axes(mesh: Mesh) -> Tuple[bool, bool]:
    """Which mesh axes cross a TPU slice boundary, from the slice index
    JAX exposes on multi-slice deployments (``device.slice_index``).
    An axis "crosses" when any two devices adjacent along it belong to
    different slices — hops over it ride DCN, not ICI. Devices without
    a slice index (CPU, single-slice TPU) detect as (False, False)."""
    devs = mesh.devices
    ids = [[getattr(d, "slice_index", None) for d in row] for row in devs]
    flat = [s for row in ids for s in row]
    if any(s is None for s in flat) or len(set(flat)) <= 1:
        return False, False
    gx = len(ids)
    gy = len(ids[0]) if gx else 0
    x_cross = any(ids[i][j] != ids[i + 1][j]
                  for i in range(gx - 1) for j in range(gy))
    y_cross = any(ids[i][j] != ids[i][j + 1]
                  for i in range(gx) for j in range(gy - 1))
    return x_cross, y_cross


def _resolve_topology(mesh: Mesh,
                      weights: Tuple[float, float]) -> MeshTopology:
    if weights != (1.0, 1.0):
        return MeshTopology(weights, "config")
    try:
        crossings = detect_slice_axes(mesh)
    except Exception:         # exotic device objects must not break
        crossings = (False, False)      # planning — fall back to flat
    if any(crossings):
        return MeshTopology(
            tuple(DCN_AXIS_WEIGHT if c else 1.0 for c in crossings),
            "detected")
    return MeshTopology((1.0, 1.0), "default")


_resolve_topology_cached = functools.lru_cache(maxsize=64)(
    _resolve_topology)


def mesh_topology(mesh: Mesh, config=None) -> MeshTopology:
    """The MeshTopology governing cost models on this mesh: an explicit
    ``config.axis_cost_weights`` ≠ (1.0, 1.0) wins (the calibration
    hook — a measured DCN/ICI ratio beats the built-in default), else
    slice-boundary detection weights each DCN-crossing axis
    DCN_AXIS_WEIGHT, else the homogeneous default. Never raises: the
    planner consults this on every matmul (and the session on every
    query, cache hits included), so resolution is memoised per
    (mesh, configured weights) — the O(devices) slice scan runs once
    per mesh, not once per matmul."""
    from matrel_tpu.config import default_config
    cfg = config or default_config()
    w = tuple(cfg.axis_cost_weights)
    try:
        return _resolve_topology_cached(mesh, w)
    except TypeError:         # unhashable mesh stand-ins (tests)
        return _resolve_topology(mesh, w)


def axis_weights(mesh: Mesh, config=None) -> Tuple[float, float]:
    """Shorthand for ``mesh_topology(mesh, config).axis_weights`` — the
    (wx, wy) every weighted costing path consumes."""
    return mesh_topology(mesh, config).axis_weights


# -- slice views (multi-slice serving fleet — serve/fleet.py) ---------------


def slice_device_groups(mesh: Mesh, n: int):
    """Partition a mesh's devices into ``n`` serving-slice groups:
    ``(groups, source)`` with ``source`` naming how the boundary was
    drawn.

    - ``"detected"``: the devices carry ``slice_index`` values and the
      distinct indices match ``n`` exactly — the groups ARE the real
      TPU slices, so intra-group collectives ride ICI and only
      cross-group traffic rides DCN.
    - ``"virtual"``: no (matching) hardware boundary; the flat device
      list splits into ``n`` equal contiguous runs. Row-major
      contiguity keeps each virtual slice a compact neighbourhood of
      the parent grid — the CPU-testable stand-in the whole fleet
      subsystem runs on in tier-1.
    - ``"shared"``: fewer devices than would split evenly; every
      group is the full device set (oversubscribed virtual slices —
      the 1-chip dev loop). Still a valid fleet: the slices share
      hardware but keep independent queues/workers/caches.
    """
    if n < 1:
        raise ValueError(f"slice count must be >= 1, got {n!r}")
    devs = [d for row in mesh.devices for d in row]
    by_slice: dict = {}
    for d in devs:
        by_slice.setdefault(getattr(d, "slice_index", None),
                            []).append(d)
    if None not in by_slice and len(by_slice) == n:
        return [by_slice[k] for k in sorted(by_slice)], "detected"
    if len(devs) >= n and len(devs) % n == 0:
        c = len(devs) // n
        return [devs[i * c:(i + 1) * c] for i in range(n)], "virtual"
    return [list(devs) for _ in range(n)], "shared"


def slice_meshes(mesh: Mesh, n: int):
    """``n`` near-square sub-meshes over :func:`slice_device_groups`'
    partition (same axis names as the parent, so specs/strategies are
    vocabulary-compatible): ``(meshes, source)``."""
    groups, source = slice_device_groups(mesh, n)
    return [make_mesh(axis_names=mesh.axis_names, devices=g)
            for g in groups], source


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharding_2d(mesh: Mesh) -> NamedSharding:
    """Both matrix dims sharded: the 2D block-cyclic analogue."""
    x, y = mesh.axis_names
    return NamedSharding(mesh, P(x, y))


def sharding_row(mesh: Mesh) -> NamedSharding:
    """Row-sharded over the whole mesh (both axes on dim 0) — the
    RowPartitioner analogue."""
    x, y = mesh.axis_names
    return NamedSharding(mesh, P((x, y), None))


def sharding_col(mesh: Mesh) -> NamedSharding:
    """Column-sharded over the whole mesh — the ColumnPartitioner analogue."""
    x, y = mesh.axis_names
    return NamedSharding(mesh, P(None, (x, y)))
