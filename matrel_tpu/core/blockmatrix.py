"""BlockMatrix — the TPU-native distributed matrix representation (layer L2).

Reference semantics (SURVEY.md §2 "Block representation"): MatRel stores a
distributed matrix as a Spark Dataset/RDD of ``(rowBlkIdx, colBlkIdx,
MLMatrix)`` records with a fixed block size, partitioned across executors by a
RowPartitioner / ColumnPartitioner / BlockCyclicPartitioner.

TPU-native redesign: a BlockMatrix wraps ONE ``jax.Array`` laid out on a 2D
device mesh with a ``NamedSharding``. "Blocks" are the shards XLA already
manages; the partitioner choice collapses into the PartitionSpec. What
remains of the reference's representation is the metadata the optimizer
needs — logical shape, block size for cost granularity, and an nnz/sparsity
estimate (SURVEY.md §2 "Statistics / sparsity estimation").

Padding: logical dims are padded up to multiples of the mesh axis sizes so
every shard is equal-sized (XLA-friendly static shapes). The padded region is
zero; aggregate ops mask it where zeros would change the answer (max/min).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core import mesh as mesh_lib

Array = jax.Array


def _pad_to(n: int, multiple: int) -> int:
    return int(math.ceil(n / multiple) * multiple) if multiple > 1 else n


@dataclasses.dataclass
class BlockMatrix:
    """A 2D-mesh-sharded distributed matrix.

    Attributes:
      data: the padded device array, shape ``padded_shape``.
      shape: the logical (unpadded) shape.
      mesh: the device mesh this matrix lives on.
      spec: PartitionSpec of ``data`` (how blocks map to devices).
      nnz: estimated number of structural nonzeros in the logical region,
        or None for "assume dense".
      block_size: logical tile edge for cost-model granularity.
      integral: every entry is an exact integer representable in f32 —
        the static fact the precision-tier planner's integer-exactness
        inference reads (ir/stats.infer_integral), so an "exact"
        accuracy SLA can route integer-shaped workloads (adjacency
        matrices, counts, boolean joins) onto the exact int32/int8 MXU
        paths. Auto-detected by from_numpy for integer/bool sources;
        declare it explicitly for integer-valued float data.
      int_abs_max: max|entry| of an integral matrix, recorded at
        construction (from_numpy computes it for integral sources) —
        the magnitude half of the exactness proof: the planner only
        auto-picks an int tier when the accumulated product provably
        fits the int32 accumulator (ir/stats.integral_abs_bound), so
        "exact" can never silently wrap. None = unproven (the chooser
        conservatively keeps f32).
    """

    data: Array
    shape: Tuple[int, int]
    mesh: Mesh
    spec: P
    nnz: Optional[int] = None
    block_size: int = 512
    integral: bool = False
    int_abs_max: Optional[float] = None

    # -- basic properties ---------------------------------------------------

    @property
    def padded_shape(self) -> Tuple[int, int]:
        return tuple(self.data.shape)  # type: ignore[return-value]

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def sparsity(self) -> float:
        """Fraction of nonzeros (density). 1.0 when unknown/dense."""
        if self.nnz is None:
            return 1.0
        n = self.shape[0] * self.shape[1]
        return self.nnz / n if n else 0.0

    @property
    def is_padded(self) -> bool:
        return self.padded_shape != self.shape

    def sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec)

    # -- construction -------------------------------------------------------

    @staticmethod
    def _padded_dims(shape: Tuple[int, int], mesh: Mesh) -> Tuple[int, int]:
        from matrel_tpu.core import padding
        return padding.padded_shape(tuple(shape), mesh)

    @classmethod
    def from_numpy(
        cls,
        arr: np.ndarray,
        mesh: Optional[Mesh] = None,
        spec: Optional[P] = None,
        dtype: Any = None,
        config: Optional[MatrelConfig] = None,
        nnz: Optional[int] = None,
        integral: Optional[bool] = None,
    ) -> "BlockMatrix":
        cfg = config or default_config()
        if integral is None:
            # integer/bool sources are integer-valued by construction;
            # float sources need the caller's word (checking every
            # entry would cost an O(n) host pass per construction)
            integral = bool(np.issubdtype(arr.dtype, np.integer)
                            or arr.dtype == np.bool_)
        # magnitude proof for the int-tier overflow gate — one O(n)
        # host max, noise next to the device_put copy, only for the
        # (rare) integral sources that can use it
        int_abs_max = (float(np.abs(arr).max()) if integral and arr.size
                       else (0.0 if integral else None))
        if arr.ndim == 1:
            arr = arr.reshape(-1, 1)
        if arr.ndim != 2:
            raise ValueError(f"BlockMatrix is 2D; got shape {arr.shape}")
        mesh = mesh or mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
        dtype = dtype or cfg.default_dtype
        shape = tuple(arr.shape)
        ps = cls._padded_dims(shape, mesh)
        if spec is None:
            from matrel_tpu.core import padding
            spec = padding.canonical_spec(ps, mesh)
        if ps != shape:
            padded = np.zeros(ps, dtype=dtype)
            padded[: shape[0], : shape[1]] = arr
        else:
            padded = np.asarray(arr, dtype=dtype)
        data = jax.device_put(padded, NamedSharding(mesh, spec))
        return cls(data=data, shape=shape, mesh=mesh, spec=spec, nnz=nnz,
                   block_size=cfg.block_size, integral=bool(integral),
                   int_abs_max=int_abs_max)

    @classmethod
    def from_array(
        cls,
        data: Array,
        shape: Tuple[int, int],
        mesh: Mesh,
        spec: P,
        nnz: Optional[int] = None,
        block_size: Optional[int] = None,
    ) -> "BlockMatrix":
        return cls(data=data, shape=tuple(shape), mesh=mesh, spec=spec,
                   nnz=nnz, block_size=block_size or default_config().block_size)

    @classmethod
    def random(
        cls,
        shape: Tuple[int, int],
        mesh: Optional[Mesh] = None,
        spec: Optional[P] = None,
        dtype: Any = None,
        seed: int = 0,
        config: Optional[MatrelConfig] = None,
    ) -> "BlockMatrix":
        """Uniform [0,1) random matrix, generated device-side (no host copy)."""
        cfg = config or default_config()
        mesh = mesh or mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
        dtype = dtype or cfg.default_dtype
        ps = cls._padded_dims(tuple(shape), mesh)
        if spec is None:
            from matrel_tpu.core import padding
            spec = padding.canonical_spec(ps, mesh)
        sharding = NamedSharding(mesh, spec)

        @jax.jit  # matlint: disable=ML010 construction-time helper — arrays are born here, before any plan exists
        def _gen():
            vals = jax.random.uniform(jax.random.PRNGKey(seed), ps, dtype=jnp.float32)
            r = jnp.arange(ps[0])[:, None] < shape[0]
            c = jnp.arange(ps[1])[None, :] < shape[1]
            vals = jnp.where(r & c, vals, 0.0).astype(dtype)
            return jax.lax.with_sharding_constraint(vals, sharding)

        return cls(data=_gen(), shape=tuple(shape), mesh=mesh, spec=spec,
                   nnz=None, block_size=cfg.block_size)

    @classmethod
    def zeros(cls, shape, mesh=None, spec=None, dtype=None, config=None) -> "BlockMatrix":
        cfg = config or default_config()
        mesh = mesh or mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
        dtype = dtype or cfg.default_dtype
        ps = cls._padded_dims(tuple(shape), mesh)
        if spec is None:
            from matrel_tpu.core import padding
            spec = padding.canonical_spec(ps, mesh)
        sharding = NamedSharding(mesh, spec)
        data = jax.jit(lambda: jax.lax.with_sharding_constraint(  # matlint: disable=ML010 construction-time helper — arrays are born here, before any plan exists
            jnp.zeros(ps, dtype=dtype), sharding))()
        return cls(data=data, shape=tuple(shape), mesh=mesh, spec=spec, nnz=0,
                   block_size=cfg.block_size)

    @classmethod
    def eye(cls, n: int, mesh=None, spec=None, dtype=None, config=None) -> "BlockMatrix":
        cfg = config or default_config()
        mesh = mesh or mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
        dtype = dtype or cfg.default_dtype
        ps = cls._padded_dims((n, n), mesh)
        if spec is None:
            from matrel_tpu.core import padding
            spec = padding.canonical_spec(ps, mesh)
        sharding = NamedSharding(mesh, spec)

        @jax.jit  # matlint: disable=ML010 construction-time helper — arrays are born here, before any plan exists
        def _gen():
            r = jnp.arange(ps[0])[:, None]
            c = jnp.arange(ps[1])[None, :]
            vals = jnp.where((r == c) & (r < n), 1.0, 0.0).astype(dtype)
            return jax.lax.with_sharding_constraint(vals, sharding)

        return cls(data=_gen(), shape=(n, n), mesh=mesh, spec=spec, nnz=n,
                   block_size=cfg.block_size)

    @classmethod
    def from_block_fn(
        cls,
        shape: Tuple[int, int],
        fn: Callable[[Array, Array], Array],
        mesh=None,
        spec=None,
        dtype=None,
        config=None,
        nnz: Optional[int] = None,
    ) -> "BlockMatrix":
        """Generate entries from ``fn(row_idx, col_idx)`` device-side.

        The analogue of the reference's per-block generator constructors:
        fn receives broadcastable index grids and returns values.
        """
        cfg = config or default_config()
        mesh = mesh or mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
        dtype = dtype or cfg.default_dtype
        ps = cls._padded_dims(tuple(shape), mesh)
        if spec is None:
            from matrel_tpu.core import padding
            spec = padding.canonical_spec(ps, mesh)
        sharding = NamedSharding(mesh, spec)

        @jax.jit  # matlint: disable=ML010 construction-time helper — arrays are born here, before any plan exists
        def _gen():
            r = jnp.arange(ps[0])[:, None]
            c = jnp.arange(ps[1])[None, :]
            vals = fn(r, c).astype(dtype)
            vals = jnp.where((r < shape[0]) & (c < shape[1]), vals, 0)
            return jax.lax.with_sharding_constraint(vals, sharding)

        return cls(data=_gen(), shape=tuple(shape), mesh=mesh, spec=spec,
                   nnz=nnz, block_size=cfg.block_size)

    # -- materialisation ----------------------------------------------------

    def to_numpy(self) -> np.ndarray:
        """Gather to host, dropping padding."""
        full = np.asarray(jax.device_get(self.data))
        return full[: self.shape[0], : self.shape[1]]

    def block_until_ready(self) -> "BlockMatrix":
        self.data.block_until_ready()
        return self

    # -- sharding management ------------------------------------------------

    def with_spec(self, spec: P) -> "BlockMatrix":
        """Reshard (the analogue of repartitioning by a different partitioner)."""
        if spec == self.spec:
            return self
        data = jax.device_put(self.data, NamedSharding(self.mesh, spec))
        return dataclasses.replace(self, data=data, spec=spec)

    def valid_mask(self) -> Array:
        """Boolean mask of the logical (non-padding) region, padded shape."""
        ps = self.padded_shape
        r = jnp.arange(ps[0])[:, None] < self.shape[0]
        c = jnp.arange(ps[1])[None, :] < self.shape[1]
        return r & c

    # -- lazy DSL (builds IR; mirrors the reference's Dataset implicits) ----
    # SURVEY.md §2 "Scala DSL": t(), multiply(), add(), elemMultiply(),
    # divide(), power(), rowSum(), colSum(), sum(), trace(), vec(),
    # rankOneUpdate(), selection/join methods. Each returns a lazy MatExpr.

    def expr(self):
        from matrel_tpu.ir.expr import leaf
        return leaf(self)

    def t(self):
        return self.expr().t()

    def multiply(self, other):
        return self.expr().multiply(other)

    def matmul(self, other):
        return self.expr().multiply(other)

    def add(self, other):
        return self.expr().add(other)

    def subtract(self, other):
        return self.expr().subtract(other)

    def elem_multiply(self, other):
        return self.expr().elem_multiply(other)

    def divide(self, other):
        return self.expr().divide(other)

    def add_scalar(self, s):
        return self.expr().add_scalar(s)

    def multiply_scalar(self, s):
        return self.expr().multiply_scalar(s)

    def power(self, p):
        return self.expr().power(p)

    def row_sum(self):
        return self.expr().row_sum()

    def col_sum(self):
        return self.expr().col_sum()

    def sum(self):
        return self.expr().sum()

    def trace(self):
        return self.expr().trace()

    def norm(self, kind: str = "fro"):
        return self.expr().norm(kind)

    def inverse(self):
        return self.expr().inverse()

    def solve(self, b, assume: str = "general"):
        return self.expr().solve(b, assume=assume)

    def vec(self):
        return self.expr().vec()

    def rank_one_update(self, u, v):
        return self.expr().rank_one_update(u, v)

    def select_value(self, predicate, **kw):
        return self.expr().select_value(predicate, **kw)

    def select_index(self, *, rows=None, cols=None):
        return self.expr().select_index(rows=rows, cols=cols)

    def join_on_index(self, other, merge):
        return self.expr().join_on_index(other, merge)

    def __matmul__(self, other):
        return self.multiply(other)

    def __add__(self, other):
        return self.add(other)

    def __sub__(self, other):
        return self.subtract(other)

    def __mul__(self, other):
        if isinstance(other, (int, float)):
            return self.multiply_scalar(other)
        return self.elem_multiply(other)

    def __repr__(self) -> str:
        return (f"BlockMatrix(shape={self.shape}, dtype={self.dtype}, "
                f"spec={self.spec}, nnz={self.nnz}, "
                f"mesh={dict(self.mesh.shape)})")


jax.tree_util.register_pytree_node(
    BlockMatrix,
    lambda bm: ((bm.data,), (bm.shape, bm.mesh, bm.spec, bm.nnz, bm.block_size)),
    lambda aux, children: BlockMatrix(children[0], *aux),
)
