"""Element-sparse COO matrix — the TPU answer to the reference's CSC
local payloads (SURVEY.md §2 "Local matrix kernels": MLlib `SparseMatrix`
is element-granular CSC).

Block-granular sparsity (`core/sparse.py`) is the MXU-idiomatic layout for
matrices whose nonzeros cluster into dense tiles; uniform/graph-shaped
sparsity (1e-5-class densities) would touch every tile. `COOMatrix` covers
that regime: a fixed edge list compiled once into a blocked one-hot SpMV
plan (`ops/spmv.py` — width-row gather + hi/lo one-hot MXU scatter, no
XLA scatter anywhere), with transpose plans built lazily and a plain
segment-sum fallback for degree distributions the planner refuses.

Matvec is the hot op (PageRank-class workloads). `matmat` handles narrow
dense right-hand sides by reusing the row gather once and cycling the
one-hot contraction per column — fine for the tall-skinny multivector
shapes (personalization vectors, feature panels) this type exists for.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from matrel_tpu.ops import spmv as spmv_lib


@dataclasses.dataclass
class COOMatrix:
    """Immutable element-sparse matrix over a fixed coordinate list."""

    rows: np.ndarray          # host int64, unsorted as given
    cols: np.ndarray
    vals: np.ndarray          # float32
    shape: Tuple[int, int]
    _plan: Optional[spmv_lib.EdgeSpMVPlan] = dataclasses.field(
        default=None, repr=False)
    _plan_t: Optional[spmv_lib.EdgeSpMVPlan] = dataclasses.field(
        default=None, repr=False)
    _plan_tried: bool = dataclasses.field(default=False, repr=False)
    _plan_t_tried: bool = dataclasses.field(default=False, repr=False)
    # fallback-path caches: (device out_ids, device in_ids, device vals),
    # sorted by out_ids — fixed per matrix, built once per direction
    _seg_fwd: Optional[tuple] = dataclasses.field(default=None, repr=False)
    _seg_bwd: Optional[tuple] = dataclasses.field(default=None, repr=False)
    # set by .shard(): forward matvec runs this mesh-sharded plan; kept
    # separate from _plan so the DSL/transpose paths (which expect
    # default-placement plans) never see sharded tables
    _mesh: Optional[object] = dataclasses.field(default=None, repr=False)
    _plan_sharded: Optional[spmv_lib.EdgeSpMVPlan] = dataclasses.field(
        default=None, repr=False)

    # ---------------------------------------------------------- build
    @classmethod
    def from_edges(cls, rows, cols, vals=None,
                   shape: Optional[Tuple[int, int]] = None) -> "COOMatrix":
        rows = np.asarray(rows, dtype=np.int64).ravel()
        cols = np.asarray(cols, dtype=np.int64).ravel()
        if rows.shape != cols.shape:
            raise ValueError(f"rows/cols length mismatch: "
                             f"{rows.shape} vs {cols.shape}")
        if vals is None:
            vals = np.ones(rows.shape, np.float32)
        else:
            vals = np.asarray(vals, dtype=np.float32).ravel()
            if vals.shape != rows.shape:
                raise ValueError("vals length must match rows/cols")
        if shape is None:
            shape = (int(rows.max()) + 1 if rows.size else 1,
                     int(cols.max()) + 1 if cols.size else 1)
        if rows.size and (rows.min() < 0 or rows.max() >= shape[0]
                          or cols.min() < 0 or cols.max() >= shape[1]):
            raise ValueError("edge indices out of bounds for shape")
        return cls(rows=rows, cols=cols, vals=vals, shape=tuple(shape))

    @classmethod
    def from_scipy(cls, mat) -> "COOMatrix":
        """From any scipy.sparse matrix (converted to COO)."""
        coo = mat.tocoo()
        return cls.from_edges(coo.row, coo.col, coo.data, shape=coo.shape)

    # ------------------------------------------------------ properties
    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    @property
    def T(self) -> "COOMatrix":
        """Transpose view — shares this matrix's plan caches swapped, so
        ``A.T.matvec`` costs no rebuild once ``A.rmatvec`` (or a prior
        ``A.T``) compiled a plan."""
        return COOMatrix(rows=self.cols, cols=self.rows, vals=self.vals,
                         shape=(self.shape[1], self.shape[0]),
                         _plan=self._plan_t, _plan_t=self._plan,
                         _plan_tried=self._plan_t_tried,
                         _plan_t_tried=self._plan_tried,
                         _seg_fwd=self._seg_bwd, _seg_bwd=self._seg_fwd)

    # ----------------------------------------------------------- plans
    def _get_plan(self) -> Optional[spmv_lib.EdgeSpMVPlan]:
        if not self._plan_tried:
            self._plan = spmv_lib.build_spmv_plan(
                self.rows, self.cols, self.vals,
                n_rows=self.shape[0], n_cols=self.shape[1])
            self._plan_tried = True
        return self._plan

    def _get_plan_t(self) -> Optional[spmv_lib.EdgeSpMVPlan]:
        if not self._plan_t_tried:
            self._plan_t = spmv_lib.build_spmv_plan(
                self.cols, self.rows, self.vals,
                n_rows=self.shape[1], n_cols=self.shape[0])
            self._plan_t_tried = True
        return self._plan_t

    def shard(self, mesh) -> "COOMatrix":
        """Return a copy whose forward ``matvec`` runs a plan
        row-decomposed over every device of ``mesh``
        (ops/spmv.py::shard_plan): each device contracts its slice of
        output blocks against the replicated x and one tiled all_gather
        assembles the result. DSL/transpose/rmatvec paths keep their own
        default-placement plans.

        Raises when the planner refuses this graph — distribution was
        requested explicitly, and silently degrading to a single-device
        segment-sum would mask the perf cliff; catch and use the
        unsharded matrix if that degradation is acceptable."""
        if self._plan_tried and self._plan is None:
            plan = None                      # known-refused: don't rebuild
        elif (self._plan_tried and self._plan is not None
              and self._plan._tables is None):
            plan = self._plan                # fresh unexpanded plan: reuse
        else:
            plan = spmv_lib.build_spmv_plan(self.rows, self.cols,
                                            self.vals,
                                            n_rows=self.shape[0],
                                            n_cols=self.shape[1])
        if plan is None:
            raise ValueError(
                "degree distribution too heavy-tailed for the one-hot "
                "plan; sharded matvec unavailable for this graph")
        return COOMatrix(rows=self.rows, cols=self.cols, vals=self.vals,
                         shape=self.shape, _mesh=mesh,
                         _plan_sharded=spmv_lib.shard_plan(plan, mesh))

    # ------------------------------------------------------------ ops
    def matvec(self, x) -> jax.Array:
        """y = A·x, shape (n_rows,)."""
        x = jnp.asarray(x, jnp.float32).ravel()
        if x.shape[0] != self.shape[1]:
            raise ValueError(f"x has {x.shape[0]} entries, A has "
                             f"{self.shape[1]} columns")
        if self._plan_sharded is not None:
            return spmv_lib.spmv_sharded(self._plan_sharded, x,
                                         self._mesh)
        plan = self._get_plan()
        if plan is not None:
            return spmv_lib.spmv(plan, x)
        if self._seg_fwd is None:
            self._seg_fwd = self._seg_arrays(self.rows, self.cols)
        return self._segment_matvec(self._seg_fwd, x, self.shape[0])

    def rmatvec(self, y) -> jax.Array:
        """x = Aᵀ·y, shape (n_cols,) — uses the lazily-built transpose
        plan (no re-sort of the forward plan)."""
        y = jnp.asarray(y, jnp.float32).ravel()
        if y.shape[0] != self.shape[0]:
            raise ValueError(f"y has {y.shape[0]} entries, A has "
                             f"{self.shape[0]} rows")
        plan = self._get_plan_t()
        if plan is not None:
            return spmv_lib.spmv(plan, y)
        if self._seg_bwd is None:
            self._seg_bwd = self._seg_arrays(self.cols, self.rows)
        return self._segment_matvec(self._seg_bwd, y, self.shape[1])

    def matmat(self, X) -> jax.Array:
        """Y = A·X for dense X (n_cols, k): the k-wide SpMM shares ONE
        row gather across all columns (ops/spmv.py::spmm; wide X is
        processed in column chunks). Falls back to a per-column matvec
        loop only when the planner refused the graph."""
        X = jnp.asarray(X, jnp.float32)
        if X.ndim != 2 or X.shape[0] != self.shape[1]:
            raise ValueError(f"X must be ({self.shape[1]}, k), "
                             f"got {X.shape}")
        if X.shape[1] == 0:
            return jnp.zeros((self.shape[0], 0), jnp.float32)
        if self._plan_sharded is not None:
            return spmv_lib.spmm_sharded(self._plan_sharded, X,
                                         self._mesh)
        plan = self._get_plan()
        if plan is not None:
            return spmv_lib.spmm(plan, X)
        cols = [self.matvec(X[:, j]) for j in range(X.shape[1])]
        return jnp.stack(cols, axis=1)

    def _seg_arrays(self, out_ids, in_ids) -> tuple:
        order = np.argsort(out_ids, kind="stable")
        return (jnp.asarray(out_ids[order], jnp.int32),
                jnp.asarray(in_ids[order], jnp.int32),
                jnp.asarray(self.vals[order]))

    def _segment_matvec(self, seg, x, n_out) -> jax.Array:
        out_s, in_s, val_s = seg
        w = val_s * spmv_lib.gather_1d(x, in_s)
        return jax.ops.segment_sum(w, out_s, num_segments=n_out,
                                   indices_are_sorted=True)

    def to_dense(self) -> np.ndarray:
        """Host densification (small matrices / tests)."""
        out = np.zeros(self.shape, np.float32)
        np.add.at(out, (self.rows, self.cols), self.vals)
        return out

    def to_block(self, mesh=None, config=None):
        """Densify into a mesh-sharded BlockMatrix — the fallback when a
        COO matrix is used where no SpMV lowering applies. O(n·m) memory:
        meant for modest shapes; keep giant graphs on matvec/matmat."""
        from matrel_tpu.core.blockmatrix import BlockMatrix
        return BlockMatrix.from_numpy(self.to_dense(), mesh=mesh,
                                      config=config, nnz=self.nnz)

    # ------------------------------------------------------------ DSL
    def expr(self):
        """Enter the lazy IR as an element-sparse leaf: matmuls against
        narrow dense operands lower to the one-hot SpMV plan; other uses
        densify (see executor)."""
        from matrel_tpu.ir import expr as E
        return E.MatExpr("coo_leaf", (), tuple(self.shape),
                         min(self.nnz, self.shape[0] * self.shape[1]),
                         {"matrix": self})

    def multiply(self, other):
        from matrel_tpu.ir import expr as E
        return E.matmul(self.expr(), E.as_expr(other))
