"""Element-sparse COO matrix — the TPU answer to the reference's CSC
local payloads (SURVEY.md §2 "Local matrix kernels": MLlib `SparseMatrix`
is element-granular CSC).

Block-granular sparsity (`core/sparse.py`) is the MXU-idiomatic layout for
matrices whose nonzeros cluster into dense tiles; uniform/graph-shaped
sparsity (1e-5-class densities) would touch every tile. `COOMatrix` covers
that regime: a fixed edge list compiled once into a blocked one-hot SpMV
plan (`ops/spmv.py` — width-row gather + hi/lo one-hot MXU scatter, no
XLA scatter anywhere; on real TPU the compact-table Pallas executor of
`ops/pallas_spmv.py` runs it at 13 B/slot), with transpose plans built
lazily and a plain segment-sum fallback for degree distributions the
planner refuses.

Matvec is the hot op (PageRank-class workloads). `matmat` handles narrow
dense right-hand sides by reusing the row gather once and cycling the
one-hot contraction per column — fine for the tall-skinny multivector
shapes (personalization vectors, feature panels) this type exists for.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from matrel_tpu.ops import spmv as spmv_lib


@dataclasses.dataclass
class COOMatrix:
    """Immutable element-sparse matrix over a fixed coordinate list."""

    rows: np.ndarray          # host int64, unsorted as given
    cols: np.ndarray
    vals: np.ndarray          # float32
    shape: Tuple[int, int]
    _plan: Optional[spmv_lib.EdgeSpMVPlan] = dataclasses.field(
        default=None, repr=False)
    _plan_t: Optional[spmv_lib.EdgeSpMVPlan] = dataclasses.field(
        default=None, repr=False)
    _plan_tried: bool = dataclasses.field(default=False, repr=False)
    _plan_t_tried: bool = dataclasses.field(default=False, repr=False)
    # fallback-path caches: (device out_ids, device in_ids, device vals),
    # sorted by out_ids — fixed per matrix, built once per direction
    _seg_fwd: Optional[tuple] = dataclasses.field(default=None, repr=False)
    _seg_bwd: Optional[tuple] = dataclasses.field(default=None, repr=False)
    # set by .shard(): forward matvec runs this mesh-sharded plan; kept
    # separate from _plan so the DSL/transpose paths (which expect
    # default-placement plans) never see sharded tables
    _mesh: Optional[object] = dataclasses.field(default=None, repr=False)
    _plan_sharded: Optional[spmv_lib.EdgeSpMVPlan] = dataclasses.field(
        default=None, repr=False)
    # True when coordinates are known-unique (outputs of coalesce/
    # select_value/join): lets chained relational ops skip the re-sort
    _coalesced: bool = dataclasses.field(default=False, repr=False)

    # ---------------------------------------------------------- build
    @classmethod
    def from_edges(cls, rows, cols, vals=None,
                   shape: Optional[Tuple[int, int]] = None) -> "COOMatrix":
        rows = np.asarray(rows, dtype=np.int64).ravel()
        cols = np.asarray(cols, dtype=np.int64).ravel()
        if rows.shape != cols.shape:
            raise ValueError(f"rows/cols length mismatch: "
                             f"{rows.shape} vs {cols.shape}")
        if vals is None:
            vals = np.ones(rows.shape, np.float32)
        else:
            vals = np.asarray(vals, dtype=np.float32).ravel()
            if vals.shape != rows.shape:
                raise ValueError("vals length must match rows/cols")
        if shape is None:
            shape = (int(rows.max()) + 1 if rows.size else 1,
                     int(cols.max()) + 1 if cols.size else 1)
        if rows.size and (rows.min() < 0 or rows.max() >= shape[0]
                          or cols.min() < 0 or cols.max() >= shape[1]):
            raise ValueError("edge indices out of bounds for shape")
        return cls(rows=rows, cols=cols, vals=vals, shape=tuple(shape))

    @classmethod
    def from_scipy(cls, mat) -> "COOMatrix":
        """From any scipy.sparse matrix (converted to COO)."""
        coo = mat.tocoo()
        return cls.from_edges(coo.row, coo.col, coo.data, shape=coo.shape)

    # ------------------------------------------------------ properties
    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    @property
    def T(self) -> "COOMatrix":
        """Transpose view — shares this matrix's plan caches swapped, so
        ``A.T.matvec`` costs no rebuild once ``A.rmatvec`` (or a prior
        ``A.T``) compiled a plan."""
        return COOMatrix(rows=self.cols, cols=self.rows, vals=self.vals,
                         shape=(self.shape[1], self.shape[0]),
                         _plan=self._plan_t, _plan_t=self._plan,
                         _plan_tried=self._plan_t_tried,
                         _plan_t_tried=self._plan_tried,
                         _seg_fwd=self._seg_bwd, _seg_bwd=self._seg_fwd,
                         _coalesced=self._coalesced)

    # ----------------------------------------------------------- plans
    def _get_plan(self) -> Optional[spmv_lib.EdgeSpMVPlan]:
        if not self._plan_tried:
            self._plan = spmv_lib.build_spmv_plan(
                self.rows, self.cols, self.vals,
                n_rows=self.shape[0], n_cols=self.shape[1])
            self._plan_tried = True
        return self._plan

    def _get_plan_t(self) -> Optional[spmv_lib.EdgeSpMVPlan]:
        if not self._plan_t_tried:
            self._plan_t = spmv_lib.build_spmv_plan(
                self.cols, self.rows, self.vals,
                n_rows=self.shape[1], n_cols=self.shape[0])
            self._plan_t_tried = True
        return self._plan_t

    def shard(self, mesh) -> "COOMatrix":
        """Return a copy whose forward ``matvec`` runs a plan
        row-decomposed over every device of ``mesh``
        (ops/spmv.py::shard_plan): each device contracts its slice of
        output blocks against the replicated x and one tiled all_gather
        assembles the result. DSL/transpose/rmatvec paths keep their own
        default-placement plans.

        Raises when the planner refuses this graph — distribution was
        requested explicitly, and silently degrading to a single-device
        segment-sum would mask the perf cliff; catch and use the
        unsharded matrix if that degradation is acceptable."""
        if self._plan_tried and self._plan is None:
            plan = None                      # known-refused: don't rebuild
        elif (self._plan_tried and self._plan is not None
              and self._plan._tables is None):
            plan = self._plan                # fresh unexpanded plan: reuse
        else:
            plan = spmv_lib.build_spmv_plan(self.rows, self.cols,
                                            self.vals,
                                            n_rows=self.shape[0],
                                            n_cols=self.shape[1])
        if plan is None:
            raise ValueError(
                "degree distribution too heavy-tailed for the one-hot "
                "plan; sharded matvec unavailable for this graph")
        return COOMatrix(rows=self.rows, cols=self.cols, vals=self.vals,
                         shape=self.shape, _mesh=mesh,
                         _plan_sharded=spmv_lib.shard_plan(plan, mesh),
                         _coalesced=self._coalesced)

    # ------------------------------------------------------------ ops
    @staticmethod
    def _compact_mode() -> bool:
        """On real TPU the compact-table Pallas executor wins on both
        time and (17×) memory — the expanded one-hot tables are never
        built (config.pallas_enabled is the single shared gate)."""
        from matrel_tpu.config import pallas_enabled
        return pallas_enabled()

    def matvec(self, x) -> jax.Array:
        """y = A·x, shape (n_rows,)."""
        x = jnp.asarray(x, jnp.float32).ravel()
        if x.shape[0] != self.shape[1]:
            raise ValueError(f"x has {x.shape[0]} entries, A has "
                             f"{self.shape[1]} columns")
        if self._plan_sharded is not None:
            return spmv_lib.spmv_sharded(self._plan_sharded, x,
                                         self._mesh)
        plan = self._get_plan()
        if plan is not None:
            if self._compact_mode():
                from matrel_tpu.ops import pallas_spmv as pc
                return pc.spmv_compact(plan, x)
            return spmv_lib.spmv(plan, x)
        if self._seg_fwd is None:
            self._seg_fwd = self._seg_arrays(self.rows, self.cols)
        return self._segment_matvec(self._seg_fwd, x, self.shape[0])

    def rmatvec(self, y) -> jax.Array:
        """x = Aᵀ·y, shape (n_cols,) — uses the lazily-built transpose
        plan (no re-sort of the forward plan)."""
        y = jnp.asarray(y, jnp.float32).ravel()
        if y.shape[0] != self.shape[0]:
            raise ValueError(f"y has {y.shape[0]} entries, A has "
                             f"{self.shape[0]} rows")
        plan = self._get_plan_t()
        if plan is not None:
            return spmv_lib.spmv(plan, y)
        if self._seg_bwd is None:
            self._seg_bwd = self._seg_arrays(self.cols, self.rows)
        return self._segment_matvec(self._seg_bwd, y, self.shape[1])

    def matmat(self, X) -> jax.Array:
        """Y = A·X for dense X (n_cols, k): the k-wide SpMM shares ONE
        row gather across all columns (ops/spmv.py::spmm; wide X is
        processed in column chunks). Falls back to a per-column matvec
        loop only when the planner refused the graph."""
        X = jnp.asarray(X, jnp.float32)
        if X.ndim != 2 or X.shape[0] != self.shape[1]:
            raise ValueError(f"X must be ({self.shape[1]}, k), "
                             f"got {X.shape}")
        if X.shape[1] == 0:
            return jnp.zeros((self.shape[0], 0), jnp.float32)
        if self._plan_sharded is not None:
            return spmv_lib.spmm_sharded(self._plan_sharded, X,
                                         self._mesh)
        plan = self._get_plan()
        if plan is not None:
            if self._compact_mode():
                from matrel_tpu.ops import pallas_spmv as pc
                return pc.spmm_compact(plan, X)
            return spmv_lib.spmm(plan, X)
        cols = [self.matvec(X[:, j]) for j in range(X.shape[1])]
        return jnp.stack(cols, axis=1)

    def _seg_arrays(self, out_ids, in_ids) -> tuple:
        order = np.argsort(out_ids, kind="stable")
        return (jnp.asarray(out_ids[order], jnp.int32),
                jnp.asarray(in_ids[order], jnp.int32),
                jnp.asarray(self.vals[order]))

    def _segment_matvec(self, seg, x, n_out) -> jax.Array:
        out_s, in_s, val_s = seg
        w = val_s * spmv_lib.gather_1d(x, in_s)
        return jax.ops.segment_sum(w, out_s, num_segments=n_out,
                                   indices_are_sorted=True)

    def to_dense(self) -> np.ndarray:
        """Host densification (small matrices / tests)."""
        out = np.zeros(self.shape, np.float32)
        np.add.at(out, (self.rows, self.cols), self.vals)
        return out

    def to_block(self, mesh=None, config=None):
        """Densify into a mesh-sharded BlockMatrix — the fallback when a
        COO matrix is used where no SpMV lowering applies. O(n·m) memory:
        meant for modest shapes; keep giant graphs on matvec/matmat."""
        from matrel_tpu.core.blockmatrix import BlockMatrix
        return BlockMatrix.from_numpy(self.to_dense(), mesh=mesh,
                                      config=config, nnz=self.nnz)

    # ------------------------------------------------- relational (σ/γ/⋈)
    # Eager, edge-list-native forms of the relational operators — the
    # scale path: a 1M×1M graph cannot take the executor's densifying
    # lowering, but filtering/aggregating its edge list is O(nnz) host
    # work. Semantics match the dense masked model exactly (0 = missing;
    # SURVEY.md §7.6), so results agree with the IR lowerings wherever
    # both are feasible.

    def coalesce(self) -> "COOMatrix":
        """Collapse duplicate coordinates additively (entry-level view).
        Relational σ/γ operate on ENTRIES, not raw edges, so they
        coalesce first; matvec/plans are additive and never need to.
        No-op (returns self) when coordinates are known-unique."""
        if self._coalesced:
            return self
        m = self.shape[1]
        keys, vals = _sum_dups(self.rows * m + self.cols, self.vals)
        out = COOMatrix.from_edges(keys // m, keys % m, vals,
                                   shape=self.shape)
        out._coalesced = True
        return out

    def select_value(self, predicate, fill: float = 0.0) -> "COOMatrix":
        """σ on ENTRY values (duplicates coalesced first — an entry's
        value is the sum of its edges, exactly the dense semantics).
        Only fill=0 keeps the result sparse; other fills would densify —
        use the dense IR path for those."""
        if fill != 0.0:
            raise ValueError("COOMatrix.select_value supports fill=0 "
                             "only (a nonzero fill densifies; use "
                             "to_block(...).select_value)")
        A = self.coalesce()
        keep = np.asarray(predicate(A.vals), bool)
        out = COOMatrix.from_edges(A.rows[keep], A.cols[keep],
                                   A.vals[keep], shape=self.shape)
        out._coalesced = True
        return out

    def select_index(self, *, rows=None, cols=None) -> "COOMatrix":
        """σ on indices: keep edges whose row/col satisfy the
        predicates (vectorised callables over index arrays)."""
        keep = np.ones(self.rows.shape, bool)
        if rows is not None:
            keep &= np.asarray(rows(self.rows), bool)
        if cols is not None:
            keep &= np.asarray(cols(self.cols), bool)
        out = COOMatrix.from_edges(self.rows[keep], self.cols[keep],
                                   self.vals[keep], shape=self.shape)
        out._coalesced = self._coalesced   # subsets stay unique
        return out

    def _axis_agg(self, axis: str, kind: str) -> np.ndarray:
        # count/avg/max/min are entry-level (γ over nonzero TUPLES):
        # duplicates must coalesce first; plain sums are additive anyway
        A = self if kind == "sum" else self.coalesce()
        ids = A.rows if axis == "row" else A.cols
        n = self.shape[0] if axis == "row" else self.shape[1]
        vals = A.vals
        nz = vals != 0
        if kind == "sum":
            out = np.bincount(ids, weights=vals,
                              minlength=n).astype(np.float32)
        elif kind == "count":
            out = np.bincount(ids[nz], minlength=n).astype(np.float32)
        elif kind == "avg":
            sv = np.bincount(ids, weights=vals, minlength=n)
            c = np.bincount(ids[nz], minlength=n)
            out = np.where(c > 0, sv / np.maximum(c, 1), 0.0)
        elif kind in ("max", "min"):
            fill = -np.inf if kind == "max" else np.inf
            out = np.full(n, fill, np.float64)
            op = np.maximum if kind == "max" else np.minimum
            op.at(out, ids[nz], vals[nz].astype(np.float64))
            out = np.where(np.isfinite(out), out, 0.0)
            # dense-lowering parity: a row/col with any MISSING entry
            # includes implicit zeros in its max/min (executor._agg runs
            # over the full logical region), so clamp toward 0 wherever
            # the axis isn't fully populated by nonzeros
            width = self.shape[1] if axis == "row" else self.shape[0]
            cnt = np.bincount(ids[nz], minlength=n)
            partial = cnt < width
            out = np.where(partial, op(out, 0.0), out)
        else:
            raise ValueError(f"unknown aggregate {kind!r}")
        return out.astype(np.float32)

    def row_sum(self) -> np.ndarray:
        """γ: per-row sums as (n, 1) — O(nnz), never densifies."""
        return self._axis_agg("row", "sum")[:, None]

    def col_sum(self) -> np.ndarray:
        return self._axis_agg("col", "sum")[None, :]

    def row_count(self) -> np.ndarray:
        return self._axis_agg("row", "count")[:, None]

    def col_count(self) -> np.ndarray:
        return self._axis_agg("col", "count")[None, :]

    def row_avg(self) -> np.ndarray:
        return self._axis_agg("row", "avg")[:, None]

    def col_avg(self) -> np.ndarray:
        return self._axis_agg("col", "avg")[None, :]

    def row_max(self) -> np.ndarray:
        return self._axis_agg("row", "max")[:, None]

    def row_min(self) -> np.ndarray:
        return self._axis_agg("row", "min")[:, None]

    def col_max(self) -> np.ndarray:
        return self._axis_agg("col", "max")[None, :]

    def col_min(self) -> np.ndarray:
        return self._axis_agg("col", "min")[None, :]

    def sum(self) -> float:
        return float(self.vals.sum())

    def norm(self, kind: str = "fro") -> float:
        """Matrix norm over ENTRIES (duplicates coalesced first —
        absent entries are 0 and contribute nothing to any of these)."""
        v = self.coalesce().vals.astype(np.float64)
        if kind == "fro":
            return float(np.sqrt((v * v).sum()))
        if kind == "l1":
            return float(np.abs(v).sum())
        if kind == "max":
            return float(np.abs(v).max()) if v.size else 0.0
        raise ValueError(f"unknown norm kind {kind!r} "
                         "(expected 'fro', 'l1', or 'max')")

    def trace(self) -> float:
        d = self.rows == self.cols
        return float(self.vals[d].sum())

    def join_on_index(self, other: "COOMatrix", merge) -> "COOMatrix":
        """⋈ on index equality: C[i,j] = merge(A[i,j], B[i,j]) over the
        UNION of both coordinate sets (absent entries read 0, the masked
        semantics). merge must be a vectorised callable; exact zeros in
        the merged result are dropped from the edge list."""
        if tuple(self.shape) != tuple(other.shape):
            raise ValueError(f"join_on_index shape mismatch: "
                             f"{self.shape} vs {other.shape}")
        if float(merge(np.float32(0.0), np.float32(0.0))) != 0.0:
            raise ValueError(
                "merge(0, 0) != 0: the result is dense (every absent "
                "coordinate becomes nonzero) — use the dense IR "
                "join_on_index for such merges")
        m = self.shape[1]
        ka = self.rows * m + self.cols
        kb = other.rows * m + other.cols
        # duplicate coordinates are additive (from_edges semantics)
        ka_u, va = _sum_dups(ka, self.vals)
        kb_u, vb = _sum_dups(kb, other.vals)
        union = np.union1d(ka_u, kb_u)
        a_full = np.zeros(union.shape, np.float32)
        b_full = np.zeros(union.shape, np.float32)
        a_full[np.searchsorted(union, ka_u)] = va
        b_full[np.searchsorted(union, kb_u)] = vb
        merged = np.asarray(merge(a_full, b_full), np.float32)
        nz = merged != 0
        out = COOMatrix.from_edges(union[nz] // m, union[nz] % m,
                                   merged[nz], shape=self.shape)
        out._coalesced = True
        return out

    def join_on_value(self, other: "COOMatrix", merge="mul",
                      predicate="eq", max_pairs: int = 1 << 22):
        """⋈ on values over NONZERO entry tuples — the edge-list-native
        value join (the dense IR's pair matrix ranges over ALL logical
        entries; here only stored nonzeros join, the relational
        entry-tuple semantics of the reference's sparse value joins).

        predicate: "eq"/"lt"/"le"/"gt"/"ge" (sort-based matching,
        O((na+nb)·log nb) before materialising pairs) or a vectorised
        callable over (va, vb) (brute-force, capped). merge: one of
        "left"/"right"/"add"/"mul" or a vectorised callable.

        Returns matched pairs as a tuple of numpy arrays
        ``(ia, ja, ib, jb, value)`` — A-coordinates, B-coordinates,
        merged value per pair. Refuses to materialise more than
        ``max_pairs`` pairs with a clear error.
        """
        A = self.coalesce()
        B = other.coalesce()
        # zero-valued entries (duplicate cancellation) are ABSENT under
        # the masked entry semantics — they never join
        nza = A.vals != 0
        nzb = B.vals != 0
        a_rows, a_cols = A.rows[nza], A.cols[nza]
        b_rows, b_cols = B.rows[nzb], B.cols[nzb]
        va = A.vals[nza].astype(np.float32)
        vb = B.vals[nzb].astype(np.float32)
        merge_np = {"left": lambda x, y: x, "right": lambda x, y: y,
                    "add": np.add, "mul": np.multiply}.get(merge, merge)
        if not callable(merge_np):
            raise ValueError(f"unknown merge {merge!r}")
        if callable(predicate):
            if va.size * vb.size > max_pairs:
                raise ValueError(
                    f"callable-predicate value join must enumerate "
                    f"{va.size}x{vb.size} pairs (> max_pairs = "
                    f"{max_pairs}); use a structured predicate "
                    f"('eq'/'lt'/'le'/'gt'/'ge') or raise max_pairs")
            mask = np.asarray(predicate(va[:, None], vb[None, :]), bool)
            pa, pb = np.nonzero(mask)
        else:
            # shared predicate→range semantics (incl. IEEE NaN
            # handling) with the streaming executor path
            from matrel_tpu.relational.value_join import match_range
            order = np.argsort(vb, kind="stable")   # NaNs sort last
            sv = vb[order]
            lo, hi = match_range(sv, va, predicate, xp=np)
            cnt = hi - lo
            total = int(cnt.sum())
            if total > max_pairs:
                raise ValueError(
                    f"value join matches {total} pairs (> max_pairs = "
                    f"{max_pairs}); tighten the predicate or raise "
                    f"max_pairs")
            pa = np.repeat(np.arange(va.size), cnt)
            # pair k of entry i maps to sorted-B slot lo[i] + offset
            offs = np.arange(total) - np.repeat(
                np.cumsum(cnt) - cnt, cnt)
            pb = order[np.repeat(lo, cnt) + offs]
        vals = np.asarray(merge_np(va[pa], vb[pb]), np.float32)
        return (a_rows[pa], a_cols[pa], b_rows[pb], b_cols[pb], vals)

    # ------------------------------------------------------------ DSL
    def expr(self):
        """Enter the lazy IR as an element-sparse leaf: matmuls against
        narrow dense operands lower to the one-hot SpMV plan; other uses
        densify (see executor)."""
        from matrel_tpu.ir import expr as E
        return E.MatExpr("coo_leaf", (), tuple(self.shape),
                         min(self.nnz, self.shape[0] * self.shape[1]),
                         {"matrix": self})

    def multiply(self, other):
        from matrel_tpu.ir import expr as E
        return E.matmul(self.expr(), E.as_expr(other))


def _sum_dups(keys: np.ndarray, vals: np.ndarray):
    """Collapse duplicate coordinates additively: unique keys + summed
    values (host, O(nnz log nnz))."""
    if keys.size == 0:
        return keys, vals.astype(np.float32)
    uniq, inv = np.unique(keys, return_inverse=True)
    return uniq, np.bincount(inv, weights=vals,
                             minlength=uniq.size).astype(np.float32)
