"""BlockSparseMatrix — block-granular sparse matrices (SURVEY.md §7.7).

Reference semantics: MatRel stores sparse blocks as MLlib CSC matrices
inside the same (rowBlk, colBlk, matrix) records, and its cost model is
sparsity-aware (SURVEY.md §2 "Local matrix kernels", "Statistics").

TPU-native redesign: element-granular CSC is hostile to the MXU; the
idiomatic unit is the BLOCK. A BlockSparseMatrix keeps only nonzero
``block_size × block_size`` tiles, as a dense stack:

    blocks:     f32/bf16 [nnzb, bs, bs]   — the tile payloads
    block_rows: int32 [nnzb]              — tile row index  (sorted)
    block_cols: int32 [nnzb]              — tile col index

SpMM against a dense BlockMatrix runs as gather → batched MXU matmul →
segment-sum (ops/spmm.py), or the Pallas scalar-prefetch kernel
(ops/pallas_spmm.py) on TPU. Element-level sparsity inside a kept tile is
simply stored as zeros — the MXU multiplies them at full speed, which beats
any gather-based element skipping until density drops far below what the
reference's workloads use (1%, clustered).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core import mesh as mesh_lib

Array = jax.Array


@dataclasses.dataclass
class BlockSparseMatrix:
    """Block-sparse matrix with dense tile payloads.

    Tiles are replicated across the mesh (the broadcast operand of a
    BMM-style SpMM); the dense operand carries the sharding.
    """

    blocks: Array        # [nnzb, bs, bs]
    block_rows: Array    # [nnzb] int32, sorted (row-major order)
    block_cols: Array    # [nnzb] int32
    shape: Tuple[int, int]
    block_size: int
    mesh: Mesh

    @property
    def nnzb(self) -> int:
        return self.blocks.shape[0]

    @property
    def grid(self) -> Tuple[int, int]:
        bs = self.block_size
        return (math.ceil(self.shape[0] / bs), math.ceil(self.shape[1] / bs))

    @property
    def nnz(self) -> int:
        """Upper-bound structural nnz (block granular)."""
        return self.nnzb * self.block_size * self.block_size

    @property
    def density(self) -> float:
        gr, gc = self.grid
        return self.nnzb / (gr * gc) if gr * gc else 0.0

    @property
    def dtype(self):
        return self.blocks.dtype

    # -- construction -------------------------------------------------------

    @classmethod
    def from_numpy(cls, arr: np.ndarray, block_size: Optional[int] = None,
                   mesh: Optional[Mesh] = None,
                   config: Optional[MatrelConfig] = None,
                   dtype: Any = None) -> "BlockSparseMatrix":
        """Keep only tiles containing at least one nonzero."""
        cfg = config or default_config()
        bs = block_size or cfg.block_size
        mesh = mesh or mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
        dtype = dtype or cfg.default_dtype
        n, m = arr.shape
        gr, gc = math.ceil(n / bs), math.ceil(m / bs)
        padded = np.zeros((gr * bs, gc * bs), dtype=dtype)
        padded[:n, :m] = arr
        tiles = padded.reshape(gr, bs, gc, bs).transpose(0, 2, 1, 3)
        nz = np.argwhere(np.abs(tiles).sum(axis=(2, 3)) > 0)
        if len(nz) == 0:
            nz = np.zeros((1, 2), dtype=np.int64)  # keep one zero tile
        order = np.lexsort((nz[:, 1], nz[:, 0]))   # row-major sort
        nz = nz[order]
        payload = tiles[nz[:, 0], nz[:, 1]]
        rep = NamedSharding(mesh, P())
        return cls(
            blocks=jax.device_put(payload.astype(dtype), rep),
            block_rows=jax.device_put(nz[:, 0].astype(np.int32), rep),
            block_cols=jax.device_put(nz[:, 1].astype(np.int32), rep),
            shape=(n, m), block_size=bs, mesh=mesh,
        )

    @classmethod
    def from_scipy(cls, sp, block_size: Optional[int] = None,
                   mesh: Optional[Mesh] = None,
                   config: Optional[MatrelConfig] = None,
                   dtype: Any = None) -> "BlockSparseMatrix":
        """From a scipy.sparse matrix (the CSC-block ingestion path of the
        reference, SURVEY.md §2 'Local matrix kernels'): element-sparse
        input is bucketed into block-granular payloads WITHOUT densifying
        the full matrix — only touched tiles are materialised."""
        coo = sp.tocoo()
        return cls.from_coo_arrays(coo.row, coo.col, coo.data, coo.shape,
                                   block_size=block_size, mesh=mesh,
                                   config=config, dtype=dtype)

    @classmethod
    def from_coo_arrays(cls, rows, cols, vals, shape: Tuple[int, int],
                        block_size: Optional[int] = None,
                        mesh: Optional[Mesh] = None,
                        config: Optional[MatrelConfig] = None,
                        dtype: Any = None) -> "BlockSparseMatrix":
        """From raw COO coordinate arrays — the shared bucketing core of
        ``from_scipy`` and the executor's COOMatrix→block-sparse
        conversion for the SpGEMM dispatch (ops/spgemm.py): only touched
        tiles are materialised, the full matrix never is. Duplicate
        coordinates accumulate (scipy COO semantics)."""
        cfg = config or default_config()
        bs = block_size or cfg.block_size
        mesh = mesh or mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
        dtype = dtype or cfg.default_dtype
        rows = np.asarray(rows, np.int64).ravel()
        cols = np.asarray(cols, np.int64).ravel()
        vals = np.asarray(vals).ravel()
        n, m = shape
        gc = math.ceil(m / bs)
        bi = rows // bs
        bj = cols // bs
        keys = bi * gc + bj
        uniq, tile_idx = np.unique(keys, return_inverse=True)
        payload = np.zeros((max(len(uniq), 1), bs, bs), dtype=dtype)
        np.add.at(payload,
                  (tile_idx.ravel(), rows % bs, cols % bs),
                  vals.astype(payload.dtype))
        trows = (uniq // gc).astype(np.int32)
        tcols = (uniq % gc).astype(np.int32)
        if len(uniq) == 0:
            trows = np.zeros(1, np.int32)
            tcols = np.zeros(1, np.int32)
        rep = NamedSharding(mesh, P())
        return cls(blocks=jax.device_put(payload, rep),
                   block_rows=jax.device_put(trows, rep),
                   block_cols=jax.device_put(tcols, rep),
                   shape=(int(n), int(m)), block_size=bs, mesh=mesh)

    @classmethod
    def random(cls, shape: Tuple[int, int], block_density: float,
               block_size: Optional[int] = None, mesh: Optional[Mesh] = None,
               seed: int = 0, config: Optional[MatrelConfig] = None,
               dtype: Any = None) -> "BlockSparseMatrix":
        """Random block-sparse matrix: a uniform sample of nonzero tiles
        filled with uniform values — the BASELINE row-4 generator, built
        device-side per tile (host only materialises indices)."""
        cfg = config or default_config()
        bs = block_size or cfg.block_size
        mesh = mesh or mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
        dtype = dtype or cfg.default_dtype
        n, m = shape
        gr, gc = math.ceil(n / bs), math.ceil(m / bs)
        rng = np.random.default_rng(seed)
        total = gr * gc
        nnzb = max(1, int(round(total * block_density)))
        flat = rng.choice(total, size=nnzb, replace=False)
        flat.sort()
        rows, cols = (flat // gc).astype(np.int32), (flat % gc).astype(np.int32)
        rep = NamedSharding(mesh, P())

        @jax.jit  # matlint: disable=ML010 construction-time helper — arrays are born here, before any plan exists
        def gen():
            vals = jax.random.uniform(
                jax.random.PRNGKey(seed), (nnzb, bs, bs), dtype=jnp.float32)
            return jax.lax.with_sharding_constraint(vals.astype(dtype), rep)

        return cls(blocks=gen(),
                   block_rows=jax.device_put(rows, rep),
                   block_cols=jax.device_put(cols, rep),
                   shape=shape, block_size=bs, mesh=mesh)

    # -- materialisation ----------------------------------------------------

    def to_numpy(self) -> np.ndarray:
        gr, gc = self.grid
        bs = self.block_size
        out = np.zeros((gr * bs, gc * bs), dtype=self.blocks.dtype)
        br = np.asarray(self.block_rows)
        bc = np.asarray(self.block_cols)
        blocks = np.asarray(self.blocks)
        for i in range(self.nnzb):
            out[br[i] * bs:(br[i] + 1) * bs, bc[i] * bs:(bc[i] + 1) * bs] = blocks[i]
        return out[: self.shape[0], : self.shape[1]]

    def to_dense(self, config: Optional[MatrelConfig] = None):
        """Scatter tiles into a dense BlockMatrix (device-side)."""
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.core import padding
        cfg = config or default_config()
        gr, gc = self.grid
        bs = self.block_size
        pshape = padding.padded_shape(self.shape, self.mesh)
        sharding = padding.canonical_sharding(pshape, self.mesh)

        @jax.jit  # matlint: disable=ML010 construction-time helper — arrays are born here, before any plan exists
        def scatter(blocks, br, bc):
            full = jnp.zeros((gr, gc, bs, bs), dtype=blocks.dtype)
            full = full.at[br, bc].set(blocks)
            dense = full.transpose(0, 2, 1, 3).reshape(gr * bs, gc * bs)
            dense = dense[: pshape[0], : pshape[1]]
            if dense.shape != pshape:
                dense = jnp.pad(dense, ((0, pshape[0] - dense.shape[0]),
                                        (0, pshape[1] - dense.shape[1])))
            # zero anything outside the logical region
            r = jnp.arange(pshape[0])[:, None] < self.shape[0]
            c = jnp.arange(pshape[1])[None, :] < self.shape[1]
            dense = jnp.where(r & c, dense, 0)
            return jax.lax.with_sharding_constraint(dense, sharding)

        data = scatter(self.blocks, self.block_rows, self.block_cols)
        return BlockMatrix.from_array(
            data, self.shape, self.mesh,
            padding.canonical_spec(pshape, self.mesh),
            nnz=min(self.nnz, self.shape[0] * self.shape[1]),
            block_size=bs)

    def transpose(self) -> "BlockSparseMatrix":
        """Sᵀ: swap tile coordinates and transpose payloads (one device op);
        re-sorted row-major to keep the kernel invariants."""
        rows = np.asarray(self.block_cols)
        cols = np.asarray(self.block_rows)
        order = np.lexsort((cols, rows))
        rep = NamedSharding(self.mesh, P())
        blocks_t = jax.jit(  # matlint: disable=ML010 construction-time helper — arrays are born here, before any plan exists
            lambda b: jax.lax.with_sharding_constraint(
                jnp.transpose(b, (0, 2, 1))[jnp.asarray(order)], rep)
        )(self.blocks)
        return BlockSparseMatrix(
            blocks=blocks_t,
            block_rows=jax.device_put(rows[order].astype(np.int32), rep),
            block_cols=jax.device_put(cols[order].astype(np.int32), rep),
            shape=(self.shape[1], self.shape[0]),
            block_size=self.block_size, mesh=self.mesh)

    def norm(self, kind: str = "fro") -> float:
        """Matrix norm from the tile stack (tiles are unique by
        construction; zeros outside kept tiles contribute nothing)."""
        # float64 like the COO sibling: f32 squaring overflows at
        # ~1.8e19 magnitudes and f32 sums drift on large stacks
        b = np.asarray(self.blocks, np.float64)
        if kind == "fro":
            return float(np.sqrt((b * b).sum()))
        if kind == "l1":
            return float(np.abs(b).sum())
        if kind == "max":
            return float(np.abs(b).max()) if self.nnzb else 0.0
        raise ValueError(f"unknown norm kind {kind!r} "
                         "(expected 'fro', 'l1', or 'max')")

    def shard(self, mesh: Optional[Mesh] = None):
        """Distribute the tile stack over a mesh (each device holds
        ~nnzb/P tiles in its output row range) — the scale-out SpMM
        plan; see ops/spmm_sharded.py."""
        from matrel_tpu.ops.spmm_sharded import shard_block_sparse
        return shard_block_sparse(self, mesh)

    # -- lazy DSL -----------------------------------------------------------

    def expr(self):
        from matrel_tpu.ir import expr as E
        return E.MatExpr("sparse_leaf", (), tuple(self.shape),
                         min(self.nnz, self.shape[0] * self.shape[1]),
                         {"matrix": self})

    def multiply(self, other):
        from matrel_tpu.ir import expr as E
        return E.matmul(self.expr(), E.as_expr(other))

    def __repr__(self):
        return (f"BlockSparseMatrix(shape={self.shape}, bs={self.block_size}, "
                f"nnzb={self.nnzb}/{self.grid[0] * self.grid[1]})")
