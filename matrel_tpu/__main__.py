"""CLI: python -m matrel_tpu <command>

Commands:
  info                  device/mesh/config summary
  bench                 headline benchmark (one JSON line)
  serve [--port P]      run the JSON-RPC bridge server
  sql "<query>" [--table name=path.npy ...]   one-shot SQL query
  autotune N [K M]      time every matmul strategy for the given dims
  pagerank PATH         PageRank over a .mtx adjacency or src,dst CSV
  history [--last N] [--summary] [--drift] [--log PATH]
                        aggregate a query event log (the history-server
                        analogue; log written when MATREL_OBS_LEVEL=on);
                        --drift runs the cost-model drift auditor
                        (obs/drift.py) over the same log
  trace --export chrome [--log PATH] [--out PATH] [--last N]
                        render the log's tracing spans as a
                        Chrome/Perfetto trace_event JSON (load in
                        https://ui.perfetto.dev)
  top [--url U | --port P | --log PATH] [--interval S] [--once]
                        live operator console: per-tenant QPS /
                        p50/p95/p99 / goodput / shed rate / SLO burn
                        rate + active alerts, polling a session's
                        metrics endpoint (config.obs_metrics_port) or
                        tailing an event log
  why [--last N] [--key K] [--log PATH]
                        render served answers' lineage trees from the
                        event log's ``provenance`` records (written
                        when config.obs_provenance > 0); --audit
                        replays a sampled workload's lineages fresh
                        (cache bypassed) and proves each served
                        answer bit-equal / within its stamped
                        err_bound — the audit-replay CI gate with
                        --check
"""

from __future__ import annotations

import argparse
import json



def cmd_info(args):
    import jax
    from matrel_tpu.config import default_config
    from matrel_tpu.core import mesh as mesh_lib
    cfg = default_config()
    mesh = mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
    print(json.dumps({
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "config": {f: getattr(cfg, f) for f in (
            "block_size", "broadcast_threshold_bytes", "strategy_override",
            "matmul_precision", "use_pallas", "chain_opt")},
    }, indent=2))


def cmd_bench(args):
    import bench
    bench.main()


def cmd_serve(args):
    from matrel_tpu.bridge import BridgeServer
    srv = BridgeServer(port=args.port)
    print(f"matrel_tpu bridge listening on 127.0.0.1:{srv.port}", flush=True)
    srv.serve_forever()


def cmd_sql(args):
    import numpy as np
    from matrel_tpu.session import MatrelSession
    sess = MatrelSession.builder().get_or_create()
    for spec in args.table or []:
        name, path = spec.split("=", 1)
        sess.register(name, sess.from_numpy(np.load(path)))
    if getattr(args, "explain", False):
        print(sess.explain_sql(args.query))
        return
    out = sess.compute(sess.sql(args.query))
    np.set_printoptions(precision=5, suppress=True, threshold=200)
    print(out.to_numpy())


def cmd_autotune(args):
    from matrel_tpu.parallel.autotune import autotune_matmul
    n = args.n
    k = args.k or n
    m = args.m or n
    best, table = autotune_matmul(n, k, m)
    print(json.dumps({"best": best,
                      "seconds": {s: round(t, 6) for s, t in table.items()}},
                     indent=2))


def cmd_history(args):
    import sys
    from matrel_tpu.obs import history
    sys.exit(history.main(args))


def cmd_trace(args):
    import sys
    from matrel_tpu.obs import trace
    sys.exit(trace.main(args))


def cmd_top(args):
    import sys
    from matrel_tpu.obs import top
    sys.exit(top.main(args))


def cmd_why(args):
    import sys
    from matrel_tpu.obs import provenance
    sys.exit(provenance.main(args))


def cmd_pagerank(args):
    import numpy as np
    from matrel_tpu import io as mio
    from matrel_tpu.workloads.pagerank import pagerank_edges
    if args.path.endswith(".mtx"):
        A = mio.load_mtx_coo(args.path)
        src, dst, w, n = A.rows, A.cols, A.vals, max(A.shape)
    else:  # 'src,dst[,w]' CSV / edge list (weight defaults to 1)
        src, dst, w = mio.read_edges_csv(args.path)
        n = int(max(src.max(), dst.max())) + 1
    if np.all(w == 1.0):
        w = None                      # unweighted fast path
    ranks = np.asarray(pagerank_edges(src, dst, int(n), rounds=args.rounds,
                                      alpha=args.alpha, weights=w))
    top = np.argsort(ranks)[::-1][:args.top]
    print(json.dumps({
        "nodes": int(n), "edges": int(len(src)),
        "rounds": args.rounds,
        "top": [{"node": int(i), "rank": float(ranks[i])} for i in top],
        "rank_sum": float(ranks.sum()),
    }, indent=2))


def main(argv=None):
    import os
    if os.environ.get("JAX_PLATFORMS"):
        # the axon sitecustomize pins the platform at interpreter start;
        # honour an explicit JAX_PLATFORMS request via the config API,
        # which still works after that (see tests/conftest.py)
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    p = argparse.ArgumentParser(prog="matrel_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("info").set_defaults(fn=cmd_info)
    sub.add_parser("bench").set_defaults(fn=cmd_bench)
    sp = sub.add_parser("serve")
    sp.add_argument("--port", type=int, default=8765)
    sp.set_defaults(fn=cmd_serve)
    sq = sub.add_parser("sql")
    sq.add_argument("query")
    sq.add_argument("--table", action="append")
    sq.add_argument("--explain", action="store_true",
                    help="print the logical + optimized plan instead "
                         "of executing")
    sq.set_defaults(fn=cmd_sql)
    sa = sub.add_parser("autotune")
    sa.add_argument("n", type=int)
    sa.add_argument("k", type=int, nargs="?")
    sa.add_argument("m", type=int, nargs="?")
    sa.set_defaults(fn=cmd_autotune)
    hi = sub.add_parser("history")
    hi.add_argument("--last", type=int, default=None,
                    help="show only the most recent N query records")
    hi.add_argument("--summary", action="store_true",
                    help="per-strategy / cache roll-up instead of the "
                         "per-query table")
    hi.add_argument("--log", default=None,
                    help="event-log path (default: the obs default, "
                         ".matrel_events.jsonl)")
    hi.add_argument("--drift", action="store_true",
                    help="cost-model drift audit: estimated vs "
                         "measured calibration per strategy/shape "
                         "class/backend, rank-order flags, persisted "
                         "table update")
    hi.add_argument("--drift-table", default=None,
                    help="calibration-table path (default: "
                         "config.drift_table_path, else "
                         ".matrel_drift.json)")
    hi.add_argument("--coeffs", action="store_true",
                    help="cost-model loop view: planner decisions by "
                         "cost source, coefficient epoch, and every "
                         "rank-order flag paired with whether a "
                         "re-plan round actioned it")
    hi.add_argument("--no-save", action="store_true",
                    help="with --drift: report only, don't update the "
                         "persisted calibration table")
    hi.add_argument("--check", action="store_true",
                    help="with --drift: exit nonzero when any DRIFT "
                         "rank-order flag fires; with --summary: exit "
                         "nonzero on any UN-CLEARED SLO alert; with "
                         "--coeffs: exit nonzero on a firing but "
                         "UNACTIONED flag — the CI/make obs-report "
                         "gates")
    hi.set_defaults(fn=cmd_history)
    tp = sub.add_parser("top")
    tp.add_argument("--url", default=None,
                    help="metrics-endpoint base URL "
                         "(http://127.0.0.1:<obs_metrics_port>)")
    tp.add_argument("--port", type=int, default=None,
                    help="shorthand for --url http://127.0.0.1:PORT")
    tp.add_argument("--log", default=None,
                    help="event-log path to tail instead of polling "
                         "an endpoint (same resolution as history)")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="refresh interval in seconds (default 2)")
    tp.add_argument("--once", action="store_true",
                    help="render one frame and exit (scripting/tests)")
    tp.add_argument("--iterations", type=int, default=None,
                    help="stop after N frames (default: run until "
                         "interrupted)")
    tp.set_defaults(fn=cmd_top)
    tr = sub.add_parser("trace")
    tr.add_argument("--export", default="chrome",
                    help="output format (chrome: trace_event JSON for "
                         "Perfetto / chrome://tracing)")
    tr.add_argument("--log", default=None,
                    help="event-log path (same resolution as history)")
    tr.add_argument("--out", default=None,
                    help="output path (default: <log>.chrome.json; "
                         "'-' for stdout)")
    tr.add_argument("--last", type=int, default=None,
                    help="keep only the last N root spans (+ their "
                         "descendants)")
    tr.set_defaults(fn=cmd_trace)
    wy = sub.add_parser("why")
    wy.add_argument("--last", type=int, default=10,
                    help="show only the most recent N lineage records")
    wy.add_argument("--key", default=None,
                    help="filter by cache-key / key-hash substring or "
                         "exact ledger query id")
    wy.add_argument("--log", default=None,
                    help="event-log path (same resolution as history)")
    wy.add_argument("--audit", action="store_true",
                    help="audit replay: run the built-in serve "
                         "workload (cache hits, an interior hit, an "
                         "IVM-patched serve), then re-execute sampled "
                         "lineages fresh and compare against the "
                         "served answers")
    wy.add_argument("--sample", type=int, default=8,
                    help="with --audit: number of lineages to replay "
                         "(default 8)")
    wy.add_argument("--check", action="store_true",
                    help="with --audit: exit nonzero when any replay "
                         "disagrees — the CI/make obs-report gate")
    wy.set_defaults(fn=cmd_why)
    pr = sub.add_parser("pagerank")
    pr.add_argument("path", help=".mtx adjacency or 'src,dst' CSV edges")
    pr.add_argument("--rounds", type=int, default=30)
    pr.add_argument("--alpha", type=float, default=0.85)
    pr.add_argument("--top", type=int, default=10)
    pr.set_defaults(fn=cmd_pagerank)
    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
