"""Sliding-window streaming graph — the IVM proving workload
(ROADMAP item 5's open workload-zoo half; docs/IVM.md).

A production graph dashboard re-runs a fixed query set over an
adjacency that changes a little per tick: a batch of edges arrives,
the batch that entered ``window`` ticks ago expires. Composed here
entirely from the engine's int paths: the adjacency is a dense
INTEGRAL BlockMatrix (0/1 entries), the dashboard queries are the
triangle-count / label-propagation family (trace(A³), A·L label
counts, A·A common neighbors, degrees, A·F feature products), and
each tick's change is one ``session.register_delta`` COO batch
(+1 per arrival, −1 per expiry, symmetrized) — so every repeat
answers from the delta-patched result cache instead of recomputing,
and the integer queries patch EXACTLY (err bound 0).

The edge batches are CONSTANT-CAPACITY (zero-padded slots): every
tick's delta shares one signature, so the delta plane re-runs its
compiled patch plans with rebound factors — the steady-state path
``bench.py --stream`` measures.

``pagerank()`` is the iterative member: ranks are maintained by
warm-restarting the power iteration from the cached vector
(ir/delta.pagerank_warm_restart) instead of a cold uniform start.

A numpy mirror of the adjacency rides along as the oracle — the
``tools/soak.py stream`` battery checks every patched answer against
it (int queries bit-exactly) every tick.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from matrel_tpu.ir import delta as delta_lib


class EdgeStream:
    """Seeded sliding-window undirected edge stream over ``n`` nodes:
    each ``step()`` yields (arrivals, expiries) as (k, 2) index arrays
    with i < j, arrivals disjoint from the live edge set, expiries the
    batch that arrived ``window`` steps ago (empty until the window
    fills)."""

    def __init__(self, n: int, batch_edges: int = 32, window: int = 8,
                 seed: int = 0):
        if n < 4 or batch_edges < 1 or window < 1:
            raise ValueError("EdgeStream needs n >= 4, "
                             "batch_edges >= 1, window >= 1")
        self.n = n
        self.batch_edges = batch_edges
        self.window = window
        self._rng = np.random.default_rng(seed)
        self._live: set = set()
        self._batches: list = []

    def step(self) -> Tuple[np.ndarray, np.ndarray]:
        adds = []
        tries = 0
        while len(adds) < self.batch_edges and tries < 100 * self.batch_edges:
            tries += 1
            i = int(self._rng.integers(0, self.n))
            j = int(self._rng.integers(0, self.n))
            if i == j:
                continue
            e = (min(i, j), max(i, j))
            if e in self._live:
                continue
            self._live.add(e)
            adds.append(e)
        expires: list = []
        self._batches.append(list(adds))
        if len(self._batches) > self.window:
            expires = self._batches.pop(0)
            for e in expires:
                self._live.discard(e)
        return (np.asarray(adds, np.int64).reshape(-1, 2),
                np.asarray(expires, np.int64).reshape(-1, 2))


def _delta_arrays(adds: np.ndarray, expires: np.ndarray,
                  capacity: int) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]:
    """One symmetric COO batch (+1 arrivals, −1 expiries), padded to a
    FIXED capacity with zero-valued (0,0) slots — constant capacity
    means one delta signature per stream, so the plane's patch plans
    rebind instead of recompiling every tick."""
    rows: list = []
    cols: list = []
    vals: list = []
    for (i, j) in adds:
        rows += [i, j]
        cols += [j, i]
        vals += [1.0, 1.0]
    for (i, j) in expires:
        rows += [i, j]
        cols += [j, i]
        vals += [-1.0, -1.0]
    if len(rows) > capacity:
        raise ValueError(f"delta batch {len(rows)} exceeds fixed "
                         f"capacity {capacity}")
    pad = capacity - len(rows)
    rows += [0] * pad
    cols += [0] * pad
    vals += [0.0] * pad
    return (np.asarray(rows, np.int64), np.asarray(cols, np.int64),
            np.asarray(vals, np.float32))


class StreamingGraph:
    """The dashboard: a session-bound streaming adjacency plus the
    fixed query set and its numpy oracle (see module docstring)."""

    def __init__(self, sess, n: int, batch_edges: int = 32,
                 window: int = 8, feature_k: int = 32,
                 n_labels: int = 8, seed: int = 0, name: str = "A"):
        self.sess = sess
        self.n = n
        self.name = name
        self.stream = EdgeStream(n, batch_edges, window, seed)
        #: fixed per-tick delta capacity: 2 slots per arrival + 2 per
        #: expiry (symmetrized), zero-padded
        self.capacity = 4 * batch_edges
        rng = np.random.default_rng(seed + 1)
        self.adj = np.zeros((n, n), np.float32)       # the oracle
        # warm the window so the first measured ticks already expire
        for _ in range(window):
            adds, expires = self.stream.step()
            self._apply_host(adds, expires)
        feats = rng.random((n, feature_k), dtype=np.float32)
        labels = rng.integers(0, n_labels, n)
        onehot = np.zeros((n, n_labels), np.float32)
        onehot[np.arange(n), labels] = 1.0
        sess.register(name, sess.from_numpy(self.adj, integral=True))
        sess.register(name + "_feats", sess.from_numpy(feats))
        sess.register(name + "_labels",
                      sess.from_numpy(onehot, integral=True))
        self.feats = feats
        self.onehot = onehot
        self._pr: Optional[np.ndarray] = None

    # -- queries (the dashboard set; rebuilt per tick like a client) --------

    def queries(self) -> Dict[str, object]:
        s = self.sess
        a = s.table(self.name).expr()
        a2 = s.table(self.name).expr()
        a3 = s.table(self.name).expr()
        return {
            "degrees": a.row_sum(),
            "feature_product": a.multiply(
                s.table(self.name + "_feats").expr()),
            "label_counts": a.multiply(
                s.table(self.name + "_labels").expr()),
            "common_neighbors": a.multiply(a2),
            "triangles6": a.multiply(a2).multiply(a3).trace(),
        }

    def run_all(self) -> Dict[str, np.ndarray]:
        return {k: self.sess.run(q).to_numpy()
                for k, q in self.queries().items()}

    def oracle(self) -> Dict[str, np.ndarray]:
        A = self.adj
        return {
            "degrees": A.sum(axis=1, keepdims=True),
            "feature_product": A @ self.feats,
            "label_counts": A @ self.onehot,
            "common_neighbors": A @ A,
            "triangles6": np.trace(A @ A @ A).reshape(1, 1),
        }

    def triangle_count(self) -> float:
        """The graph-count headline: trace(A³)/6 from the (cached,
        delta-patched) dashboard entry."""
        return float(self.sess.run(
            self.queries()["triangles6"]).to_numpy()[0, 0]) / 6.0

    # -- the stream ---------------------------------------------------------

    def _apply_host(self, adds: np.ndarray, expires: np.ndarray):
        for (i, j) in adds:
            self.adj[i, j] += 1.0
            self.adj[j, i] += 1.0
        for (i, j) in expires:
            self.adj[i, j] -= 1.0
            self.adj[j, i] -= 1.0

    def step_delta(self) -> dict:
        """One tick through the IVM plane: register the constant-
        capacity COO delta; dependent cached entries patch in place
        (docs/IVM.md). Returns register_delta's summary."""
        adds, expires = self.stream.step()
        rows, cols, vals = _delta_arrays(adds, expires, self.capacity)
        self._apply_host(adds, expires)
        return self.sess.register_delta(self.name, (rows, cols, vals),
                                        kind="coo")

    def step_rebind(self) -> dict:
        """One tick through the HISTORICAL path — a plain register()
        rebind (transitive invalidation, full recompute on the next
        run) — the control arm ``bench.py --stream`` compares
        against."""
        adds, expires = self.stream.step()
        self._apply_host(adds, expires)
        self.sess.register(
            self.name,
            self.sess.from_numpy(self.adj, integral=True))
        return {"adds": int(adds.shape[0]),
                "expires": int(expires.shape[0])}

    # -- the iterative member: PageRank warm restart ------------------------

    def pagerank(self, rounds: int = 8, cold_rounds: int = 60,
                 alpha: float = 0.85) -> np.ndarray:
        """Ranks over the CURRENT adjacency, warm-restarted from the
        previous tick's cached vector (ir/delta.pagerank_warm_restart)
        — a cold start pays ``cold_rounds``, the warm restart
        ``rounds``, and for a small per-tick delta both land on the
        same fixed point (the soak battery proves it)."""
        r0 = (self._pr if self._pr is not None
              else np.full(self.n, 1.0 / self.n))
        warm_rounds = rounds if self._pr is not None else cold_rounds
        self._pr = delta_lib.pagerank_warm_restart(
            self.adj.astype(np.float64), r0, alpha=alpha,
            rounds=warm_rounds)
        return self._pr
