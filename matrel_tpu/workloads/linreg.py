"""Normal-equations linear regression — the reference's flagship workload
(SURVEY.md §3.2, BASELINE.md row 3: tall-skinny (XᵀX)⁻¹Xᵀy, 10M×1k).

Reference execution: the DSL query X.t().multiply(X) runs as shuffle-bounded
Spark stages; the k×k Gram matrix is collected and inverted on the driver.
TPU rebuild: Gram + RHS build through the IR (so the chain optimizer sees
the whole expression), lower to ONE jitted program where the tall-skinny
product reduce-scatters over the mesh, and the tiny k×k solve runs
replicated on-device via Cholesky — no host round trip at all.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.executor import compile_exprs
from matrel_tpu.ir.expr import matmul, transpose


def normal_equations_expr(X: BlockMatrix, y: BlockMatrix):
    """The logical plan (XᵀX, Xᵀy) as IR expressions."""
    xe, ye = X.expr(), y.expr()
    return matmul(transpose(xe), xe), matmul(transpose(xe), ye)


def fit(X: BlockMatrix, y: BlockMatrix,
        l2: float = 0.0,
        config: Optional[MatrelConfig] = None) -> jax.Array:
    """Solve argmin ‖Xθ - y‖² (+ l2‖θ‖²) by normal equations.

    Returns θ as a replicated (k, 1) array. The Gram build and the solve are
    fused into one XLA program per call via plan compilation + a jitted
    solve; X may be any mesh sharding (typically row-sharded: the data-
    parallel layout for tall-skinny X).
    """
    cfg = config or default_config()
    gram_e, rhs_e = normal_equations_expr(X, y)
    gram, rhs = compile_exprs((gram_e, rhs_e), X.mesh, cfg).run()
    k = X.shape[1]

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def solve(g, r):
        gl = g[:k, :k] + l2 * jnp.eye(k, dtype=g.dtype)
        # Gram matrices are SPD (up to conditioning): Cholesky solve
        c, low = jax.scipy.linalg.cho_factor(gl)
        return jax.scipy.linalg.cho_solve((c, low), r[:k, :])

    return solve(gram.data, rhs.data)


def fit_fused(X: BlockMatrix, y: BlockMatrix, l2: float = 0.0,
              config: Optional[MatrelConfig] = None) -> jax.Array:
    """Single-program variant: Gram, RHS and solve in ONE jit — the shape
    used by the benchmarks (zero host sync between stages)."""
    cfg = config or default_config()
    k = X.shape[1]
    mesh = X.mesh
    row_spec = P((mesh.axis_names[0], mesh.axis_names[1]), None)

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def step(xd, yd):
        xs = jax.lax.with_sharding_constraint(xd, NamedSharding(mesh, row_spec))
        prec = getattr(jax.lax.Precision, cfg.matmul_precision.upper(),
                       jax.lax.Precision.HIGHEST)
        if (cfg.matmul_precision == "high"
                and xs.dtype == jnp.float32):
            # symmetric 2-pass bf16 Gram (ops/gram.py, round-3)
            from matrel_tpu.ops.gram import symmetric_gram
            gram_raw = symmetric_gram(
                xs, lambda p, q: jnp.einsum(
                    "nk,nj->kj", p, q,
                    preferred_element_type=jnp.float32))
        else:
            gram_raw = jnp.einsum("nk,nj->kj", xs, xs, precision=prec,
                                  preferred_element_type=jnp.float32)
        gram = jax.lax.with_sharding_constraint(
            gram_raw, NamedSharding(mesh, P()))
        rhs = jax.lax.with_sharding_constraint(
            jnp.einsum("nk,nj->kj", xs, yd, precision=prec,
                       preferred_element_type=jnp.float32),
            NamedSharding(mesh, P()))
        gl = gram[:k, :k] + l2 * jnp.eye(k, dtype=gram.dtype)
        c, low = jax.scipy.linalg.cho_factor(gl)
        return jax.scipy.linalg.cho_solve((c, low), rhs[:k, :])

    return step(X.data, y.data)


def fit_streaming(n_rows: int, k: int,
                  panel_fn,
                  panel_rows: int = 262_144,
                  l2: float = 0.0,
                  mesh=None,
                  dtype=None,
                  precision: str = "highest",
                  config: Optional[MatrelConfig] = None) -> jax.Array:
    """Tall-skinny normal equations when X exceeds HBM (BASELINE row 3:
    10M×1k f32 = 40 GB on a 16 GB chip).

    The Gram matrix is a sum over row panels: XᵀX = Σ_p X_pᵀX_p, so the
    loop streams panels through a ``lax.fori_loop`` — panels are produced
    on device by ``panel_fn(panel_index) -> (X_p, y_p)`` (a traceable
    generator: synthetic data, or a gather from a device-resident shard) —
    and only the k×k accumulators live across iterations. One jitted
    program, O(panel) memory, every FLOP on the MXU.

    ``precision``: MXU passes for the f32 Gram products — "highest"
    (6-pass bf16, ≈exact f32, the safe default: cond(XᵀX) = cond(X)²) or
    "high" (f32-representation-level error; fine for well-conditioned
    problems). For f32 panels, "high" uses a SYMMETRIC 2-pass split
    instead of XLA's generic bf16x3: the Gram's cross terms loᵀ·hi and
    hiᵀ·lo are transposes of each other, so HiᵀHi + HiᵀLo + (HiᵀLo)ᵀ
    reproduces the exact same three products with one MXU pass fewer —
    a 33% FLOP cut XLA cannot apply because its dot lowering does not
    know both operands are the same matrix (round-3 floor analysis,
    docs/ROUND3.md).
    """
    import math as _math
    if precision.lower() not in ("default", "high", "highest"):
        raise ValueError(f"precision must be one of 'default', 'high', "
                         f"'highest'; got {precision!r}")
    precision = precision.lower()
    cfg = config or default_config()
    mesh = mesh or _default_mesh(cfg)
    n_panels = _math.ceil(n_rows / panel_rows)
    key = (panel_fn, n_panels, k, l2, precision)
    run = _stream_cache.get(key)
    if run is None:

        @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
        def run():
            prec = getattr(jax.lax.Precision, precision.upper())

            def body(p, carry):
                gram, rhs = carry
                xp, yp = panel_fn(p)
                if precision == "high" and xp.dtype == jnp.float32:
                    # symmetric 2-pass bf16 split (see docstring);
                    # shared identity lives in ops/gram.py
                    from matrel_tpu.ops.gram import symmetric_gram
                    gram = gram + symmetric_gram(
                        xp, lambda p, q: jnp.einsum(
                            "nk,nj->kj", p, q,
                            preferred_element_type=jnp.float32))
                else:
                    gram = gram + jnp.einsum(
                        "nk,nj->kj", xp, xp, precision=prec,
                        preferred_element_type=jnp.float32)
                rhs = rhs + jnp.einsum("nk,nj->kj", xp, yp, precision=prec,
                                       preferred_element_type=jnp.float32)
                return gram, rhs

            gram0 = jnp.zeros((k, k), jnp.float32)
            rhs0 = jnp.zeros((k, 1), jnp.float32)
            gram, rhs = jax.lax.fori_loop(0, n_panels, body, (gram0, rhs0))
            gl = gram + l2 * jnp.eye(k, dtype=gram.dtype)
            c, low = jax.scipy.linalg.cho_factor(gl)
            return jax.scipy.linalg.cho_solve((c, low), rhs)

        _stream_cache[key] = run
    return run()


# jitted-program cache for fit_streaming (fresh closures would recompile
# per call; keyed on the panel generator identity + static dims)
_stream_cache: dict = {}


def _default_mesh(cfg):
    from matrel_tpu.core import mesh as mesh_lib
    return mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)


def predict(X: BlockMatrix, theta: jax.Array) -> jax.Array:
    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def f(xd, t):
        return xd @ jnp.pad(t, ((0, xd.shape[1] - t.shape[0]), (0, 0)))

    return f(X.data, theta)[: X.shape[0]]
