"""Normal-equations linear regression — the reference's flagship workload
(SURVEY.md §3.2, BASELINE.md row 3: tall-skinny (XᵀX)⁻¹Xᵀy, 10M×1k).

Reference execution: the DSL query X.t().multiply(X) runs as shuffle-bounded
Spark stages; the k×k Gram matrix is collected and inverted on the driver.
TPU rebuild: Gram + RHS build through the IR (so the chain optimizer sees
the whole expression), lower to ONE jitted program where the tall-skinny
product reduce-scatters over the mesh, and the tiny k×k solve runs
replicated on-device via Cholesky — no host round trip at all.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core import padding
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.executor import compile_expr
from matrel_tpu.ir.expr import matmul, transpose


def normal_equations_expr(X: BlockMatrix, y: BlockMatrix):
    """The logical plan (XᵀX, Xᵀy) as IR expressions."""
    xe, ye = X.expr(), y.expr()
    return matmul(transpose(xe), xe), matmul(transpose(xe), ye)


def fit(X: BlockMatrix, y: BlockMatrix,
        l2: float = 0.0,
        config: Optional[MatrelConfig] = None) -> jax.Array:
    """Solve argmin ‖Xθ - y‖² (+ l2‖θ‖²) by normal equations.

    Returns θ as a replicated (k, 1) array. The Gram build and the solve are
    fused into one XLA program per call via plan compilation + a jitted
    solve; X may be any mesh sharding (typically row-sharded: the data-
    parallel layout for tall-skinny X).
    """
    cfg = config or default_config()
    gram_e, rhs_e = normal_equations_expr(X, y)
    gram_plan = compile_expr(gram_e, X.mesh, cfg)
    rhs_plan = compile_expr(rhs_e, X.mesh, cfg)
    gram = gram_plan.run()
    rhs = rhs_plan.run()
    k = X.shape[1]

    @jax.jit
    def solve(g, r):
        gl = g[:k, :k] + l2 * jnp.eye(k, dtype=g.dtype)
        # Gram matrices are SPD (up to conditioning): Cholesky solve
        c, low = jax.scipy.linalg.cho_factor(gl)
        return jax.scipy.linalg.cho_solve((c, low), r[:k, :])

    return solve(gram.data, rhs.data)


def fit_fused(X: BlockMatrix, y: BlockMatrix, l2: float = 0.0,
              config: Optional[MatrelConfig] = None) -> jax.Array:
    """Single-program variant: Gram, RHS and solve in ONE jit — the shape
    used by the benchmarks (zero host sync between stages)."""
    cfg = config or default_config()
    k = X.shape[1]
    mesh = X.mesh
    row_spec = P((mesh.axis_names[0], mesh.axis_names[1]), None)

    @jax.jit
    def step(xd, yd):
        xs = jax.lax.with_sharding_constraint(xd, NamedSharding(mesh, row_spec))
        prec = jax.lax.Precision.HIGHEST
        gram = jax.lax.with_sharding_constraint(
            jnp.einsum("nk,nj->kj", xs, xs, precision=prec,
                       preferred_element_type=jnp.float32),
            NamedSharding(mesh, P()))
        rhs = jax.lax.with_sharding_constraint(
            jnp.einsum("nk,nj->kj", xs, yd, precision=prec,
                       preferred_element_type=jnp.float32),
            NamedSharding(mesh, P()))
        gl = gram[:k, :k] + l2 * jnp.eye(k, dtype=gram.dtype)
        c, low = jax.scipy.linalg.cho_factor(gl)
        return jax.scipy.linalg.cho_solve((c, low), rhs[:k, :])

    return step(X.data, y.data)


def predict(X: BlockMatrix, theta: jax.Array) -> jax.Array:
    @jax.jit
    def f(xd, t):
        return xd @ jnp.pad(t, ((0, xd.shape[1] - t.shape[0]), (0, 0)))

    return f(X.data, theta)[: X.shape[0]]
