"""Matrix-chain workload — the cost-based-reorder benchmark
(SURVEY.md §3.3, BASELINE.md row 2: A·B·C, 10k dims, skewed).

Builds a skewed chain through the IR so the DP reorders it, compiles to one
program, and reports which parenthesisation the optimizer chose — the
assertable "plan shape" of the reference's chain benchmarks.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.executor import CompiledPlan, compile_expr
from matrel_tpu.ir import chain as chain_lib
from matrel_tpu.ir.expr import MatExpr, matmul


def build_chain(mats: Sequence[BlockMatrix]) -> MatExpr:
    e = mats[0].expr()
    for m in mats[1:]:
        e = matmul(e, m.expr())
    return e


def parenthesisation(e: MatExpr) -> str:
    """Render the matmul tree structure, e.g. '((A·B)·C)'."""
    names = {}

    def walk(n: MatExpr) -> str:
        if n.kind == "matmul":
            return f"({walk(n.children[0])}·{walk(n.children[1])})"
        if n.kind == "leaf":
            if n.uid not in names:
                names[n.uid] = chr(ord("A") + len(names))
            return names[n.uid]
        return f"{n.kind}[{walk(n.children[0]) if n.children else ''}]"

    return walk(e)


def compile_chain(mats: Sequence[BlockMatrix],
                  config: Optional[MatrelConfig] = None
                  ) -> Tuple[CompiledPlan, str, float]:
    """Compile a chain; returns (plan, chosen parenthesisation, est cost)."""
    cfg = config or default_config()
    e = build_chain(mats)
    plan = compile_expr(e, mats[0].mesh, cfg)
    return plan, parenthesisation(plan.optimized), chain_lib.chain_cost(plan.optimized)


def skewed_abc(mesh, n: int = 10_000, mid: int = 100, seed: int = 0,
               dtype="float32") -> List[BlockMatrix]:
    """The BASELINE.md row-2 shape: A(n×mid)·B(mid×n)·C(n×mid) — the
    left-assoc order is catastrophically worse than the DP's pick."""
    A = BlockMatrix.random((n, mid), mesh=mesh, seed=seed, dtype=dtype)
    B = BlockMatrix.random((mid, n), mesh=mesh, seed=seed + 1, dtype=dtype)
    C = BlockMatrix.random((n, mid), mesh=mesh, seed=seed + 2, dtype=dtype)
    return [A, B, C]
