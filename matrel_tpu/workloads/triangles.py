"""Triangle counting — the classic graph "matrix query" workload
(SURVEY.md §1 L6 "graph/matrix queries"): the number of triangles in an
undirected graph is trace(A³)/6.

Built entirely through the framework's query surface, so it exercises
the stack the way a MatRel user would write it:
  - the IR multiply chain A·A·A goes through chain-DP (all dims equal,
    so the DP is a tie — the comm term breaks it),
  - trace(·) is the γ(sum, diag) aggregate, and R3 pushes the diagonal
    aggregate INTO the final multiply where profitable,
  - sparse adjacency enters as a BlockSparse or COO leaf and routes
    through the corresponding kernels.

Also exposed through SQL: ``trace(A * A * A)`` over a registered
adjacency table computes the same plan.
"""

from __future__ import annotations

from typing import Union

import numpy as np

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import expr as E


def triangle_count_expr(A: Union[BlockMatrix, E.MatExpr]) -> E.MatExpr:
    """trace(A·A·A) as a lazy expression; divide by 6 on the scalar
    result for the triangle count of a simple undirected graph."""
    a = E.as_expr(A)
    if a.shape[0] != a.shape[1]:
        raise ValueError(f"adjacency must be square, got {a.shape}")
    return E.agg(a.multiply(a).multiply(a), "sum", "diag")


def triangle_count(A: Union[BlockMatrix, E.MatExpr]) -> float:
    """Number of triangles in the simple undirected graph with
    0/1 symmetric adjacency ``A`` (zero diagonal)."""
    out = triangle_count_expr(A).compute().to_numpy()
    return float(out[0, 0]) / 6.0


def triangles_numpy_oracle(a: np.ndarray) -> float:
    """Dense numpy oracle for tests."""
    return float(np.trace(a @ a @ a)) / 6.0
