"""PageRank power iteration — reference workload (SURVEY.md §3.5,
BASELINE.md row 5: 1M-node adjacency, 30 matvec rounds).

Reference execution: a driver-side loop; every round is one optimized plan
execution and one Spark shuffle — the shuffle dominates. TPU rebuild: the
WHOLE loop is one jitted ``lax.fori_loop``; the matvec's psum rides ICI and
there is no host round trip between rounds (SURVEY.md §3.5 🔥 note).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

from matrel_tpu.utils import compat
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix


def pagerank(A: BlockMatrix, rounds: int = 30, alpha: float = 0.85,
             config: Optional[MatrelConfig] = None) -> jax.Array:
    """r ← α·Âᵀ·r + (1-α)/N, iterated ``rounds`` times inside one program.

    A is the (row-stochastic-normalisable) adjacency matrix: A[i, j] = 1 for
    an edge i→j. Dangling nodes (zero out-degree) redistribute uniformly.
    Returns the rank vector as a replicated (N, 1) array.
    """
    n = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError(f"adjacency must be square, got {A.shape}")
    mesh = A.mesh
    pn = A.padded_shape[0]
    out_sharding = NamedSharding(mesh, P())

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run(ad):
        valid_row = (jnp.arange(pn) < n)[:, None]
        deg = jnp.sum(ad, axis=1, keepdims=True)               # out-degree
        inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1e-30), 0.0)
        dangling = (valid_row & (deg == 0)).astype(ad.dtype)
        r0 = jnp.where(valid_row, 1.0 / n, 0.0).astype(ad.dtype)
        teleport = (1.0 - alpha) / n

        def body(_, r):
            # contribution along edges: Âᵀ·r with Â = D⁻¹A (row-normalised)
            contrib = jnp.einsum("ij,ik->jk", ad, inv_deg * r,
                                 precision=jax.lax.Precision.HIGHEST)
            # dangling mass redistributes uniformly over real nodes
            dmass = jnp.sum(dangling * r)
            r_new = alpha * (contrib + dmass / n) + teleport
            return jnp.where(valid_row, r_new, 0.0)

        r = jax.lax.fori_loop(0, rounds, body, r0)
        return jax.lax.with_sharding_constraint(r, out_sharding)

    return run(A.data)[:n]


def pagerank_edges(src: jax.Array, dst: jax.Array, n: int,
                   rounds: int = 30, alpha: float = 0.85,
                   mesh=None, impl: str = "auto",
                   weights=None, passes: int = 3) -> jax.Array:
    """PageRank over an edge list — the BASELINE row-5 scale (1M nodes).

    A dense or block-sparse 1M×1M adjacency is off the table (4 TB dense;
    uniform-random graphs touch every 512² block). The TPU-idiomatic sparse
    matvec for graphs is gather/segment-sum over the edge arrays:

        contrib[j] = Σ_{(i,j)∈E} r[i] / outdeg[i]

    Edges are device-resident int32 arrays (10M edges = 80 MB); the whole
    30-round loop is one jitted fori_loop, no host round trips. Edge arrays
    may be sharded over the mesh (segment_sum psums over ICI).
    """
    if impl not in ("auto", "segment", "onehot"):
        raise ValueError(f"unknown impl {impl!r}")
    if impl == "onehot":
        # explicit choice: any backend; with mesh= the sharded variant
        # (plan tables row-decomposed over every device)
        if not (_host_fetchable(src) and _host_fetchable(dst)):
            raise ValueError(
                "impl='onehot' builds its plan on the host; edge arrays "
                "sharded across non-addressable devices need "
                "impl='segment'")
        if mesh is not None:
            from matrel_tpu.config import pallas_enabled
            if pallas_enabled():
                out = _pagerank_compact_sharded(
                    src, dst, n, rounds, alpha, mesh, max_slots=None,
                    weights=weights, passes=passes)
            else:
                out = _pagerank_onehot_sharded(src, dst, n, rounds,
                                               alpha, mesh,
                                               max_slots=None,
                                               weights=weights)
        else:
            out = _pagerank_onehot(src, dst, n, rounds, alpha,
                                   weights=weights, passes=passes)
        if out is None:
            raise ValueError(
                "impl='onehot' requested but the graph's degree "
                "distribution is too heavy-tailed for the one-hot plan "
                "(build_spmv_plan refused); use impl='segment' or 'auto'")
        return out
    if impl == "auto":
        # The one-hot MXU matvec (ops/spmv.py) beats segment_sum ~5× on
        # TPU; on CPU the extra one-hot FLOPs lose, so auto keeps the
        # segment path there. The plan build is host-side numpy, so
        # edge arrays sharded across non-addressable (multi-host) devices
        # stay on the segment path. Falls back when the degree
        # distribution is too heavy-tailed to pad, or when the expanded
        # tables would exceed the per-device HBM budget (~224 B/slot
        # expanded, ~30 B/slot compact — _auto_max_slots picks;
        # the cap keeps auto from OOMing on huge graphs that the
        # 8 B/edge segment path handles fine).
        on_tpu = jax.default_backend() in ("tpu", "axon")
        if on_tpu and _host_fetchable(src) and _host_fetchable(dst):
            if mesh is not None:
                from matrel_tpu.config import pallas_enabled
                if pallas_enabled():
                    out = _pagerank_compact_sharded(
                        src, dst, n, rounds, alpha, mesh,
                        max_slots=_auto_max_slots() * mesh.size,
                        weights=weights, passes=passes)
                else:
                    out = _pagerank_onehot_sharded(
                        src, dst, n, rounds, alpha, mesh,
                        max_slots=_PLAN_CACHE_MAX_SLOTS * mesh.size,
                        weights=weights)
            else:
                out = _pagerank_onehot(src, dst, n, rounds, alpha,
                                       max_slots=_auto_max_slots(),
                                       weights=weights, passes=passes)
            if out is not None:
                return out
    src = jnp.asarray(src, dtype=jnp.int32)
    dst = jnp.asarray(dst, dtype=jnp.int32)
    w = (jnp.ones_like(src, dtype=jnp.float32) if weights is None
         else jnp.asarray(weights, jnp.float32))
    prepare, run = _edges_runner(int(n), int(rounds), float(alpha))
    src, dst, w = prepare(src, dst, w)
    return run(src, dst, w)


def prepare_pagerank_onehot(src, dst, n: int, max_slots: int = None,
                            weights=None):
    """Build the one-hot SpMV plan for a graph (ops/spmv.py), reusable
    across pagerank runs — plan construction is the expensive, per-graph
    step (host sort + pad, one device table expansion).

    The contribution matvec is contrib = Âᵀ·r with Â[i,j] = w_ij/outdeg_w
    [i] for each edge i→j (w ≡ 1 unweighted) — so the plan is rows=dst,
    cols=src, vals=w/outdeg_w[src]; the normalisation rides the
    gather-select table for free. Returns (plan, dangling_mask), or None
    when the plan refuses the graph (heavy-tailed padding).
    """
    from matrel_tpu.ops import spmv as spmv_lib

    src_np = np.asarray(src, dtype=np.int64)
    dst_np = np.asarray(dst, dtype=np.int64)
    if weights is None:
        w = np.ones(src_np.shape, np.float32)
    else:
        w = np.asarray(weights, dtype=np.float32)
    outdeg = np.bincount(src_np, weights=w,
                         minlength=n).astype(np.float32)
    # epsilon (not 1.0) floor: weighted out-masses below 1 must not be
    # clamped or the ranks skew (same rationale as pagerank_block_sparse)
    inv = np.where(outdeg > 0, 1.0 / np.maximum(outdeg, 1e-30), 0.0)
    plan = spmv_lib.build_spmv_plan(dst_np, src_np,
                                    vals=w * inv[src_np],
                                    n_rows=n, n_cols=n,
                                    max_slots=max_slots)
    if plan is None:
        return None
    dangling = jnp.asarray((outdeg == 0).astype(np.float32))
    return plan, dangling


def run_pagerank_onehot(prepared, rounds: int = 30,
                        alpha: float = 0.85) -> jax.Array:
    """Execute PageRank rounds over a prepared one-hot plan."""
    if prepared is None:
        raise ValueError(
            "prepare_pagerank_onehot returned None for this graph "
            "(degree distribution too heavy-tailed for the one-hot "
            "plan); use the segment-sum path instead")
    plan, dangling = prepared
    run = _onehot_runner(plan.n_rows, int(rounds), float(alpha),
                         (plan.n_rows, plan.n_cols, plan.block),
                         len(plan.arrays()))
    return run(plan.arrays(), dangling)


def run_pagerank_compact(prepared, rounds: int = 30, alpha: float = 0.85,
                         passes: int = 2,
                         interpret=None) -> jax.Array:
    """PageRank rounds over the compact-table Pallas SpMV
    (ops/pallas_spmv.py): ~14× smaller device tables than the expanded
    plan and faster on real TPU (measured 18.8 ms vs 29.4 per matvec at
    BASELINE row-5 scale). ``passes`` trades round fidelity for speed:
    2 → ~2^-16 relative error per matvec (ranking-grade), 3 → ~f32."""
    if prepared is None:
        raise ValueError(
            "prepare_pagerank_onehot returned None for this graph; "
            "use the segment-sum path instead")
    from matrel_tpu.ops import pallas_spmv as pc
    from matrel_tpu.ops import spmv as spmv_lib
    plan, dangling = prepared
    from matrel_tpu.config import resolve_interpret
    interpret = resolve_interpret(interpret)
    tables = pc.compact_tables(plan)
    ov = plan.overflow
    run = _compact_runner_loop(plan.n_rows, int(rounds), float(alpha),
                               (plan.n_rows, plan.n_cols, plan.block,
                                spmv_lib.LO),
                               len(ov), int(passes), bool(interpret))
    return run(tables, ov, dangling)


# Prepared-plan cache for the auto path: repeated pagerank_edges calls on
# the same graph (alpha/round sweeps) must not repay the host sort + table
# transfer. Keyed by a FULL content hash (blake2b runs ~1 GB/s, so a 10M-
# edge probe costs ~0.2 s against ~1 s of saved 30-round compute — and a
# sampled key would silently serve a stale plan after small graph edits).
# Callers holding device-resident edge arrays should use
# prepare_pagerank_onehot/run_pagerank_onehot directly: a cache probe
# pulls the arrays to host. Eviction is byte-aware in PER-DEVICE slots
# (expanded one-hot tables are ~224 B per padded slot — the compact
# executor's ~30 B/slot plans cost far less, so this budget is the
# conservative worst case across both executors; sharded plans
# spread theirs over mesh.size devices): pinning several multi-GB plans
# would OOM a 16 GB chip, and plans above the budget run uncached.
_PLAN_CACHE: dict = {}
_PLAN_CACHE_MAX_SLOTS = 24_000_000   # ≈5.4 GB of expanded tables/device


def _host_fetchable(a) -> bool:
    """True when np.asarray(a) is safe — numpy/lists always; jax arrays
    only when every shard is addressable from this process."""
    if isinstance(a, jax.Array):
        return a.is_fully_addressable
    return True


def _cache_get_or_insert(key, build, per_dev_slots_of):
    """Byte-aware cache: values are (prepared, per_dev_slots). ``build``
    runs on a miss (may return None = refused); oversized results are
    returned uncached."""
    hit = _PLAN_CACHE.get(key)
    if hit is not None:
        return hit[0]
    prepared = build()
    if prepared is None:
        return None
    cost = per_dev_slots_of(prepared)
    if cost <= _PLAN_CACHE_MAX_SLOTS:
        total = sum(c for _, c in _PLAN_CACHE.values())
        while _PLAN_CACHE and total + cost > _PLAN_CACHE_MAX_SLOTS:
            total -= _PLAN_CACHE.pop(next(iter(_PLAN_CACHE)))[1]
        _PLAN_CACHE[key] = (prepared, cost)
    return prepared


def _graph_fingerprint(src, dst, n: int, weights=None) -> tuple:
    import hashlib
    h = hashlib.blake2b(digest_size=16)
    sizes = []
    for a in (src, dst):
        # canonicalize to int32 (node ids fit by construction) so the
        # same graph hashes identically whatever index dtype it arrives
        # in; no copy when it already is int32
        a = np.ascontiguousarray(np.asarray(a, dtype=np.int32))
        h.update(a.tobytes())
        sizes.append(a.shape[0])
    if weights is not None:
        h.update(np.ascontiguousarray(
            np.asarray(weights, dtype=np.float32)).tobytes())
    return (n, tuple(sizes), weights is not None, h.hexdigest())


def _plan_slots(prepared) -> int:
    plan, _ = prepared
    return plan.src8.shape[0] * plan.src8.shape[1]


def _auto_max_slots() -> int:
    """Plan-size gate for the auto path: when the compact executor will
    run (~13 B/slot device-side) the budget is 8× the expanded path's
    (whose ~224 B/slot sized _PLAN_CACHE_MAX_SLOTS). Must consult the
    SAME gate as the executor choice — with use_pallas=False the
    expanded tables run, and an 8× budget would admit ~43 GB plans."""
    from matrel_tpu.config import pallas_enabled
    if pallas_enabled():
        return _PLAN_CACHE_MAX_SLOTS * 8     # ~3 GB compact + host copy
    return _PLAN_CACHE_MAX_SLOTS


def _pagerank_onehot(src, dst, n: int, rounds: int, alpha: float,
                     max_slots: int = None, weights=None,
                     passes: int = 3):
    prepared = _cache_get_or_insert(
        _graph_fingerprint(src, dst, n, weights),
        lambda: prepare_pagerank_onehot(src, dst, n, max_slots=max_slots,
                                        weights=weights),
        _plan_slots)
    if prepared is None:
        return None
    from matrel_tpu.config import pallas_enabled
    if pallas_enabled():
        # compact-table Pallas executor: faster and ~17× less HBM than
        # the expanded tables (BASELINE row 5). passes=3 (default) is
        # f32-faithful like the expanded path; callers may pass 2 for
        # ranking-grade (~2^-16 per matvec) at higher speed
        return run_pagerank_compact(prepared, rounds, alpha,
                                    passes=passes)
    return run_pagerank_onehot(prepared, rounds, alpha)


def _pagerank_compact_sharded(src, dst, n: int, rounds: int, alpha: float,
                              mesh, max_slots: int = None, weights=None,
                              passes: int = 3, interpret=None):
    """Multi-chip PageRank over mesh-sharded COMPACT tables: each device
    holds ~13 B/slot / P and generates its scatter one-hots in VMEM
    (ops/pallas_spmv.py); the whole power iteration is one shard_map'd
    program with a tiled all_gather of r per round."""
    from matrel_tpu.ops import pallas_spmv as pc
    from matrel_tpu.ops import spmv as spmv_lib

    key = _graph_fingerprint(src, dst, n, weights) + (mesh, "compact")

    def build():
        prepared = prepare_pagerank_onehot(src, dst, n,
                                           max_slots=max_slots,
                                           weights=weights)
        if prepared is None:
            return None
        pc.shard_compact_tables(prepared[0], mesh)   # place now
        return prepared

    prepared = _cache_get_or_insert(
        key, build, lambda pr_: -(-_plan_slots(pr_) // (16 * mesh.size)))
    if prepared is None:
        return None
    plan, dangling = prepared
    from matrel_tpu.config import resolve_interpret
    interpret = resolve_interpret(interpret)
    tables = pc.shard_compact_tables(plan, mesh)
    ov = plan.overflow
    run = _compact_sharded_loop(
        int(n), int(rounds), float(alpha),
        (plan.n_rows, plan.n_cols, plan.block, spmv_lib.LO),
        len(ov), int(passes), bool(interpret), mesh)
    return run(*tables, jnp.asarray(dangling), *ov)


@functools.lru_cache(maxsize=32)
def _compact_sharded_loop(n: int, rounds: int, alpha: float, plan_static,
                          n_ov: int, passes: int, interpret: bool, mesh):
    from matrel_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P
    from matrel_tpu.ops import pallas_spmv as pc
    from matrel_tpu.ops import spmv as spmv_lib

    axes = tuple(mesh.axis_names)
    in_specs = pc.compact_sharded_specs(axes, n_ov)

    def kernel(src8, lane, off, val, dangling, *ov):
        def matvec(r):
            return pc.compact_sharded_apply(
                plan_static, (src8, lane, off, val), ov, r, axes,
                passes, interpret)

        body = _power_body(matvec, n, alpha, dangling)
        r0 = _r0(n)
        pcast = getattr(jax.lax, "pcast", None)
        r0 = (pcast(r0, axes, to="varying") if pcast is not None
              else compat.pvary(r0, axes))
        return jax.lax.fori_loop(0, rounds, body, r0)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=in_specs,  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
                             out_specs=P(), check_vma=False))


def _pagerank_onehot_sharded(src, dst, n: int, rounds: int, alpha: float,
                             mesh, max_slots: int = None, weights=None):
    """Multi-chip one-hot PageRank: the whole power iteration runs inside
    ONE shard_map'd jitted program; each device owns a slice of
    destination blocks and the round ends in a tiled all_gather of r."""
    from matrel_tpu.ops import spmv as spmv_lib

    p = mesh.size
    # Mesh is hashable and identity-precise: same-shaped meshes over
    # different devices must not share cached (device-committed) plans
    key = _graph_fingerprint(src, dst, n, weights) + (mesh,)

    def build():
        prepared = prepare_pagerank_onehot(src, dst, n,
                                           max_slots=max_slots,
                                           weights=weights)
        if prepared is None:
            return None
        return (spmv_lib.shard_plan(prepared[0], mesh), prepared[1])

    prepared = _cache_get_or_insert(
        key, build, lambda pr_: -(-_plan_slots(pr_) // p))
    if prepared is None:
        return None
    plan, dangling = prepared
    run = _onehot_sharded_runner(int(n), int(rounds), float(alpha),
                                 (plan.n_rows, plan.n_cols, plan.block),
                                 len(plan.arrays()), mesh)
    return run(*plan.arrays(), dangling)


@functools.lru_cache(maxsize=32)
def _onehot_sharded_runner(n: int, rounds: int, alpha: float, plan_static,
                           n_arrays: int, mesh):
    from matrel_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P
    from matrel_tpu.ops import spmv as spmv_lib

    axes = tuple(mesh.axis_names)
    in_specs = spmv_lib.sharded_table_specs(axes, n_arrays)
    in_specs = in_specs + (P(),)          # dangling, replicated

    def kernel(src8, sel, oh_hi, oh_lo, *rest):
        ov, dangling = rest[:-1], rest[-1]
        arrays = (src8, sel, oh_hi, oh_lo) + ov

        body = _power_body(
            lambda r: spmv_lib.spmv_sharded_apply(plan_static, arrays,
                                                  r, mesh),
            n, alpha, dangling)
        r0 = _r0(n)
        pcast = getattr(jax.lax, "pcast", None)
        r0 = (pcast(r0, axes, to="varying") if pcast is not None
              else compat.pvary(r0, axes))
        return jax.lax.fori_loop(0, rounds, body, r0)

    # check_vma=False: see _sharded_spmv_runner — the all_gathered carry
    # is value-identical per device but typed varying
    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=in_specs,  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
                             out_specs=P(), check_vma=False))


def _power_body(matvec, n: int, alpha: float, dangling):
    """The shared PageRank update: one body for every edge-based impl so
    the teleport/dangling semantics (and precision) cannot drift apart."""
    teleport = (1.0 - alpha) / n

    def body(_, r):
        contrib = matvec(r)
        dmass = jnp.sum(dangling * r)
        return alpha * (contrib + dmass / n) + teleport

    return body


def _r0(n: int):
    return jnp.full((n,), 1.0 / n, dtype=jnp.float32)


@functools.lru_cache(maxsize=32)
def _onehot_runner(n: int, rounds: int, alpha: float, plan_static,
                   n_arrays: int):
    from matrel_tpu.ops import spmv as spmv_lib

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run(arrays, dangling):
        body = _power_body(
            lambda r: spmv_lib.spmv_apply(plan_static, arrays, r),
            n, alpha, dangling)
        return jax.lax.fori_loop(0, rounds, body, _r0(n))

    return run


@functools.lru_cache(maxsize=32)
def _compact_runner_loop(n: int, rounds: int, alpha: float, plan_static,
                         n_ov: int, passes: int, interpret: bool):
    from matrel_tpu.ops import pallas_spmv as pc

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run(tables, ov, dangling):
        body = _power_body(
            lambda r: pc.compact_apply(plan_static, tables, ov, r,
                                       passes, interpret),
            n, alpha, dangling)
        return jax.lax.fori_loop(0, rounds, body, _r0(n))

    return run


@functools.lru_cache(maxsize=32)
def _edges_runner(n: int, rounds: int, alpha: float):
    """Jitted programs cached per (n, rounds, alpha) — fresh closures per
    call would recompile on every invocation."""

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def prepare(s, d, w):
        # sort edges by destination once so the per-round scatter-add runs
        # with indices_are_sorted (much cheaper on TPU)
        order = jnp.argsort(d)
        return s[order], d[order], w[order]

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run(s, d, w):
        outdeg = jax.ops.segment_sum(w, s, num_segments=n)
        inv_deg = jnp.where(outdeg > 0,
                            1.0 / jnp.maximum(outdeg, 1e-30), 0.0)
        dangling = (outdeg == 0).astype(jnp.float32)

        def matvec(r):
            rn = r * inv_deg
            return jax.ops.segment_sum(rn[s] * w, d, num_segments=n,
                                       indices_are_sorted=True)

        body = _power_body(matvec, n, alpha, dangling)
        return jax.lax.fori_loop(0, rounds, body, _r0(n))

    return prepare, run


def pagerank_csr(src, dst, n: int, rounds: int = 30, alpha: float = 0.85,
                 max_degree_factor: float = 2.0):
    """PageRank via a padded in-neighbor table — scatter-free matvec.

    Build (host-side, once) a dense (n, D) table of in-neighbors padded
    with a sentinel, where D is the max in-degree; each round is then a
    dense gather + row-sum — no scatter in the loop. The padded table does
    D/mean-degree × the gathers of the edge-list form, so this only wins
    when the in-degree distribution is TIGHT (near-regular graphs, D ≲
    2×mean — measured on 1M/10M uniform-random edges, D≈3.5×mean, the
    segment-sum form is ~2.5× faster). Anything looser falls back to
    ``pagerank_edges``.
    """
    src = np.asarray(src, dtype=np.int32)
    dst = np.asarray(dst, dtype=np.int32)
    indeg = np.bincount(dst, minlength=n)
    D = int(indeg.max()) if len(dst) else 0
    mean_deg = max(len(dst) / max(n, 1), 1.0)
    if D > max_degree_factor * mean_deg:
        return pagerank_edges(src, dst, n, rounds, alpha)
    order = np.argsort(dst, kind="stable")
    dst_s, src_s = dst[order], src[order]
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(indeg, out=offsets[1:])
    slot = np.arange(len(dst_s)) - offsets[dst_s]
    neighbors = np.full((n, max(D, 1)), n, dtype=np.int32)  # n = sentinel
    neighbors[dst_s, slot] = src_s
    outdeg = np.bincount(src, minlength=n).astype(np.float32)
    run = _csr_runner(int(n), int(rounds), float(alpha), int(max(D, 1)))
    return run(jnp.asarray(neighbors), jnp.asarray(outdeg))


@functools.lru_cache(maxsize=32)
def _csr_runner(n: int, rounds: int, alpha: float, D: int):
    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run(neighbors, outdeg):
        inv_deg = jnp.where(outdeg > 0, 1.0 / jnp.maximum(outdeg, 1.0), 0.0)
        dangling = (outdeg == 0).astype(jnp.float32)

        def matvec(r):
            w = r * inv_deg
            w_pad = jnp.concatenate([w, jnp.zeros((1,), w.dtype)])  # sentinel
            return jnp.sum(w_pad[neighbors], axis=1)

        body = _power_body(matvec, n, alpha, dangling)
        return jax.lax.fori_loop(0, rounds, body, _r0(n))

    return run


def pagerank_block_sparse(S, rounds: int = 30, alpha: float = 0.85,
                          config: Optional[MatrelConfig] = None) -> jax.Array:
    """PageRank on a block-sparse adjacency (clustered graphs where tiles
    are dense enough to pay — web/community graphs; for uniform-random
    edge lists use pagerank_edges). The matvec is the SpMM fast path over
    Âᵀ; the loop is host-driven but each round is one cached compiled
    program (no re-trace), mirroring the reference's per-round plan
    execution without its shuffle."""
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.ops import spmm as spmm_lib

    n = S.shape[0]
    if S.shape[0] != S.shape[1]:
        raise ValueError(f"adjacency must be square, got {S.shape}")
    st = S.transpose()
    mesh = S.mesh
    deg_bm = spmm_lib.spmm(
        S, BlockMatrix.from_numpy(np.ones((n, 1), np.float32), mesh=mesh),
        config)

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def prep(deg):
        # epsilon (not 1.0) floor: weighted adjacencies can have row sums
        # below 1, and clamping those would silently skew the ranks
        inv = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1e-30), 0.0)
        dangling = ((deg == 0) &
                    (jnp.arange(deg.shape[0])[:, None] < n)).astype(jnp.float32)
        return inv, dangling

    inv_deg, dangling = prep(deg_bm.data)
    teleport = (1.0 - alpha) / n
    r = BlockMatrix.from_numpy(np.full((n, 1), 1.0 / n, np.float32),
                               mesh=mesh)

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def poststep(contrib, r_old):
        dmass = jnp.sum(dangling * r_old)
        r_new = alpha * (contrib + dmass / n) + teleport
        valid = (jnp.arange(r_new.shape[0]) < n)[:, None]
        return jnp.where(valid, r_new, 0.0)

    for _ in range(rounds):
        weighted = BlockMatrix.from_array(r.data * inv_deg,
                                          (n, 1), mesh, r.spec)
        contrib = spmm_lib.spmm(st, weighted, config)
        r = BlockMatrix.from_array(poststep(contrib.data, r.data),
                                   (n, 1), mesh, r.spec)
    return r.data[:n]


def pagerank_numpy_oracle(a, rounds=30, alpha=0.85):
    """Naive host oracle for tests."""
    n = a.shape[0]
    deg = a.sum(1, keepdims=True)
    inv = np.where(deg > 0, 1.0 / np.maximum(deg, 1e-30), 0.0)
    r = np.full((n, 1), 1.0 / n, dtype=np.float64)
    for _ in range(rounds):
        contrib = (a * inv).T @ r
        dmass = r[(deg == 0).ravel()].sum()
        r = alpha * (contrib + dmass / n) + (1 - alpha) / n
    return r
