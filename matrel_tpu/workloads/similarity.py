"""Cosine-similarity matrix — a row-similarity "matrix analytics" query
(the all-pairs similarity workload of relational-matrix systems):

    S = D⁻¹ · (X·Xᵀ) · D⁻¹,   D = diag(‖x_i‖₂)

The X·Xᵀ core is a GRAM, so under ``matmul_precision="high"`` the
executor's symmetric 2-pass bf16 split (ops/gram.py, round-3) applies
automatically — this workload is the user-facing consumer of that
lowering. The normalisation is rowwise masking-safe elementwise math on
the framework surface (no host round-trips); thresholded similarity
joins compose via select_value on the result.
"""

from __future__ import annotations

from typing import Union

import numpy as np

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import expr as E


def cosine_similarity_expr(X: Union[BlockMatrix, E.MatExpr]) -> E.MatExpr:
    """Lazy S = normalize-rows(X) gram: S[i,j] = cos(x_i, x_j).

    Expressed as G / (n·nᵀ) with G = X·Xᵀ and n = sqrt(rowSum(X∘X)):
    one gram multiply (symmetric-split eligible), one rank-1-shaped
    denominator via a row-norm outer product, one elementwise divide.
    """
    x = E.as_expr(X)
    g = x.multiply(x.t())                        # X·Xᵀ — gram path
    sq = E.agg(E.elemwise("mul", x, x), "sum", "row")   # (n, 1) ‖x‖²
    norms = sq.power(0.5)
    denom = norms.multiply(norms.t())            # ‖x_i‖·‖x_j‖ outer
    return E.elemwise("div", g, denom)


def cosine_similarity(X: Union[BlockMatrix, E.MatExpr]) -> np.ndarray:
    return cosine_similarity_expr(X).compute().to_numpy()


def cosine_similarity_numpy_oracle(x: np.ndarray) -> np.ndarray:
    n = np.linalg.norm(x, axis=1, keepdims=True)
    return (x @ x.T) / (n @ n.T)
