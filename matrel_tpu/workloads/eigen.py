"""Power iteration — dominant eigenpair and spectral norm.

The reference's iterative-workload family (PageRank is power iteration
on the transition matrix; SURVEY.md §3.5) generalised to any square
matrix: the loop body is one distributed matvec + normalisation, jitted
as a single ``lax.fori_loop`` program — no host round-trips, exactly
the PageRank execution shape.

``spectral_norm`` runs the iteration on AᵀA (‖A‖₂² = λ_max(AᵀA))
without forming AᵀA: each step multiplies by A then Aᵀ, so the memory
stays O(n + m) and every FLOP is a matvec on the MXU.
"""

from __future__ import annotations

from typing import Tuple, Union

import functools

import jax
import jax.numpy as jnp
import numpy as np

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import expr as E


def power_iteration(A: Union[BlockMatrix, E.MatExpr],
                    rounds: int = 50,
                    seed: int = 0) -> Tuple[float, jax.Array]:
    """(dominant eigenvalue, eigenvector) of square A by power
    iteration: v ← A·v / ‖A·v‖, λ = vᵀ·A·v. Converges to the
    eigenvalue of largest MAGNITUDE (gap-dependent rate)."""
    e = E.as_expr(A)
    n, m = e.shape
    if n != m:
        raise ValueError(f"power iteration needs a square matrix, got "
                         f"{e.shape}")
    data = _dense_data(A, e)
    lam, v = power_runner(rounds, seed)(data)
    return float(lam), v[:n]


@functools.lru_cache(maxsize=16)
def power_runner(rounds: int = 50, seed: int = 0):
    """Reusable jitted power-iteration ``run(mat) -> (lam, v)`` —
    memoised per (rounds, seed) so repeated calls (benchmark reps,
    sweeps over same-shaped matrices) reuse the compiled program."""

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run(mat):
        v0 = jax.random.normal(jax.random.PRNGKey(seed), (mat.shape[0],),
                               jnp.float32)
        v0 = v0 / jnp.linalg.norm(v0)

        def body(_, v):
            w = mat @ v
            return w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

        v = jax.lax.fori_loop(0, rounds, body, v0)
        lam = v @ (mat @ v)
        return lam, v

    return run


def spectral_norm(A: Union[BlockMatrix, E.MatExpr],
                  rounds: int = 50, seed: int = 0) -> float:
    """‖A‖₂ = sqrt(λ_max(AᵀA)) by power iteration on the Gram operator,
    applied as two matvecs per step (AᵀA never materialises)."""
    e = E.as_expr(A)
    data = _dense_data(A, e)

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run(mat):
        v0 = jax.random.normal(jax.random.PRNGKey(seed),
                               (mat.shape[1],), jnp.float32)
        v0 = v0 / jnp.linalg.norm(v0)

        def body(_, v):
            w = mat.T @ (mat @ v)
            return w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

        v = jax.lax.fori_loop(0, rounds, body, v0)
        return jnp.linalg.norm(mat @ v)

    # padded rows/cols are exactly zero and do not affect σ_max
    return float(run(data))


def _dense_data(A, e: E.MatExpr):
    """Padded device array of a dense operand (leaf matrices directly;
    expressions via one compile+run)."""
    if isinstance(A, BlockMatrix):
        return A.data
    if e.kind == "leaf":
        return e.attrs["matrix"].data
    from matrel_tpu.executor import execute
    return execute(e).data


def power_iteration_coo(A, rounds: int = 50,
                        seed: int = 0) -> Tuple[float, jax.Array]:
    """Power iteration on an element-sparse ``COOMatrix`` via its
    one-hot SpMV plan: every round is one planned SpMV inside a single
    jitted ``fori_loop`` — the graph-spectral path that never
    densifies A (uses the expanded-table plan; graphs the plan refuses
    fall back to the dense path)."""
    from matrel_tpu.ops import spmv as spmv_lib

    if A.shape[0] != A.shape[1]:
        raise ValueError(f"power iteration needs a square matrix, got "
                         f"{A.shape}")
    plan = A._get_plan()
    if plan is None:          # heavy-tailed graph: plan refused
        return power_iteration(
            E.as_expr(
                BlockMatrix.from_numpy(A.to_dense())), rounds, seed)
    static = (plan.n_rows, plan.n_cols, plan.block)

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run(arrays):
        v0 = jax.random.normal(jax.random.PRNGKey(seed),
                               (plan.n_cols,), jnp.float32)
        v0 = v0 / jnp.linalg.norm(v0)

        def body(_, v):
            w = spmv_lib.spmv_apply(static, arrays, v)
            return w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

        v = jax.lax.fori_loop(0, rounds, body, v0)
        lam = v @ spmv_lib.spmv_apply(static, arrays, v)
        return lam, v

    lam, v = run(plan.arrays())
    return float(lam), v[: A.shape[0]]


def eig_numpy_oracle(a: np.ndarray) -> float:
    """|λ|_max for tests (dense numpy)."""
    return float(np.max(np.abs(np.linalg.eigvals(a))))
