"""North-star workload: the 65k×65k chain A·B·C (BASELINE.json:2).

65k² f32 is 17 GB per matrix — three operands plus intermediates cannot be
resident on a 16 GB v5e chip, and the pod-scale path (v5e-64: operands
sharded P(x,y), strategies from parallel/) is exercised by dryrun_multichip.
This module makes the chain FEASIBLE AND FAST on chips it doesn't fit on,
by streaming:

    out_panel_i = (A_i · B) · C         for row panels A_i

with B and C never fully resident — their k-tiles are produced on demand by
traceable generator functions (synthetic data, checkpoint shards, or
gathers from host storage). Memory is O(panel × n); every FLOP is an MXU
tile GEMM; the whole triple loop is ONE jitted program (fori_loops).

This is the blockwise-accumulation answer SURVEY.md §6/§7 calls for
("intermediates force thought about donation/accumulation order; blockwise
chain evaluation may be needed").
"""

from __future__ import annotations

import functools
from typing import Callable

import jax

from matrel_tpu.utils import compat
import jax.numpy as jnp

Gen = Callable[[jax.Array, jax.Array], jax.Array]
# Gen(bi, bj) -> tile of shape (tile, tile): block (bi, bj) of the operand.


def default_gen(seed: int, tile: int, dtype=jnp.bfloat16, scale: float = None
                ) -> Gen:
    """Deterministic tile generator (iota arithmetic — RNG at 65k² costs
    more than the matmuls). Scaled ~1/sqrt(n) so chained products stay in
    bf16 range. Carries a ``.slab(r0, c0, shape)`` fast path generating an
    arbitrary global-coordinate rectangle in one fused elementwise op."""
    s = scale if scale is not None else 0.01

    def gen(bi, bj):
        r = jax.lax.broadcasted_iota(jnp.float32, (tile, tile), 0)
        c = jax.lax.broadcasted_iota(jnp.float32, (tile, tile), 1)
        v = jnp.sin(r * 0.1 + c * 0.37 + bi * 1.7 + bj * 0.3 + seed) * s
        return v.astype(dtype)

    def slab(r0, c0, shape):
        rg = jax.lax.broadcasted_iota(jnp.float32, shape, 0) + r0
        cg = jax.lax.broadcasted_iota(jnp.float32, shape, 1) + c0
        r, bi = rg % tile, rg // tile
        c, bj = cg % tile, cg // tile
        v = jnp.sin(r * 0.1 + c * 0.37 + bi * 1.7 + bj * 0.3 + seed) * s
        return v.astype(dtype)

    gen.slab = slab
    return gen


def cheap_gen(seed: int, tile: int, dtype=jnp.bfloat16, scale: float = None
              ) -> Gen:
    """Generator with a ~4-op elementwise body (fractional-part mixing
    instead of sin) — at 65k² the transcendental in ``default_gen`` is
    VPU time stolen from the MXU. Values are uniform-ish in [-s, s];
    statistically crude but plenty for exercising/benchmarking the
    pipeline, and fully deterministic."""
    s = scale if scale is not None else 0.01

    def _vals(rg, cg):
        x = rg * 0.6180339887 + cg * 0.7548776662 + (seed + 1) * 0.5545497
        return ((x - jnp.floor(x)) * 2.0 - 1.0) * s

    def gen(bi, bj):
        r = jax.lax.broadcasted_iota(jnp.float32, (tile, tile), 0)
        c = jax.lax.broadcasted_iota(jnp.float32, (tile, tile), 1)
        return _vals(r + bi * tile, c + bj * tile).astype(dtype)

    def slab(r0, c0, shape):
        rg = jax.lax.broadcasted_iota(jnp.float32, shape, 0) + r0
        cg = jax.lax.broadcasted_iota(jnp.float32, shape, 1) + c0
        return _vals(rg, cg).astype(dtype)

    gen.slab = slab
    return gen


def streaming_chain(n: int,
                    gen_a: Gen, gen_b: Gen, gen_c: Gen,
                    tile: int = 8192,
                    panel: int = 16384,
                    dtype=jnp.bfloat16,
                    reduce: str = "fro") -> jax.Array:
    """Evaluate reduce(A·B·C) for n×n operands produced tile-wise.

    Per output row panel i:
        T_i[., :]  = Σ_k gen_a(i, k) · B_k      (B_k = row-block k of B)
        O_i[., :]  = Σ_k T_i[., k] · C_k
        acc       += reduction(O_i)
    The returned scalar (Frobenius² by default, or 'sum') certifies the
    whole product was computed without materialising any n×n array.
    """
    if n % tile or n % panel or panel % tile:
        raise ValueError("n must divide by tile and panel; panel by tile")
    kt = n // tile         # tiles along contraction
    npan = n // panel      # row panels
    prec = jax.lax.Precision.DEFAULT

    run = _chain_runner(n, tile, panel, kt, npan, gen_a, gen_b, gen_c,
                        dtype, reduce, prec)
    return run()


def streaming_chain_slab(n: int,
                         gen_a: Gen, gen_b: Gen, gen_c: Gen,
                         tile: int = 8192,
                         panel: int = 16384,
                         dtype=jnp.bfloat16,
                         reduce: str = "fro") -> jax.Array:
    """Slab-structured evaluation of reduce(A·B·C) — the fast single-chip
    north-star path.

    Differs from ``streaming_chain`` in how the contraction is scheduled:
    instead of accumulating a (panel, n) f32 carry across k-steps (which
    round-trips the 4 GB accumulator through HBM kt× per phase), every
    output slab is ONE ``dot_general`` over the full 65k contraction —
    the f32 accumulation happens inside the MXU's tiling, never touching
    HBM. Operand column slabs (n, tile) are produced by the generators'
    ``.slab`` fast path in one fused elementwise op each.

        T_i[:, j] = A_i · B[:, j]      (one dot per slab, full k)
        acc      += reduce(T_i · C[:, j])

    Requires gens built by ``default_gen``/``cheap_gen`` (anything with
    ``.slab(r0, c0, shape)``).
    """
    if n % tile or n % panel or panel % tile:
        raise ValueError("n must divide by tile and panel; panel by tile")
    for g in (gen_a, gen_b, gen_c):
        if not hasattr(g, "slab"):
            raise ValueError("streaming_chain_slab needs .slab-capable "
                             "generators (default_gen / cheap_gen)")
    run = _slab_runner(n, tile, panel, gen_a, gen_b, gen_c, dtype, reduce)
    return run()


def _vma_zeros(shape, dt, vma_axes):
    """Zeros marked varying over ``vma_axes`` (loop carries under
    shard_map need this or the fori carry types mismatch)."""
    z = jnp.zeros(shape, dtype=dt)
    if vma_axes:
        pcast = getattr(jax.lax, "pcast", None)
        z = (pcast(z, vma_axes, to="varying") if pcast is not None
             else compat.pvary(z, vma_axes))
    return z


def _make_slab_panel_body(n, tile, panel, gen_a, gen_b, gen_c, dtype,
                          reduce, vma_axes=()):
    """Slab-scheduled per-panel contraction, shared by the single- and
    multi-chip evaluators. ``vma_axes`` as in ``_make_panel_body``."""
    kt = n // tile

    def zeros(shape, dt):
        return _vma_zeros(shape, dt, vma_axes)

    def panel_body(i, acc):
        a_i = gen_a.slab(i * panel, 0, (panel, n)).astype(dtype)

        # (Unrolling these j loops was measured identical to fori_loop —
        # 6.30 s either way at n=65k — so keep the compact loop form.)
        def fill_t(j, t):
            b_j = gen_b.slab(0, j * tile, (n, tile)).astype(dtype)
            s = jax.lax.dot_general(
                a_i, b_j, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return jax.lax.dynamic_update_slice(
                t, s.astype(dtype), (0, j * tile))

        t_i = jax.lax.fori_loop(0, kt, fill_t, zeros((panel, n), dtype))

        def reduce_o(j, a2):
            c_j = gen_c.slab(0, j * tile, (n, tile)).astype(dtype)
            o = jax.lax.dot_general(
                t_i, c_j, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return a2 + (jnp.sum(o * o) if reduce == "fro"
                         else jnp.sum(o))

        return acc + jax.lax.fori_loop(0, kt, reduce_o,
                                       zeros((), jnp.float32))

    return panel_body


@functools.lru_cache(maxsize=8)
def _slab_runner(n, tile, panel, gen_a, gen_b, gen_c, dtype, reduce):
    npan = n // panel
    panel_body = _make_slab_panel_body(n, tile, panel, gen_a, gen_b, gen_c,
                                       dtype, reduce)

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run():
        return jax.lax.fori_loop(0, npan, panel_body,
                                 jnp.zeros((), jnp.float32))

    return run


def streaming_chain_sharded(n: int,
                            gen_a: Gen, gen_b: Gen, gen_c: Gen,
                            mesh,
                            tile: int = 8192,
                            panel: int = 16384,
                            dtype=jnp.bfloat16,
                            reduce: str = "fro") -> jax.Array:
    """Multi-chip streaming chain: row panels distributed over ALL mesh
    devices (each device generates and contracts its own panels — the
    generators make operands location-free, so there is no input comm at
    all), one psum of the scalar reduction at the end.

    This is the v5e-64 shape of the north star: wall-clock scales ~1/P.
    Validated on the virtual CPU mesh by dryrun_multichip.
    """
    from matrel_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    if n % tile or n % panel or panel % tile:
        raise ValueError("n must divide by tile and panel; panel by tile")
    kt = n // tile
    npan = n // panel
    axes = tuple(mesh.axis_names)
    p = 1
    for a in axes:
        p *= mesh.shape[a]
    if npan % p:
        raise ValueError(f"panels ({npan}) must divide over devices ({p})")
    per_dev = npan // p
    prec = jax.lax.Precision.DEFAULT
    # slab schedule when the generators support it (same fast structure
    # as the single-chip north star); tile-assembly body otherwise
    if all(hasattr(g, "slab") for g in (gen_a, gen_b, gen_c)):
        panel_body = _make_slab_panel_body(n, tile, panel, gen_a, gen_b,
                                           gen_c, dtype, reduce,
                                           vma_axes=axes)
    else:
        panel_body = _make_panel_body(n, tile, panel, kt, gen_a, gen_b,
                                      gen_c, dtype, reduce, prec,
                                      vma_axes=axes)

    def kernel():
        idx = jnp.zeros((), jnp.int32)
        mult = 1
        for a in reversed(axes):
            idx = idx + jax.lax.axis_index(a) * mult
            mult *= mesh.shape[a]

        def body(j, acc):
            return panel_body(idx * per_dev + j, acc)

        acc0 = jnp.zeros((), jnp.float32)
        pcast = getattr(jax.lax, "pcast", None)
        acc0 = (pcast(acc0, axes, to="varying") if pcast is not None
                else compat.pvary(acc0, axes))
        local = jax.lax.fori_loop(0, per_dev, body, acc0)
        return jax.lax.psum(local, axes)

    f = jax.jit(shard_map(kernel, mesh=mesh, in_specs=(), out_specs=P()))  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    return f()


def _make_panel_body(n, tile, panel, kt, gen_a, gen_b, gen_c, dtype,
                     reduce, prec, vma_axes=()):
    """The per-panel contraction shared by the single- and multi-chip
    streaming evaluators. ``vma_axes``: mesh axes this body runs manual
    over (shard_map) — loop-carry zeros must be marked varying over them
    or the fori carries type-mismatch."""
    def zeros(shape, dt):
        return _vma_zeros(shape, dt, vma_axes)

    def row_block(gen, k, width_tiles):
        """Assemble row-block k (tile × n) from width_tiles generated tiles."""
        def one(j, acc):
            t = gen(k, j).astype(dtype)
            return jax.lax.dynamic_update_slice(acc, t, (0, j * tile))
        return jax.lax.fori_loop(0, width_tiles, one,
                                 zeros((tile, n), dtype))

    pt = panel // tile

    def col_panel(gen, i, k):
        """(panel, tile) column slab: tiles (i*pt+ti, k) stacked."""
        def one(ti, acc):
            t = gen(i * pt + ti, k).astype(dtype)
            return jax.lax.dynamic_update_slice(acc, t, (ti * tile, 0))
        return jax.lax.fori_loop(0, pt, one, zeros((panel, tile), dtype))

    def panel_body(i, acc):
        # --- T_i = A_i · B, contracted k-block by k-block so each B
        #     row-block is generated ONCE per panel (not once per
        #     tile-row — an 8× generation saving at panel=8*tile)
        def contract_b(k, part):
            a_col = col_panel(gen_a, i, k)                # (panel, tile)
            b_row = row_block(gen_b, k, kt)               # (tile, n)
            return part + jax.lax.dot_general(
                a_col, b_row, (((1,), (0,)), ((), ())),
                precision=prec, preferred_element_type=jnp.float32)

        t_i = jax.lax.fori_loop(
            0, kt, contract_b, zeros((panel, n), jnp.float32)).astype(dtype)

        # --- O_i = T_i · C, contracted tile-column by tile-column
        def contract_c(k, part):
            t_slice = jax.lax.dynamic_slice(
                t_i, (0, k * tile), (panel, tile))
            c_row = row_block(gen_c, k, kt)               # (tile, n)
            return part + jax.lax.dot_general(
                t_slice, c_row, (((1,), (0,)), ((), ())),
                precision=prec, preferred_element_type=jnp.float32)

        o_i = jax.lax.fori_loop(
            0, kt, contract_c, zeros((panel, n), jnp.float32))
        if reduce == "fro":
            return acc + jnp.sum(o_i * o_i)
        return acc + jnp.sum(o_i)

    return panel_body


@functools.lru_cache(maxsize=8)
def _chain_runner(n, tile, panel, kt, npan, gen_a, gen_b, gen_c, dtype,
                  reduce, prec):
    panel_body = _make_panel_body(n, tile, panel, kt, gen_a, gen_b, gen_c,
                                  dtype, reduce, prec)

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run():
        return jax.lax.fori_loop(0, npan, panel_body,
                                 jnp.zeros((), jnp.float32))

    return run


def north_star_flops(n: int) -> float:
    """A·B then ·C: 2n³ + 2n³."""
    return 4.0 * n ** 3
