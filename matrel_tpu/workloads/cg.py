"""Conjugate gradient — iterative SPD solve as ONE jitted program.

The reference solves normal equations with a direct driver-side solve
(Cholesky; `linreg.fit`). CG is the iterative alternative when the
system is large or the operator is only available as a matvec: each
step is one distributed matvec + a few vector reductions, compiled
into a single ``lax.while_loop`` (tolerance- AND iteration-bounded —
compiler-friendly control flow, no host round-trips).

``cg_solve`` takes a dense BlockMatrix / expression; ``cg_solve_linop``
takes any traceable matvec closure (e.g. a planned SpMV or the
never-materialised Gram operator v ↦ Aᵀ(Av)).
"""

from __future__ import annotations

from typing import Callable, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import expr as E


def cg_runner(matvec: Callable, tol: float = 1e-6,
              maxiter: int = 1000) -> Callable:
    """Reusable JITTED solver ``run(b) -> (x, iterations)`` for one SPD
    operator. ``cg_solve_linop`` builds a fresh runner per call (and so
    re-traces); repeated solves and benchmarks should hold ONE runner
    so the compiled program is cached across calls. ``b`` may be any
    float array shaped (n,) or (n, 1) — coerced like cg_solve_linop."""

    @jax.jit  # matlint: disable=ML010 workload runner cache, jitted once per static dims outside the plan path
    def run(b):
        b = jnp.asarray(b, jnp.float32).reshape(-1)
        bnorm = jnp.maximum(jnp.linalg.norm(b), 1e-30)

        def cond(state):
            _, r, _, rs, it = state
            return (jnp.sqrt(rs) > tol * bnorm) & (it < maxiter)

        def body(state):
            x, r, p, rs, it = state
            ap = matvec(p)
            alpha = rs / jnp.maximum(p @ ap, 1e-30)
            x = x + alpha * p
            r = r - alpha * ap
            rs_new = r @ r
            p = r + (rs_new / jnp.maximum(rs, 1e-30)) * p
            return x, r, p, rs_new, it + 1

        x0 = jnp.zeros_like(b)
        state = (x0, b, b, b @ b, jnp.int32(0))
        x, _, _, _, it = jax.lax.while_loop(cond, body, state)
        return x, it

    return run


def cg_solve_linop(matvec: Callable, b: jax.Array,
                   tol: float = 1e-6, maxiter: int = 1000
                   ) -> Tuple[jax.Array, jax.Array]:
    """Solve A·x = b for SPD operator ``matvec`` (traceable). Returns
    (x, iterations). Stops at ‖r‖ ≤ tol·‖b‖ or maxiter."""
    b = jnp.asarray(b, jnp.float32).reshape(-1)
    return cg_runner(matvec, tol, maxiter)(b)


def cg_solve(A: Union[BlockMatrix, E.MatExpr], b,
             tol: float = 1e-6, maxiter: int = 1000
             ) -> Tuple[jax.Array, int]:
    """CG on a dense SPD matrix (padded region is exactly zero, so the
    padded system decouples: padded residual entries stay 0)."""
    from matrel_tpu.workloads.eigen import _dense_data
    e = E.as_expr(A)
    n, m = e.shape
    if n != m:
        raise ValueError(f"CG needs a square (SPD) matrix, got {e.shape}")
    data = _dense_data(A, e)
    bb = np.zeros(data.shape[0], np.float32)
    bb[:n] = np.asarray(b, np.float32).reshape(-1)
    x, it = cg_solve_linop(lambda v: data @ v, jnp.asarray(bb),
                           tol=tol, maxiter=maxiter)
    return x[:n], int(it)


def cg_least_squares(X: Union[BlockMatrix, E.MatExpr], y,
                     l2: float = 0.0, tol: float = 1e-6,
                     maxiter: int = 1000) -> Tuple[jax.Array, int]:
    """argmin ‖Xθ − y‖² (+ l2‖θ‖²) by CG on the NORMAL EQUATIONS
    operator v ↦ Xᵀ(Xv) + l2·v — the Gram matrix never materialises
    (two matvecs per iteration; the iterative face of linreg.fit)."""
    from matrel_tpu.workloads.eigen import _dense_data
    e = E.as_expr(X)
    k = e.shape[1]
    data = _dense_data(X, e)
    yy = np.zeros(data.shape[0], np.float32)
    yy[: e.shape[0]] = np.asarray(y, np.float32).reshape(-1)
    rhs = jnp.asarray(data.T @ jnp.asarray(yy))

    def gram_op(v):
        return data.T @ (data @ v) + l2 * v

    theta, it = cg_solve_linop(gram_op, rhs, tol=tol, maxiter=maxiter)
    return theta[:k], int(it)
