"""matrel_tpu — a TPU-native rebuild of purduedb/MatRel.

Distributed relational linear algebra on JAX/XLA: block-partitioned matrices
as mesh-sharded jax.Arrays, a Catalyst-style algebraic optimizer with
matrix-chain DP reordering, cost-based physical matmul strategies lowering to
ICI collectives, and relational operators (σ/γ/⋈) over matrices.

See SURVEY.md for the reference layer map this package mirrors.
"""

from matrel_tpu.config import MatrelConfig, default_config, set_default_config
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.core.coo import COOMatrix
from matrel_tpu.core.sparse import BlockSparseMatrix
from matrel_tpu.core.mesh import make_mesh
from matrel_tpu.executor import CompiledPlan, compile_expr, execute
from matrel_tpu.ir.expr import MatExpr, as_expr, leaf
from matrel_tpu.session import MatrelSession, get_or_create_session, reset_session

__version__ = "0.1.0"

__all__ = [
    "MatrelConfig", "default_config", "set_default_config",
    "BlockMatrix", "BlockSparseMatrix", "COOMatrix", "make_mesh",
    "CompiledPlan", "compile_expr", "execute",
    "MatExpr", "as_expr", "leaf",
    "MatrelSession", "get_or_create_session", "reset_session",
    "__version__",
]
