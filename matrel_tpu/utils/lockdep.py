"""lockdep — the sanctioned lock-construction seam plus an optional
runtime lock-order sanitizer (docs/CONCURRENCY.md).

Every ``threading.Lock``/``RLock`` in the package is constructed
through :func:`make_lock` / :func:`make_rlock` (matlint ML017 — the
ML009/ML010 one-seam idiom applied to locks). The seam buys two
things:

1. **A named lock inventory.** Each lock declares a stable dotted name
   (``"fleet.controller"``, ``"session.compile"``) — the vocabulary
   the static analyzer (tools/lockcheck.py), the runtime order graph
   and docs/CONCURRENCY.md's inventory table all share.
2. **A swap point.** With ``config.lockdep_enable`` the constructors
   return :class:`_InstrumentedLock` wrappers that record per-thread
   acquisition stacks into one global lock-ORDER graph and raise or
   record typed diagnostics:

   - :class:`LockOrderInversion` — acquiring B while holding A after
     the reverse order was ever observed (a cycle in the order graph:
     two threads interleaving those paths can deadlock), and the
     immediately-fatal special case of re-acquiring a non-reentrant
     lock the same thread already holds (self-deadlock — always
     raised, never just recorded, because proceeding would wedge the
     process the drill exists to protect).
   - :class:`HeldAcrossDispatch` — a sanctioned dispatch/blocking
     point (:func:`note_dispatch` call sites: the executor dispatch
     arbitration, the serve worker's result sync) entered while
     holding a lock not explicitly sanctioned for it (the PR 8
     drain-wedge class, dynamically).

   Diagnostics flow through the emit hook (:func:`set_emit`) as
   ``lockdep`` obs events — the session wires its ``_obs_emit``
   funnel in, so they land in the JSONL event log AND the
   flight-recorder ring; ``history --summary`` rolls them up and
   ``--check`` fails on any recorded inversion.

The default path (``lockdep_enable`` off) returns the raw
``threading`` primitives directly and constructs ZERO lockdep objects
(the fusion/cse structural-zero contract; poisoned-``__init__``
test-enforced in tests/test_lockdep.py). ``note_dispatch`` is a
single module-global flag check when disabled.

Known limitation (documented, deliberate): the order graph is keyed
by lock NAME (the lock-class granularity of kernel lockdep), so two
instances of the same named lock (two slices' pipelines) share a
node; nesting a name under itself is therefore excluded from the
cycle check (it would self-loop falsely) — the static analyzer's
per-``(class, attr)`` LK104 pass and the per-INSTANCE self-deadlock
check above cover that hole. Module-level locks are constructed at
import time, so they are only instrumented when :func:`enable` runs
before their module first imports (the race drill and the lockdep
fixtures both do).
"""

from __future__ import annotations

import sys
import threading
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "make_lock", "make_rlock", "enable", "disable", "enabled",
    "reset", "set_emit", "note_dispatch", "order_graph",
    "diagnostics", "is_acyclic", "LockOrderInversion",
    "HeldAcrossDispatch",
]


class LockOrderInversion(RuntimeError):
    """Two locks were observed nesting in BOTH orders (or a
    non-reentrant lock was re-acquired by its holder): a schedule
    exists that deadlocks. Carries the diagnostic record."""

    def __init__(self, record: dict):
        self.record = record
        super().__init__(record.get("msg", "lock-order inversion"))


class HeldAcrossDispatch(RuntimeError):
    """A sanctioned dispatch/blocking point ran while holding an
    unsanctioned lock — the dynamic form of lockcheck's LK102 (the
    PR 8 drain-wedge class). Carries the diagnostic record."""

    def __init__(self, record: dict):
        self.record = record
        super().__init__(record.get("msg", "lock held across dispatch"))


# -- global sanitizer state (built lazily by enable(); the default
#    path never touches anything below beyond the _ENABLED check) ----

_ENABLED = False
_RAISE = False
_EMIT: Optional[Callable[[dict], None]] = None
# one guard for the shared graph/diagnostic stores — a RAW lock by
# necessity (the sanitizer cannot instrument itself)
_STATE_LOCK = threading.Lock()
#: observed nesting edges: (held_name, acquired_name) -> first-seen
#: {"site": ..., "held_site": ...} sample
_EDGES: Dict[Tuple[str, str], dict] = {}
_DIAGS: List[dict] = []
_TLS = threading.local()


def _held_stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def _site(depth: int = 3) -> str:
    """Lightweight ``file:line`` of the acquiring frame (skipping the
    wrapper's own frames) — cheap enough for the enabled path, never
    touched on the default path."""
    try:
        f = sys._getframe(depth)
        return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"
    except ValueError:
        return "?"


def _record(diag: dict, exc_type) -> None:
    """Store + emit one diagnostic; raise it when configured (the
    self-deadlock case forces the raise regardless — see caller)."""
    with _STATE_LOCK:
        _DIAGS.append(diag)
    emit = _EMIT
    if emit is not None:
        try:
            emit(dict(diag))
        except Exception:  # matlint: disable=ML007 diagnostics must never take a query down with a failing sink; the record is already in diagnostics()
            pass
    if _RAISE or diag.get("fatal"):
        raise exc_type(diag)


class _InstrumentedLock:
    """A named wrapper over one ``threading`` lock: bookkeeps the
    per-thread held stack, grows the global order graph on every
    acquisition, and mirrors enough of the lock protocol
    (``_release_save``/``_acquire_restore``/``_is_owned``) that
    ``threading.Condition`` built over it keeps the bookkeeping
    exact across ``wait()``."""

    __slots__ = ("name", "reentrant", "dispatch_ok", "_inner",
                 "_owner", "_count")

    def __init__(self, name: str, reentrant: bool,
                 dispatch_ok: bool = False):
        self.name = name
        self.reentrant = reentrant
        self.dispatch_ok = dispatch_ok
        self._inner = (threading.RLock() if reentrant
                       else threading.Lock())
        self._owner: Optional[int] = None   # thread ident (under GIL)
        self._count = 0

    # -- order bookkeeping ---------------------------------------------------

    def _check_before_acquire(self) -> None:
        me = threading.get_ident()
        held = _held_stack()
        if self._owner == me:
            if self.reentrant:
                return  # re-entry: no new edges, no new held entry
            _record({"kind": "lockdep", "diag": "self_deadlock",
                     "lock": self.name, "site": _site(),
                     "thread": threading.current_thread().name,
                     "fatal": True,
                     "msg": f"non-reentrant lock {self.name!r} "
                            f"re-acquired by its holder"},
                    LockOrderInversion)
            return  # unreachable (fatal always raises); defensive
        inversion = None
        with _STATE_LOCK:
            for ent in held:
                a = ent["name"]
                if a == self.name:
                    continue  # name-granularity self-loop (see module doc)
                edge = (a, self.name)
                if edge not in _EDGES:
                    _EDGES[edge] = {"site": _site(),
                                    "held_site": ent["site"]}
                if inversion is None and _path_exists(self.name, a):
                    inversion = {
                        "kind": "lockdep", "diag": "inversion",
                        "lock": self.name, "held": a,
                        "site": _site(), "held_site": ent["site"],
                        "thread": threading.current_thread().name,
                        "msg": f"acquiring {self.name!r} while "
                               f"holding {a!r} after the reverse "
                               f"order was observed",
                    }
        if inversion is not None:
            _record(inversion, LockOrderInversion)

    def _note_acquired(self) -> None:
        me = threading.get_ident()
        if self._owner == me and self.reentrant:
            self._count += 1
            return
        self._owner = me
        self._count = 1
        _held_stack().append({"name": self.name, "lock": self,
                              "site": _site()})

    def _note_released(self) -> None:
        if self._count > 1:
            self._count -= 1
            return
        self._owner = None
        self._count = 0
        st = _held_stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i]["lock"] is self:
                del st[i]
                break

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            self._check_before_acquire()
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._note_acquired()
        return got

    def release(self) -> None:
        self._note_released()
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # Condition-protocol mirrors: Condition(wrapped_lock) picks these
    # up by attribute probe; routing them through the bookkeeping
    # keeps the held stack exact across wait()'s release/re-acquire.
    def _release_save(self):
        me = threading.get_ident()
        count = self._count if self._owner == me else 1
        self._owner = None
        self._count = 0
        st = _held_stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i]["lock"] is self:
                del st[i]
                break
        if self.reentrant:
            inner_state = self._inner._release_save()
            return (count, inner_state)
        self._inner.release()
        return (count, None)

    def _acquire_restore(self, state) -> None:
        count, inner_state = state
        if self.reentrant:
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        self._owner = threading.get_ident()
        self._count = count
        _held_stack().append({"name": self.name, "lock": self,
                              "site": _site()})

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def __repr__(self) -> str:
        return (f"<lockdep {'RLock' if self.reentrant else 'Lock'} "
                f"{self.name!r} owner={self._owner}>")


def _path_exists(src: str, dst: str) -> bool:
    """DFS over _EDGES (caller holds _STATE_LOCK): would edge
    dst->...->src already order dst before src?"""
    if src == dst:
        return True
    seen = {src}
    stack = [src]
    while stack:
        node = stack.pop()
        for (a, b) in _EDGES:
            if a == node and b not in seen:
                if b == dst:
                    return True
                seen.add(b)
                stack.append(b)
    return False


# -- the seam ----------------------------------------------------------------

def make_lock(name: str, dispatch_ok: bool = False):
    """The ONE sanctioned ``threading.Lock`` constructor (ML017).
    ``name`` is the lock's stable inventory id (docs/CONCURRENCY.md);
    ``dispatch_ok`` declares that holding this lock across a
    sanctioned dispatch point is by design (the fleet's
    dispatch-to-completion arbitration)."""
    if not _ENABLED:
        return threading.Lock()
    return _InstrumentedLock(name, reentrant=False,
                             dispatch_ok=dispatch_ok)


def make_rlock(name: str, dispatch_ok: bool = False):
    """The ONE sanctioned ``threading.RLock`` constructor (ML017)."""
    if not _ENABLED:
        return threading.RLock()
    return _InstrumentedLock(name, reentrant=True,
                             dispatch_ok=dispatch_ok)


def note_dispatch(what: str) -> None:
    """Sanctioned dispatch/blocking point: with the sanitizer on,
    diagnose any held un-sanctioned lock (HeldAcrossDispatch — the
    dynamic LK102). A single flag check when off."""
    if not _ENABLED:
        return
    for ent in _held_stack():
        lk = ent["lock"]
        if not lk.dispatch_ok:
            _record({"kind": "lockdep", "diag": "held_across_dispatch",
                     "lock": lk.name, "dispatch": what,
                     "site": _site(2), "held_site": ent["site"],
                     "thread": threading.current_thread().name,
                     "msg": f"{what}: dispatching while holding "
                            f"{lk.name!r}"},
                    HeldAcrossDispatch)


# -- control surface ---------------------------------------------------------

def enable(raise_on_violation: bool = False,
           emit: Optional[Callable[[dict], None]] = None) -> None:
    """Switch the constructors to instrumented wrappers. Locks built
    BEFORE this call stay raw (module-level locks in already-imported
    modules — see the module docstring); the session calls this ahead
    of constructing any of its own locks."""
    global _ENABLED, _RAISE, _EMIT
    _ENABLED = True
    _RAISE = bool(raise_on_violation)
    if emit is not None:
        _EMIT = emit


def disable() -> None:
    global _ENABLED, _RAISE, _EMIT
    _ENABLED = False
    _RAISE = False
    _EMIT = None


def enabled() -> bool:
    return _ENABLED


def set_emit(emit: Optional[Callable[[dict], None]]) -> None:
    """Install the diagnostic sink (the session passes a closure over
    its ``_obs_emit`` funnel, so records reach the event log and the
    flight ring). Last writer wins — one global sanitizer."""
    global _EMIT
    _EMIT = emit


def reset() -> None:
    """Clear the order graph and diagnostics (NOT the enabled flag) —
    drill/fixture isolation between seeded trials."""
    with _STATE_LOCK:
        _EDGES.clear()
        _DIAGS.clear()


def order_graph() -> Dict[Tuple[str, str], dict]:
    """Snapshot of the observed nesting edges."""
    with _STATE_LOCK:
        return dict(_EDGES)


def diagnostics() -> List[dict]:
    """Snapshot of every recorded diagnostic."""
    with _STATE_LOCK:
        return [dict(d) for d in _DIAGS]


def is_acyclic() -> bool:
    """True iff the observed order graph has no cycle (no deadlock-
    capable schedule was ever recorded)."""
    with _STATE_LOCK:
        edges = list(_EDGES)
    adj: Dict[str, list] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}

    def visit(n: str) -> bool:
        color[n] = GRAY
        for m in adj.get(n, ()):
            c = color.get(m, WHITE)
            if c == GRAY:
                return False
            if c == WHITE and not visit(m):
                return False
        color[n] = BLACK
        return True

    return all(visit(n) for n in adj if color.get(n, WHITE) == WHITE)
