"""Checkpoint / resume — the RDD.checkpoint()/persist() analogue
(SURVEY.md §5 "Checkpoint / resume").

The reference cuts lineage on iterative jobs by persisting RDDs; recovery is
lineage recomputation (Spark substrate). XLA has no mid-program retry, so
the TPU-native mechanism is driver-level checkpoint-and-restart: persist
named arrays per shard with atomic rename, restore into the same sharding,
and resume the iteration loop (see resilience.py).

Format: a directory per checkpoint step —
    <dir>/step_000042.tmp/...  → atomic rename → <dir>/step_000042/
        meta.json              (shapes, dtypes, specs, user state)
        <name>.npy             (one file per array, full host gather)

Full-gather is correct on one host; multi-host sharded IO would write one
file per addressable shard (the layout leaves room: files are per-name).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Any, Dict, Mapping, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.resilience import faults as faults_lib
from matrel_tpu.resilience.errors import CheckpointCorruption


def _file_sha1(path: str) -> str:
    """Streamed sha1 of one artifact file — the stored checksum the
    restore path verifies (a torn write, disk bit-flip, or truncated
    copy must fail TYPED, never hand back silently-corrupt arrays)."""
    h = hashlib.sha1()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _verify_file(d: str, fname: str, meta: Dict[str, Any]) -> str:
    """Path of one checkpoint artifact, checksum-verified when the
    metadata carries one (legacy checkpoints without checksums load
    unverified — backward compatible by construction)."""
    path = os.path.join(d, fname)
    want = (meta.get("checksums") or {}).get(fname)
    if want is not None:
        if not os.path.exists(path):
            raise CheckpointCorruption(
                f"checkpoint artifact {fname} missing from {d}")
        got = _file_sha1(path)
        if got != want:
            raise CheckpointCorruption(
                f"checkpoint artifact {fname} failed its checksum "
                f"(stored {want[:12]}…, computed {got[:12]}…) — "
                f"refusing to restore corrupt data from {d}")
    return path


def _check_name(name: str) -> None:
    """Checkpoint entry names become FILENAMES inside the step dir: a
    separator (or '..') would crash the save on a missing subdir or
    escape the directory entirely. Surfaced by the session-level
    catalog API, where names are arbitrary user strings."""
    if (not name or name in (".", "..") or "/" in name or "\\" in name
            or "\x00" in name or os.sep in name):
        raise ValueError(
            f"checkpoint entry name {name!r} is not a valid filename "
            f"component (no separators, '..', or NUL)")


def _spec_to_json(spec: P) -> list:
    out = []
    for part in spec:
        if part is None:
            out.append(None)
        elif isinstance(part, (tuple, list)):
            out.append(list(part))
        else:
            out.append(part)
    return out


def _spec_from_json(parts: list) -> P:
    return P(*[tuple(p) if isinstance(p, list) else p for p in parts])


class CheckpointManager:
    """Writes/reads checkpoints of BlockMatrices + pytree-of-arrays state."""

    def next_step(self) -> int:
        """The step AFTER the latest saved one (0 for an empty dir) —
        monotonic saves never collide with keep-k GC."""
        latest = self.latest_step()
        return 0 if latest is None else latest + 1

    def __init__(self, directory: str, keep: int = 2, config=None):
        self.directory = directory
        self.keep = keep
        # config is only consulted for the resilience fault site
        # ("checkpoint" — resilience/faults.py); None defers to
        # default_config() at check time so env-configured chaos
        # schedules reach direct CheckpointManager users too
        self.config = config
        os.makedirs(directory, exist_ok=True)

    def _fault_check(self) -> None:
        cfg = self.config
        if cfg is None:
            from matrel_tpu.config import default_config
            cfg = default_config()
        faults_lib.check("checkpoint", cfg)

    # -- save ---------------------------------------------------------------

    def save(self, step: int,
             matrices: Optional[Mapping[str, BlockMatrix]] = None,
             arrays: Optional[Mapping[str, jax.Array]] = None,
             sparse: Optional[Mapping[str, Any]] = None,
             state: Optional[Dict[str, Any]] = None) -> str:
        self._fault_check()
        matrices = dict(matrices or {})
        arrays = dict(arrays or {})
        sparse = dict(sparse or {})
        for name in (*matrices, *arrays, *sparse):
            _check_name(name)
        final = os.path.join(self.directory, f"step_{step:09d}")
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        meta: Dict[str, Any] = {"step": step, "state": state or {},
                                "matrices": {}, "arrays": [],
                                "sparse": {}, "checksums": {}}
        for name, bm in matrices.items():
            bm.data.block_until_ready()
            np.save(os.path.join(tmp, f"{name}.npy"), np.asarray(bm.data))
            meta["matrices"][name] = {
                "shape": list(bm.shape), "spec": _spec_to_json(bm.spec),
                "nnz": bm.nnz, "block_size": bm.block_size,
            }
        for name, arr in arrays.items():
            np.save(os.path.join(tmp, f"{name}.npy"), np.asarray(arr))
            meta["arrays"].append(name)
        for name, sm in sparse.items():
            np.savez(os.path.join(tmp, f"{name}.npz"),
                     blocks=np.asarray(sm.blocks),
                     block_rows=np.asarray(sm.block_rows),
                     block_cols=np.asarray(sm.block_cols))
            meta["sparse"][name] = {"shape": list(sm.shape),
                                    "block_size": sm.block_size}
        # per-artifact checksums, computed AFTER every write: restore
        # verifies each file it reads and raises the typed
        # CheckpointCorruption on mismatch (docs/RESILIENCE.md)
        for fname in sorted(os.listdir(tmp)):
            meta["checksums"][fname] = _file_sha1(
                os.path.join(tmp, fname))
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)  # atomic commit
        self._gc()
        return final

    # -- restore ------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        steps = self._steps()
        return steps[-1] if steps else None

    def restore(self, mesh: Mesh, step: Optional[int] = None
                ) -> Optional[Tuple[int, Dict[str, BlockMatrix],
                                    Dict[str, jax.Array], Dict[str, Any]]]:
        """Returns (step, matrices, arrays, state) or None if empty.
        Every artifact is checksum-verified against the metadata
        written at save time; a mismatch (or unparseable metadata)
        raises the typed ``CheckpointCorruption``."""
        self._fault_check()
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        d = os.path.join(self.directory, f"step_{step:09d}")
        meta = self._load_meta(d)
        matrices: Dict[str, BlockMatrix] = {}
        for name, m in meta["matrices"].items():
            host = np.load(_verify_file(d, f"{name}.npy", meta))
            spec = _spec_from_json(m["spec"])
            data = jax.device_put(host, NamedSharding(mesh, spec))
            matrices[name] = BlockMatrix(
                data=data, shape=tuple(m["shape"]), mesh=mesh, spec=spec,
                nnz=m["nnz"], block_size=m["block_size"])
        arrays = {name: jax.device_put(
                      np.load(_verify_file(d, f"{name}.npy", meta)))
                  for name in meta["arrays"]}
        return meta["step"], matrices, arrays, meta["state"]

    @staticmethod
    def _load_meta(d: str) -> Dict[str, Any]:
        """Parse one step's meta.json; corruption raises TYPED (the
        restore caller decides whether an older step will do)."""
        try:
            with open(os.path.join(d, "meta.json")) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruption(
                f"checkpoint metadata unreadable in {d}: {e}") from e

    def restore_sparse(self, mesh: Mesh, step: Optional[int] = None) -> Dict[str, Any]:
        """Restore BlockSparseMatrix entries saved via ``save(sparse=...)``."""
        from matrel_tpu.core.sparse import BlockSparseMatrix
        self._fault_check()
        if step is None:
            step = self.latest_step()
        if step is None:
            return {}
        d = os.path.join(self.directory, f"step_{step:09d}")
        meta = self._load_meta(d)
        rep = NamedSharding(mesh, P())
        out = {}
        for name, m in meta.get("sparse", {}).items():
            z = np.load(_verify_file(d, f"{name}.npz", meta))
            out[name] = BlockSparseMatrix(
                blocks=jax.device_put(z["blocks"], rep),
                block_rows=jax.device_put(z["block_rows"], rep),
                block_cols=jax.device_put(z["block_cols"], rep),
                shape=tuple(m["shape"]), block_size=m["block_size"],
                mesh=mesh)
        return out

    # -- housekeeping -------------------------------------------------------

    def _steps(self):
        pat = re.compile(r"^step_(\d{9})$")
        out = []
        for name in os.listdir(self.directory):
            m = pat.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _gc(self):
        steps = self._steps()
        for s in steps[: -self.keep] if self.keep > 0 else []:
            shutil.rmtree(os.path.join(self.directory, f"step_{s:09d}"),
                          ignore_errors=True)
