"""Version compatibility shims for the jax API surface.

The codebase targets the modern jax API (``jax.shard_map`` with its
``check_vma`` flag); container images pin older releases where the same
function lives at ``jax.experimental.shard_map.shard_map`` and the flag
is spelled ``check_rep``. Every shard_map call site imports from here so
the version split lives in exactly one place.
"""

from __future__ import annotations

try:                                   # jax >= 0.6: top-level export
    from jax import shard_map as _shard_map
    _LEGACY = False
except ImportError:                    # jax 0.4.x: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map
    _LEGACY = True


def shard_map(f, mesh, in_specs, out_specs, check_vma=None, **kw):
    """jax.shard_map across versions. ``check_vma`` (the modern name for
    the per-output varying-manual-axes check) maps onto the legacy
    ``check_rep`` flag — same meaning, inverted era."""
    if check_vma is not None:
        kw["check_rep" if _LEGACY else "check_vma"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def pvary(x, axes):
    """jax.lax.pvary across versions: on legacy jax the varying-axes
    type system doesn't exist, so marking a value varying is the
    identity (check_rep handles replication checking instead)."""
    import jax
    fn = getattr(jax.lax, "pvary", None)
    if fn is None:
        return x
    return fn(x, axes)


def tpu_compiler_params(**kw):
    """pallas tpu CompilerParams across the rename
    (``TPUCompilerParams`` on legacy jax)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)
