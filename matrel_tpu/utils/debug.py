"""Numerical guards — the sanitizer-role subsystem (SURVEY.md §5 "Race
detection / sanitizers").

The RDD model designs data races out; so does SPMD functional purity — there
is nothing for TSan to find. What CAN go wrong numerically (NaN/Inf from
ill-conditioned solves, division, overflow in bf16) is guarded here:

  - ``checked(fn)``: wrap a jittable fn with ``checkify`` so NaN/Inf and
    out-of-bounds errors surface as Python exceptions with locations.
  - ``assert_finite(bm)``: eager device-side finiteness check for
    BlockMatrix / arrays, cheap enough for test/debug paths.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import checkify

from matrel_tpu.core.blockmatrix import BlockMatrix


def checked(fn: Callable, errors=None) -> Callable:
    """checkify + jit: returns a callable that raises on NaN/Inf/OOB."""
    errs = errors if errors is not None else (
        checkify.float_checks | checkify.index_checks)
    cfn = checkify.checkify(fn, errors=errs)
    jfn = jax.jit(cfn)

    @functools.wraps(fn)
    def wrapper(*args, **kw):
        err, out = jfn(*args, **kw)
        checkify.check_error(err)
        return out

    return wrapper


@jax.jit
def _finite_count(x) -> jax.Array:
    return jnp.sum(~jnp.isfinite(x))


def assert_finite(m, name: str = "array") -> None:
    x = m.data if isinstance(m, BlockMatrix) else m
    bad = int(_finite_count(x))
    if bad:
        raise FloatingPointError(
            f"{name}: {bad} non-finite entries (shape {tuple(x.shape)})")
