"""ctypes bridge to the native optimizer core (native/chain_dp.cc).

Builds libmatrel_opt.so on first use if g++ is available (no pybind11 in
this image — plain C ABI + ctypes per the environment constraints), caches
the handle, and degrades silently to the pure-Python DP when the toolchain
or library is unavailable.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("matrel_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libmatrel_opt.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "chain_dp.cc")
    if not os.path.exists(src):
        return False
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    cmd = ["g++", "-O3", "-fPIC", "-std=c++17", "-shared", "-o", _LIB_PATH, src]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.debug("native build failed: %s", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.matrel_chain_dp.restype = ctypes.c_int
            lib.matrel_chain_dp.argtypes = [
                ctypes.c_int32,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.POINTER(ctypes.c_double),
            ]
            _lib = lib
        except OSError as e:
            log.debug("native load failed: %s", e)
        return _lib


def chain_dp(dims: Sequence[int], densities: Sequence[float]
             ) -> Optional[Tuple[np.ndarray, float]]:
    """Run the native interval DP. dims has n+1 entries; densities n.
    Returns (split table [n,n] int32, total cost) or None if the native
    path is unavailable."""
    lib = load()
    if lib is None:
        return None
    n = len(densities)
    if len(dims) != n + 1:
        raise ValueError("dims must have len(densities)+1 entries")
    dims_arr = np.ascontiguousarray(dims, dtype=np.int64)
    dens_arr = np.ascontiguousarray(densities, dtype=np.float64)
    splits = np.zeros((n, n), dtype=np.int32)
    cost = ctypes.c_double(0.0)
    rc = lib.matrel_chain_dp(n, dims_arr, dens_arr, splits.reshape(-1),
                             ctypes.byref(cost))
    if rc != 0:
        return None
    return splits, float(cost.value)
