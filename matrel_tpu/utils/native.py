"""ctypes bridge to the native optimizer core (native/chain_dp.cc).

Builds libmatrel_opt.so on first use if g++ is available (no pybind11 in
this image — plain C ABI + ctypes per the environment constraints), caches
the handle, and degrades silently to the pure-Python DP when the toolchain
or library is unavailable.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np
from matrel_tpu.utils import lockdep

log = logging.getLogger("matrel_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libmatrel_opt.so")

_lock = lockdep.make_lock("native.build")
_lib: Optional[ctypes.CDLL] = None
_tried = False


_SOURCES = ("chain_dp.cc", "mtx_reader.cc", "spmv_plan.cc")


def _stale() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.exists(s) and os.path.getmtime(s) > lib_mtime
        for s in (os.path.join(_NATIVE_DIR, name) for name in _SOURCES)
    )


def _build() -> bool:
    srcs = [os.path.join(_NATIVE_DIR, s) for s in _SOURCES]
    srcs = [s for s in srcs if os.path.exists(s)]
    if not srcs:
        return False
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    cmd = ["g++", "-O3", "-fPIC", "-std=c++17", "-pthread", "-shared",
           "-o", _LIB_PATH] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.debug("native build failed: %s", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if _stale() and not _build():
            if not os.path.exists(_LIB_PATH):
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            log.debug("native load failed: %s", e)
            return None
        try:
            lib.matrel_chain_dp.restype = ctypes.c_int
            lib.matrel_chain_dp.argtypes = [
                ctypes.c_int32,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.POINTER(ctypes.c_double),
            ]
            _has_dp = True
        except AttributeError as e:
            log.debug("native chain-dp symbols unavailable: %s", e)
            _has_dp = False
        lib._matrel_has_dp = _has_dp
        try:
            # comm-aware DP binds separately so a stale prebuilt lib
            # still serves the FLOPs-only DP
            lib.matrel_chain_dp_comm.restype = ctypes.c_int
            lib.matrel_chain_dp_comm.argtypes = [
                ctypes.c_int32,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.c_double,
                ctypes.c_int32,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.POINTER(ctypes.c_double),
            ]
            lib._matrel_has_dp_comm = True
        except AttributeError as e:
            log.debug("native comm-aware chain-dp unavailable: %s", e)
            lib._matrel_has_dp_comm = False
        try:
            # layout-aware DP binds separately for the same stale-lib
            # tolerance reason
            lib.matrel_chain_dp_layout.restype = ctypes.c_int
            lib.matrel_chain_dp_layout.argtypes = [
                ctypes.c_int32,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.c_double,
                ctypes.c_int32,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.POINTER(ctypes.c_double),
            ]
            lib._matrel_has_dp_layout = True
        except AttributeError as e:
            log.debug("native layout-aware chain-dp unavailable: %s", e)
            lib._matrel_has_dp_layout = False
        try:
            # topology-weighted DP binds separately for the same
            # stale-lib tolerance reason
            lib.matrel_chain_dp_topo.restype = ctypes.c_int
            lib.matrel_chain_dp_topo.argtypes = [
                ctypes.c_int32,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.c_double,
                ctypes.c_int32,
                ctypes.c_double,
                ctypes.c_double,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.POINTER(ctypes.c_double),
            ]
            lib._matrel_has_dp_topo = True
        except AttributeError as e:
            log.debug("native topology-weighted chain-dp unavailable: %s",
                      e)
            lib._matrel_has_dp_topo = False
        _lib = lib
        try:
            # Ingestion symbols bind separately so a stale prebuilt lib
            # (pre-mtx_reader) still serves the chain DP.
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
            lib.matrel_mtx_open.restype = ctypes.c_void_p
            lib.matrel_mtx_open.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
            ]
            lib.matrel_coo_csv_open.restype = ctypes.c_void_p
            lib.matrel_coo_csv_open.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
            lib.matrel_parse_fill.restype = ctypes.c_int64
            lib.matrel_parse_fill.argtypes = [
                ctypes.c_void_p, i64p, i64p, f64p, ctypes.c_int64]
            lib.matrel_parse_close.restype = None
            lib.matrel_parse_close.argtypes = [ctypes.c_void_p]
            _has_ingest = True
        except AttributeError as e:
            log.debug("native ingestion symbols unavailable: %s", e)
            _has_ingest = False
        lib._matrel_has_ingest = _has_ingest
        try:
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.matrel_spmv_counts.restype = ctypes.c_int
            lib.matrel_spmv_counts.argtypes = [
                i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, i64p]
            lib.matrel_spmv_fill.restype = ctypes.c_int64
            lib.matrel_spmv_fill.argtypes = [
                i64p, i64p, ctypes.c_void_p,          # rows, cols, vals|NULL
                ctypes.c_int64, ctypes.c_int64,        # m, n_cols
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # block,nb,cap
                ctypes.c_int32,                        # width
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                i64p, i64p,
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                ctypes.c_int64,                        # ov_cap
            ]
            _has_spmv = True
        except AttributeError as e:
            log.debug("native spmv-plan symbols unavailable: %s", e)
            _has_spmv = False
        lib._matrel_has_spmv = _has_spmv
        return _lib


def chain_dp(dims: Sequence[int], densities: Sequence[float],
             grid: Tuple[int, int] = (1, 1),
             comm_weight: Optional[float] = None,
             itemsize: int = 4,
             layouts: Optional[Sequence[int]] = None,
             weights: Optional[Tuple[float, float]] = None
             ) -> Optional[Tuple[np.ndarray, float]]:
    """Run the native interval DP. dims has n+1 entries; densities n.
    With grid != (1,1) the step cost adds the comm term (ir/stats.py::
    chain_step_cost semantics); non-trivial ``layouts`` (int codes,
    ir/stats.py::LAYOUT_CODES) make it layout-aware, and non-uniform
    per-axis ``weights`` (core/mesh.MeshTopology) make it
    topology-aware. Returns (split table [n,n] int32, total cost) or
    None if the native path is unavailable — including a stale prebuilt
    lib lacking the needed symbol (the caller's pure-Python DP then
    decides)."""
    lib = load()
    if lib is None or not getattr(lib, "_matrel_has_dp", False):
        return None
    n = len(densities)
    if len(dims) != n + 1:
        raise ValueError("dims must have len(densities)+1 entries")
    dims_arr = np.ascontiguousarray(dims, dtype=np.int64)
    dens_arr = np.ascontiguousarray(densities, dtype=np.float64)
    splits = np.zeros((n, n), dtype=np.int32)
    cost = ctypes.c_double(0.0)
    gx, gy = grid
    weighted = weights is not None and tuple(weights) != (1.0, 1.0)
    if gx * gy > 1:
        if comm_weight is None:
            from matrel_tpu.ir.stats import COMM_FLOPS_PER_BYTE
            comm_weight = COMM_FLOPS_PER_BYTE
        if layouts is not None and len(layouts) != n:
            raise ValueError("layouts must have one entry per operand")
        if weighted:
            # topology weights change the comm term for EVERY layout
            # (including all-2d), so the topo symbol is required — a
            # stale lib degrades to the pure-Python weighted DP rather
            # than silently pricing a flat fabric
            if not getattr(lib, "_matrel_has_dp_topo", False):
                return None
            lays_arr = np.ascontiguousarray(
                layouts if layouts is not None else [0] * n,
                dtype=np.int8)
            rc = lib.matrel_chain_dp_topo(
                n, dims_arr, dens_arr, lays_arr, int(gx), int(gy),
                float(comm_weight), int(itemsize), float(weights[0]),
                float(weights[1]), splits.reshape(-1),
                ctypes.byref(cost))
        elif layouts is not None and any(layouts):
            if not getattr(lib, "_matrel_has_dp_layout", False):
                return None
            lays_arr = np.ascontiguousarray(layouts, dtype=np.int8)
            rc = lib.matrel_chain_dp_layout(
                n, dims_arr, dens_arr, lays_arr, int(gx), int(gy),
                float(comm_weight), int(itemsize), splits.reshape(-1),
                ctypes.byref(cost))
        else:
            if not getattr(lib, "_matrel_has_dp_comm", False):
                return None
            rc = lib.matrel_chain_dp_comm(
                n, dims_arr, dens_arr, int(gx), int(gy),
                float(comm_weight), int(itemsize), splits.reshape(-1),
                ctypes.byref(cost))
    else:
        rc = lib.matrel_chain_dp(n, dims_arr, dens_arr,
                                 splits.reshape(-1), ctypes.byref(cost))
    if rc != 0:
        return None
    return splits, float(cost.value)


# -- native text ingestion (mtx_reader.cc) ----------------------------------

_MTX_SYMMETRIC = 1
_MTX_PATTERN = 2
_MTX_SKEW = 4
_MTX_COMPLEX = 8
_MTX_ARRAY = 16


def mtx_read(path: str) -> Optional[Tuple[Tuple[int, int], np.ndarray,
                                          np.ndarray, np.ndarray]]:
    """Parse a MatrixMarket file natively.

    Returns ((rows, cols), row_idx, col_idx, values) with symmetry already
    expanded (mirror/negated-mirror of off-diagonal entries), or None when
    the native library is unavailable or the file needs the scipy fallback
    (complex field, parse error).
    """
    lib = load()
    if lib is None or not getattr(lib, "_matrel_has_ingest", False):
        return None
    r = ctypes.c_int64(0)
    c = ctypes.c_int64(0)
    nnz = ctypes.c_int64(0)
    flags = ctypes.c_int32(0)
    h = lib.matrel_mtx_open(path.encode(), ctypes.byref(r), ctypes.byref(c),
                            ctypes.byref(nnz), ctypes.byref(flags))
    if not h:
        return None
    try:
        if flags.value & _MTX_COMPLEX:
            return None
        cap = max(1, nnz.value)
        ri = np.empty(cap, dtype=np.int64)
        ci = np.empty(cap, dtype=np.int64)
        vals = np.empty(cap, dtype=np.float64)
        got = lib.matrel_parse_fill(h, ri, ci, vals, cap)
    finally:
        lib.matrel_parse_close(h)
    if got < 0:
        return None
    ri, ci, vals = ri[:got], ci[:got], vals[:got]
    if flags.value & _MTX_SYMMETRIC:
        off = ri != ci
        mr, mc = ci[off], ri[off]
        mv = -vals[off] if flags.value & _MTX_SKEW else vals[off]
        ri = np.concatenate([ri, mr])
        ci = np.concatenate([ci, mc])
        vals = np.concatenate([vals, mv])
    return (r.value, c.value), ri, ci, vals


def coo_csv_read(path: str) -> Optional[Tuple[np.ndarray, np.ndarray,
                                              np.ndarray]]:
    """Parse 'i,j,value' coordinate text natively (0-based indices as
    stored). Returns (row_idx, col_idx, values) or None if unavailable."""
    lib = load()
    if lib is None or not getattr(lib, "_matrel_has_ingest", False):
        return None
    n = ctypes.c_int64(0)
    h = lib.matrel_coo_csv_open(path.encode(), ctypes.byref(n))
    if not h:
        return None
    try:
        cap = max(1, int(n.value))
        ri = np.empty(cap, dtype=np.int64)
        ci = np.empty(cap, dtype=np.int64)
        vals = np.empty(cap, dtype=np.float64)
        got = lib.matrel_parse_fill(h, ri, ci, vals, cap)
    finally:
        lib.matrel_parse_close(h)
    if got < 0:
        return None
    return ri[:got], ci[:got], vals[:got]


# -- native SpMV plan layout (spmv_plan.cc) ---------------------------------


def spmv_counts(rows: np.ndarray, block: int, nb: int
                ) -> Optional[np.ndarray]:
    """Per-block edge counts (pass 1 of the plan build); None if the
    native path is unavailable."""
    lib = load()
    if lib is None or not getattr(lib, "_matrel_has_spmv", False):
        return None
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    counts = np.zeros(nb, dtype=np.int64)
    rc = lib.matrel_spmv_counts(rows, rows.shape[0], block, nb, counts)
    return counts if rc == 0 else None


def spmv_fill(rows: np.ndarray, cols: np.ndarray,
              vals: Optional[np.ndarray], n_cols: int, block: int,
              nb: int, cap: int, width: int, n_overflow: int):
    """Pass 2: scatter edges into the padded plan tables. Returns
    (src8, lane, off, val, ov_rows, ov_cols, ov_vals) or None."""
    lib = load()
    if lib is None or not getattr(lib, "_matrel_has_spmv", False):
        return None
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    cols = np.ascontiguousarray(cols, dtype=np.int64)
    m = rows.shape[0]
    src8 = np.empty((nb, cap), dtype=np.int32)
    lane = np.empty((nb, cap), dtype=np.int8)
    off = np.empty((nb, cap), dtype=np.int32)
    val = np.empty((nb, cap), dtype=np.float32)
    ov_cap = max(1, n_overflow)
    ov_r = np.empty(ov_cap, dtype=np.int64)
    ov_c = np.empty(ov_cap, dtype=np.int64)
    ov_v = np.empty(ov_cap, dtype=np.float32)
    if vals is not None:
        vals = np.ascontiguousarray(vals, dtype=np.float32)
        vptr = vals.ctypes.data_as(ctypes.c_void_p)
    else:
        vptr = None
    got = lib.matrel_spmv_fill(rows, cols, vptr, m, n_cols, block, nb,
                               cap, width, src8.reshape(-1),
                               lane.reshape(-1), off.reshape(-1),
                               val.reshape(-1), ov_r, ov_c, ov_v, ov_cap)
    if got < 0 or got != n_overflow:
        return None
    return (src8, lane, off, val, ov_r[:got], ov_c[:got], ov_v[:got])
