"""Failure detection / recovery — the task-retry/lineage analogue
(SURVEY.md §5 "Failure detection / elastic recovery").

The Spark substrate retries failed tasks and recomputes lost partitions
from RDD lineage; the driver is the SPOF. XLA programs have no mid-program
retry, so the TPU-native shape of the same guarantee is:

  run_resilient(body, cm, ...):  a driver loop that checkpoints every
  ``interval`` iterations and, on device/runtime failure, re-enters from the
  last durable checkpoint (restart-and-resume; multi-slice DCN failures
  collapse to the same story). ``checkify``-style NaN/shape guards stand in
  for sanitizers: the RDD model designed races out, and so does SPMD
  functional purity (SURVEY.md §5 "Race detection").
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.resilience.errors import is_transient
from matrel_tpu.utils.checkpoint import CheckpointManager

log = logging.getLogger("matrel_tpu.resilience")


def _is_retryable(e: BaseException) -> bool:
    """Restart-worthiness now delegates to the resilience layer's ONE
    transient/deterministic taxonomy (resilience/errors.py) — the
    driver-loop restart and the serve-plane retry must never disagree
    about what a device fault looks like."""
    return is_transient(e)


def run_resilient(
    body: Callable[[int, Dict[str, BlockMatrix], Dict[str, Any]],
                   Tuple[Dict[str, BlockMatrix], Dict[str, Any]]],
    cm: CheckpointManager,
    mesh,
    init_matrices: Mapping[str, BlockMatrix],
    init_state: Optional[Dict[str, Any]] = None,
    num_steps: int = 1,
    checkpoint_interval: int = 10,
    max_restarts: int = 3,
) -> Tuple[Dict[str, BlockMatrix], Dict[str, Any]]:
    """Run ``body(step, matrices, state)`` for num_steps with checkpointing
    and restart-on-failure from the last durable step."""
    restarts = 0
    restored = cm.restore(mesh)
    if restored is not None:
        start, matrices, _, state = restored
        start += 1
        log.info("resuming from checkpoint step %d", start - 1)
    else:
        start, matrices, state = 0, dict(init_matrices), dict(init_state or {})

    step = start
    while step < num_steps:
        try:
            matrices, state = body(step, matrices, state)
            if (step + 1) % checkpoint_interval == 0 or step == num_steps - 1:
                cm.save(step, matrices=matrices, state=state)
            step += 1
        except Exception as e:  # noqa: BLE001 — gate below
            if not _is_retryable(e) or restarts >= max_restarts:
                raise
            restarts += 1
            log.warning("step %d failed (%s); restart %d/%d from checkpoint",
                        step, type(e).__name__, restarts, max_restarts)
            restored = cm.restore(mesh)
            if restored is None:
                step, matrices, state = 0, dict(init_matrices), dict(init_state or {})
            else:
                s, matrices, _, state = restored
                step = s + 1
    return matrices, state
