"""Tracing / profiling — the Spark-UI/SparkListener analogue
(SURVEY.md §5 "Tracing / profiling").

The reference gets stage/task timelines from the Spark UI for free; here:
  - ``trace(dir)``: jax.profiler context writing TensorBoard/Perfetto traces
  - ``annotate``: named_scope so each physical operator is visible in XLA
    traces (the executor wraps every node lowering — structurally
    enforced by tests/test_obs.py)
  - ``StepTimer``: wall-clock per-step table with device sync — since the
    obs/ subsystem landed, a thin VIEW over a
    :class:`matrel_tpu.obs.metrics.MetricsRegistry` (timings record as
    histograms, ``count`` as counters), so ad-hoc timer use and the
    session's query metrics share one aggregation surface instead of the
    old private dicts.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax

from matrel_tpu.obs.metrics import MetricsRegistry


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture an XLA profiler trace (view in TensorBoard/Perfetto)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named scope that shows up in profiler timelines per operator."""
    return jax.named_scope(name)


class StepTimer:
    """Per-step wall-clock accounting with explicit device sync, backed
    by a metrics registry (private by default — back-compat with the
    original free-standing timer; pass the process
    :data:`matrel_tpu.obs.metrics.REGISTRY` to aggregate with the
    session's query metrics).

    Usage:
        t = StepTimer()
        with t.step("matmul"):
            out = plan.run(); out.block_until_ready()
        print(t.table())
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()
        self._steps: list = []      # insertion order for table()
        self._counts: list = []

    @contextlib.contextmanager
    def step(self, name: str, sync: Optional[jax.Array] = None):
        t0 = time.perf_counter()
        yield
        if sync is not None:
            sync.block_until_ready()
        if name not in self._steps:
            self._steps.append(name)
        self.registry.histogram(f"step.{name}").observe(
            time.perf_counter() - t0)

    def count(self, name: str, value: float = 1.0) -> None:
        """Accumulator-style counter (the reference counts e.g. nnz
        processed via Spark accumulators)."""
        if name not in self._counts:
            self._counts.append(name)
        self.registry.counter(name).inc(value)

    @property
    def counters(self) -> dict:
        """Name → accumulated value (the pre-obs dict surface)."""
        return {n: self.registry.counter(n).value for n in self._counts}

    def table(self) -> str:
        lines = [f"{'step':<28}{'count':>6}{'total_s':>10}{'mean_ms':>10}"]
        for name in self._steps:
            h = self.registry.histogram(f"step.{name}")
            lines.append(f"{name:<28}{h.count:>6}{h.total:>10.3f}"
                         f"{1e3 * h.mean:>10.2f}")
        for name in self._counts:
            v = self.registry.counter(name).value
            lines.append(f"{name:<28}{'-':>6}{v:>10.0f}{'':>10}")
        return "\n".join(lines)
