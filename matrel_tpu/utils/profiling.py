"""Tracing / profiling — the Spark-UI/SparkListener analogue
(SURVEY.md §5 "Tracing / profiling").

The reference gets stage/task timelines from the Spark UI for free; here:
  - ``trace(dir)``: jax.profiler context writing TensorBoard/Perfetto traces
  - ``annotate``: named_scope so each physical operator is visible in XLA
    traces (the executor wraps every node lowering)
  - ``StepTimer``: wall-clock per-step table with device sync, the
    accumulator-style counter surface
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, List, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture an XLA profiler trace (view in TensorBoard/Perfetto)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named scope that shows up in profiler timelines per operator."""
    return jax.named_scope(name)


class StepTimer:
    """Per-step wall-clock accounting with explicit device sync.

    Usage:
        t = StepTimer()
        with t.step("matmul"):
            out = plan.run(); out.block_until_ready()
        print(t.table())
    """

    def __init__(self):
        self.records: List[tuple] = []
        self.counters: Dict[str, float] = {}

    @contextlib.contextmanager
    def step(self, name: str, sync: Optional[jax.Array] = None):
        t0 = time.perf_counter()
        yield
        if sync is not None:
            sync.block_until_ready()
        self.records.append((name, time.perf_counter() - t0))

    def count(self, name: str, value: float = 1.0) -> None:
        """Accumulator-style counter (the reference counts e.g. nnz
        processed via Spark accumulators)."""
        self.counters[name] = self.counters.get(name, 0.0) + value

    def table(self) -> str:
        by_name: Dict[str, List[float]] = {}
        for name, dt in self.records:
            by_name.setdefault(name, []).append(dt)
        lines = [f"{'step':<28}{'count':>6}{'total_s':>10}{'mean_ms':>10}"]
        for name, ds in by_name.items():
            lines.append(f"{name:<28}{len(ds):>6}{sum(ds):>10.3f}"
                         f"{1e3 * sum(ds) / len(ds):>10.2f}")
        for name, v in self.counters.items():
            lines.append(f"{name:<28}{'-':>6}{v:>10.0f}{'':>10}")
        return "\n".join(lines)
