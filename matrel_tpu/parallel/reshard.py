"""Memory-efficient array redistribution — the reshard planner
(ROADMAP item 2; "Memory-efficient array redistribution through
portable collective communication", arXiv:2112.01075).

Through round 9 a LAYOUT CHANGE was whatever one-shot collective shape
XLA's SPMD partitioner emitted for a single sharding constraint: the
planner priced reshards with closed forms (``planner._to_2d_reshard``,
``_reshard_to_axis``, ``_root_reshard_cost``) that the lowering never
actually followed, and the worst one-shot lowerings materialise a FULL
gather of the array as a transient — the reason MV105 must refuse
near-HBM-limit operands outright. This module closes both gaps:

* ``compile_reshard`` decomposes a src→dst sharding change into an
  explicit STEP SEQUENCE — per-axis ``all_to_all`` for shard↔shard
  moves, per-axis ``gather`` stages for replication, ``slice`` for
  replication-dropping moves, and the legacy single-shot move
  (``oneshot``) where it is both cheapest and feasible — each step
  carrying its exact per-axis bytes and its peak per-device footprint.
* ``apply_staged`` lowers the steps inside the executor's one jitted
  program as per-step sharding constraints under one ``annotate`` label
  per step kind, so XLA emits one collective per step (assertable from
  HLO, the shard_map-strategy discipline) instead of its own one-shot
  choice.
* The byte accounting uses the planner's OWN closed-form float
  arithmetic verbatim, so on a uniform mesh an unconstrained plan's
  cost is bit-identical to the legacy model (equality-tested); a
  ``peak_budget`` forces the bounded decomposition and the (honestly
  higher) staged bill.

The knob is ``config.reshard_peak_budget_bytes``: 0 (the default)
keeps the legacy single-constraint path bit-identically and constructs
no ReshardPlan objects at all (test-enforced); > 0 caps the peak
per-device bytes live during any reshard step. MV109
(analysis/reshard_pass.py) proves every stamped plan's peak fits the
budget; round-4 autotune measures plan-vs-naive per shape class
(autotune.lookup_or_measure_reshard) so measured winners persist like
matmul strategies. docs/RESHARD.md is the narrative reference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

#: Public layout vocabulary a reshard plan moves between — the
#: planner's layout model (planner.LAYOUTS minus "other", which is
#: costed like "2d" per the LAYOUTS contract and normalised here).
RESHARD_LAYOUTS = ("2d", "row", "col", "rep")

#: Internal states a staged plan may pass through: the public vocabulary
#: plus the partially-replicated gather stages ("rowx" = P(x, None) —
#: replicated along y; "coly" = P(None, y)).
_STATES = RESHARD_LAYOUTS + ("rowx", "coly")

#: Step vocabulary (each kind is one ``annotate`` label,
#: ``matrel.reshard:<kind>``):
#:   all_to_all  single-axis shard↔shard redistribution (row↔2d on y,
#:               col↔2d on x) — peak 2 shards, never a full gather
#:   gather      single-axis all-gather raising replication (2d→rowx
#:               on y, rowx→rep on x, …)
#:   slice       replication-dropping move (rep→anything): every device
#:               already holds its target shard; zero bytes on the wire
#:   oneshot     the legacy single-constraint move across BOTH axes
#:               (row↔col) — XLA's own lowering, modelled conservatively
#:               as gather-then-slice (transient full array)
#:   host        one HBM↔host-RAM transfer leg of the spill hierarchy
#:               (docs/DURABILITY.md) — d2h on demotion, h2d on
#:               promotion; the device-side transient is the staging
#:               buffer, so ``peak_bytes`` is the entry's device bytes
#:   disk        one host-RAM↔disk leg (the checkpoint-format artifact
#:               write/read) — zero DEVICE bytes live during the step,
#:               so it never charges the peak-HBM budget
STEP_KINDS = ("all_to_all", "gather", "slice", "oneshot",
              "host", "disk")

#: Tier vocabulary of the result-cache spill hierarchy, ordered top to
#: bottom. ``spill_plan`` stages any demotion/promotion as one step
#: per ADJACENT-tier hop — an HBM↔disk move always stages through host
#: RAM (the arXiv:2112.01075 discipline: never materialise a second
#: device-resident copy to skip a tier).
SPILL_TIERS = ("hbm", "host", "disk")


@dataclasses.dataclass(frozen=True)
class ReshardStep:
    """One move of a staged redistribution. ``bytes_x``/``bytes_y`` are
    the per-device bytes the step moves over each mesh axis (raw,
    pre-weight — the unit ``matmul_decisions``/obs record);
    ``peak_bytes`` is the per-device bytes live DURING the step (source
    shard + destination buffer + any transient gather), the quantity
    ``config.reshard_peak_budget_bytes`` bounds and MV109 proves."""

    kind: str
    axis: Optional[str]          # "x" / "y" / None (slice, oneshot)
    src_state: str
    dst_state: str
    bytes_x: float
    bytes_y: float
    peak_bytes: float


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    """A compiled src→dst redistribution: the verified step sequence
    plus its exact accounting. ``weighted_cost`` is the per-device
    weighted byte bill (bytes × the topology weight of the axis each
    step rides) the planner prices the move at — bit-identical to the
    legacy closed forms on a uniform mesh when the budget does not
    force staging. ``naive_peak_bytes`` is the modelled peak of the
    legacy ONE-SHOT move for the same pair, the number the staged
    plan's ``peak_bytes`` is the improvement over."""

    src: str
    dst: str
    nbytes: float                # full (padded) array bytes
    grid: Tuple[int, int]
    weights: Tuple[float, float]
    steps: Tuple[ReshardStep, ...]
    weighted_cost: float
    naive_peak_bytes: float

    @property
    def bytes_x(self) -> float:
        return sum(s.bytes_x for s in self.steps)

    @property
    def bytes_y(self) -> float:
        return sum(s.bytes_y for s in self.steps)

    @property
    def peak_bytes(self) -> float:
        return max((s.peak_bytes for s in self.steps), default=0.0)

    @property
    def step_kinds(self) -> Tuple[str, ...]:
        return tuple(s.kind for s in self.steps)

    def fits(self, peak_budget: float) -> bool:
        """Does the plan's peak respect a budget? Budget <= 0 means
        unbounded (always fits)."""
        return peak_budget <= 0 or self.peak_bytes <= peak_budget

    def to_dict(self) -> dict:
        """The stampable/loggable record (``attrs["reshard"]``, obs
        decision records, MV109's hand-stamp surface)."""
        return {"src": self.src, "dst": self.dst,
                "nbytes": self.nbytes,
                "steps": list(self.step_kinds),
                "bytes_by_axis": [self.bytes_x, self.bytes_y],
                "peak_bytes": self.peak_bytes}


def normalize_layout(layout: str) -> Optional[str]:
    """Planner layout string → reshard vocabulary, or None for layouts
    the plan compiler does not own ("other" is costed like "2d" per the
    planner.LAYOUTS contract, so it compiles as "2d")."""
    if layout == "other":
        return "2d"
    return layout if layout in RESHARD_LAYOUTS else None


def _resident(state: str, nbytes: float, gx: int, gy: int) -> float:
    """Per-device resident bytes of a layout state."""
    p = max(gx * gy, 1)
    if state == "rep":
        return nbytes
    if state == "rowx":
        return nbytes / gx
    if state == "coly":
        return nbytes / gy
    return nbytes / p            # 2d / row / col all shard p ways


def _a2a_step(src: str, dst: str, axis: str, nbytes: float,
              gx: int, gy: int) -> ReshardStep:
    """Single-axis all_to_all between p-resident layouts. The byte
    expression is VERBATIM the planner's ``_to_2d_reshard`` /
    ``_reshard_to_axis`` perpendicular-gather closed form, so uniform-
    mesh costs stay bit-identical."""
    p = max(gx * gy, 1)
    g = gy if axis == "y" else gx
    moved = (nbytes / p) * (1 - 1 / g)
    peak = 2.0 * (nbytes / p)    # send shard + receive shard
    return ReshardStep("all_to_all", axis, src, dst,
                       moved if axis == "x" else 0.0,
                       moved if axis == "y" else 0.0, peak)


def _gather_steps(src: str, nbytes: float, gx: int, gy: int,
                  wx: float, wy: float
                  ) -> Tuple[Tuple[ReshardStep, ...], float]:
    """(steps, weighted cost) replicating ``src`` everywhere: one
    gather stage per mesh axis, the stage ORDER (and therefore which
    axis carries the big late stage) chosen exactly the way the
    planner's ``_split_full_mesh`` closed form prices it — the
    expensive axis rides the small FIRST stage, uniform weights keep
    the flat bill's float arithmetic bit-identically (y-first
    attribution)."""
    from matrel_tpu.parallel.planner import _split_full_mesh
    p = gx * gy
    cost, bx, by = _split_full_mesh(nbytes, gx, gy, wx, wy)
    # which order did the split pick? y-first puts the small stage on y
    # (by == src*(gy-1)/p); x-first mirrors it. Uniform weights always
    # attribute y-first (the split's documented convention).
    y_first = by == nbytes * (gy - 1) / p
    if y_first:
        mid = "rowx"
        s1 = ReshardStep("gather", "y", src, mid, 0.0, by,
                         _resident(src, nbytes, gx, gy)
                         + _resident(mid, nbytes, gx, gy))
        s2 = ReshardStep("gather", "x", mid, "rep", bx, 0.0,
                         _resident(mid, nbytes, gx, gy) + nbytes)
    else:
        mid = "coly"
        s1 = ReshardStep("gather", "x", src, mid, bx, 0.0,
                         _resident(src, nbytes, gx, gy)
                         + _resident(mid, nbytes, gx, gy))
        s2 = ReshardStep("gather", "y", mid, "rep", 0.0, by,
                         _resident(mid, nbytes, gx, gy) + nbytes)
    return (s1, s2), cost


def naive_peak_bytes(src: str, dst: str, nbytes: float,
                     gx: int, gy: int) -> float:
    """Modelled peak per-device bytes of the LEGACY one-shot move (a
    single sharding constraint, XLA's own collective choice). Single-
    axis moves lower as an all_to_all (peak 2 shards); any move that
    crosses both mesh axes or raises replication is modelled as
    gather-then-slice — the full array lives as a transient, which is
    exactly the footprint that makes near-HBM operands unmovable and
    the reason this module exists. Conservative on purpose: the budget
    must hold for the worst one-shot lowering, not the luckiest."""
    p = max(gx * gy, 1)
    src_n = normalize_layout(src) or "2d"
    dst_n = normalize_layout(dst) or "2d"
    if src_n == dst_n or p == 1 or src_n == "rep":
        return _resident(dst_n, nbytes, gx, gy)
    single_axis = (frozenset((src_n, dst_n)) in
                   (frozenset(("row", "2d")), frozenset(("col", "2d"))))
    if single_axis:
        return 2.0 * (nbytes / p)
    if dst_n == "rep":
        return _resident(src_n, nbytes, gx, gy) + nbytes
    # cross-axis (row<->col): gather-then-slice transient
    return _resident(src_n, nbytes, gx, gy) + nbytes \
        + _resident(dst_n, nbytes, gx, gy)


def compile_reshard(src: str, dst: str, nbytes: float,
                    gx: int, gy: int,
                    weights: Tuple[float, float] = (1.0, 1.0),
                    peak_budget: float = 0.0) -> ReshardPlan:
    """Compile one src→dst redistribution into its cheapest step
    sequence whose peak fits ``peak_budget`` (<= 0 = unbounded: the
    min-bytes decomposition, cost bit-identical to the legacy closed
    forms). When NO decomposition fits the budget the min-peak plan is
    returned anyway — ``plan.fits(budget)`` is False and MV109 turns
    that into a diagnostic; compile never raises on a hard move.

    The candidate set per pair (docs/RESHARD.md has the derivation):

      same layout        []               (nothing moves)
      rep → L            [slice]          (every device already holds L)
      row↔2d, col↔2d     [all_to_all]     (the single-axis move)
      row↔col            [oneshot]        legacy direct move — fewest
                                          bytes (the ``_split_full_mesh``
                                          bill) but full-gather peak; OR
                         [a2a, a2a]       via 2d — more bytes, peak
                                          2·shard (the bounded plan)
      L → rep            [gather, gather] per-axis stages, order chosen
                                          by the topology weights
    """
    wx, wy = weights
    p = gx * gy
    src_n = normalize_layout(src)
    dst_n = normalize_layout(dst)
    if src_n is None or dst_n is None:
        raise ValueError(
            f"reshard endpoints must be in {RESHARD_LAYOUTS} (or "
            f"'other'), got {src!r} -> {dst!r}")
    nbytes = float(nbytes)

    def plan(steps, cost) -> ReshardPlan:
        return ReshardPlan(src_n, dst_n, nbytes, (gx, gy), (wx, wy),
                           tuple(steps), cost,
                           naive_peak_bytes(src_n, dst_n, nbytes, gx,
                                            gy))

    if src_n == dst_n or p <= 1:
        return plan((), 0.0)
    if src_n == "rep":
        return plan((ReshardStep("slice", None, "rep", dst_n, 0.0, 0.0,
                                 _resident(dst_n, nbytes, gx, gy)),),
                    0.0)
    # single-axis pairs — one all_to_all, no alternative needed
    if frozenset((src_n, dst_n)) == frozenset(("row", "2d")):
        s = _a2a_step(src_n, dst_n, "y", nbytes, gx, gy)
        return plan((s,), s.bytes_y * wy)
    if frozenset((src_n, dst_n)) == frozenset(("col", "2d")):
        s = _a2a_step(src_n, dst_n, "x", nbytes, gx, gy)
        return plan((s,), s.bytes_x * wx)
    if dst_n == "rep":
        steps, cost = _gather_steps(src_n, nbytes, gx, gy, wx, wy)
        return plan(steps, cost)
    # cross-axis: row <-> col
    from matrel_tpu.parallel.planner import _split_full_mesh
    direct_cost, dbx, dby = _split_full_mesh(nbytes / p, gx, gy, wx, wy)
    direct = (ReshardStep("oneshot", None, src_n, dst_n, dbx, dby,
                          naive_peak_bytes(src_n, dst_n, nbytes, gx,
                                           gy)),)
    s1 = _a2a_step(src_n, "2d", "y" if src_n == "row" else "x",
                   nbytes, gx, gy)
    s2 = _a2a_step("2d", dst_n, "y" if dst_n == "row" else "x",
                   nbytes, gx, gy)
    staged = (s1, s2)
    staged_cost = s1.bytes_x * wx + s1.bytes_y * wy \
        + s2.bytes_x * wx + s2.bytes_y * wy
    cands = [(direct, direct_cost), (staged, staged_cost)]
    fitting = [c for c in cands
               if peak_budget <= 0
               or max(s.peak_bytes for s in c[0]) <= peak_budget]
    pool = fitting or cands
    # min weighted cost among fitting candidates; when nothing fits,
    # min PEAK (the closest-to-feasible plan, for MV109 to report)
    if fitting:
        steps, cost = min(pool, key=lambda c: c[1])
    else:
        steps, cost = min(pool,
                          key=lambda c: max(s.peak_bytes for s in c[0]))
    return plan(steps, cost)


def spill_plan(src_tier: str, dst_tier: str, nbytes: float,
               peak_budget: float = 0.0) -> ReshardPlan:
    """Compile one tier demotion/promotion of the result-cache spill
    hierarchy into the step vocabulary — the same ReshardPlan record
    the layout moves use, so MV117 proves spill stamps with the MV109
    machinery and ``plan.fits`` charges the device transient against
    the SAME ``reshard_peak_budget_bytes`` the layout moves respect.

    One step per adjacent-tier hop: ``hbm↔host`` is a ``host`` step
    (peak = the entry's device bytes — the staging buffer),
    ``host↔disk`` is a ``disk`` step (zero device bytes). Step
    ``src_state``/``dst_state`` carry TIER names, not layouts — the
    spill steps never reach ``apply_staged`` (numpy/file IO, not a
    sharding constraint). ``bytes_x`` carries each leg's payload
    bytes (no mesh axis is involved); ``weighted_cost`` is the total
    payload — pricing in milliseconds is the coefficient seam's job
    (``coeffs.spill_cost_ms``), not the topology weights'."""
    if src_tier not in SPILL_TIERS or dst_tier not in SPILL_TIERS:
        raise ValueError(
            f"spill endpoints must be in {SPILL_TIERS}, "
            f"got {src_tier!r} -> {dst_tier!r}")
    nbytes = float(nbytes)
    i, j = SPILL_TIERS.index(src_tier), SPILL_TIERS.index(dst_tier)
    step_dir = 1 if j >= i else -1
    steps = []
    for k in range(i, j, step_dir):
        a, b = SPILL_TIERS[k], SPILL_TIERS[k + step_dir]
        kind = "host" if "hbm" in (a, b) else "disk"
        steps.append(ReshardStep(
            kind, None, a, b, nbytes, 0.0,
            nbytes if kind == "host" else 0.0))
    return ReshardPlan(src_tier, dst_tier, nbytes, (1, 1), (1.0, 1.0),
                       tuple(steps), nbytes * len(steps),
                       naive_peak_bytes=nbytes)


def spill_leg(step: ReshardStep) -> str:
    """A spill step → the coefficient-seam leg token it is priced by
    (``coeffs.SPILL_LEGS``; drift calibrates ``spill:<leg>`` rows):
    direction matters — d2h and h2d ride different DMA paths, disk
    read and write different IO paths."""
    if step.kind == "host":
        return "d2h" if step.src_state == "hbm" else "h2d"
    if step.kind == "disk":
        return "disk_write" if step.dst_state == "disk" else "disk_read"
    raise ValueError(f"not a spill step: {step.kind!r}")


#: Layout each strategy's shard_map in_specs CONSUME an operand at,
#: phrased in the reshard vocabulary, or None where the consumed spec
#: is a partial replication the strategy's own in_spec gather performs
#: (bmm's broadcast side, rmm's per-axis replication) — those are the
#: strategy's working set (MV105's domain), not a reshard. ONE mapping
#: shared by the executor's staged lowering, matmul_decisions' records
#: and MV109, so the three can never disagree about which moves run.
STRATEGY_CONSUMED = {
    "bmm_right": ("row", None),
    "bmm_left": (None, "col"),
    "cpmm": ("2d", None),
    "summa": ("2d", "2d"),
    "rmm": (None, None),
    "xla": (None, None),
    "spgemm": (None, None),
}


def strategy_moves(strategy: str) -> Tuple[Optional[str], Optional[str]]:
    """(dst layout for operand A, for operand B) a strategy's lowering
    re-lays its inputs to — the moves the staged reshard path owns."""
    return STRATEGY_CONSUMED.get(strategy, (None, None))


def staged_matmul_moves(node, mesh, config, layout_memo=None,
                        dtype_memo=None):
    """The operand re-lays a stamped dense matmul's STAGED lowering
    will run under this config, as ``[(operand_index, ReshardPlan)]``
    — ONE derivation shared by the executor (which applies the steps),
    ``planner.matmul_decisions`` (which records them) and MV109 (which
    proves their peaks), so the three can never disagree about which
    moves run. Empty when ``reshard_peak_budget_bytes`` is 0 (the
    default config constructs no plans at all), on a single device,
    for sparse/COO dispatches (their kernels own their layouts), for
    replicated sources (the strategy's in_spec slices those for free),
    and for padded shapes no intermediate state divides evenly."""
    budget = config.reshard_peak_budget_bytes
    if budget <= 0:
        return []
    import numpy as np
    from matrel_tpu.core import mesh as mesh_lib, padding
    from matrel_tpu.parallel import planner
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    if gx * gy <= 1:
        return []
    moves = strategy_moves(node.attrs.get("strategy"))
    if not any(moves):
        return []
    if any(c.kind in ("sparse_leaf", "coo_leaf") for c in node.children):
        return []
    memo = {} if layout_memo is None else layout_memo
    dmemo = {} if dtype_memo is None else dtype_memo
    wts = mesh_lib.axis_weights(mesh, config)
    out = []
    for i, dst in enumerate(moves):
        if dst is None:
            continue
        child = node.children[i]
        src = normalize_layout(
            planner.infer_layout(child, mesh, memo, config))
        if src is None or src == dst or src == "rep":
            continue
        pshape = padding.padded_shape(child.shape, mesh)
        cdt = planner.infer_dtype(child, config, dmemo)
        itemsize = np.dtype(cdt).itemsize if cdt is not None else 4
        nbytes = float(pshape[0]) * pshape[1] * itemsize
        plan = compile_reshard(src, dst, nbytes, gx, gy, wts,
                               peak_budget=float(budget))
        if not plan.steps or not plan_stageable(plan, pshape):
            continue
        out.append((i, plan))
    return out


def root_relay_plan(root, mesh, config, layout_memo=None,
                    dtype_memo=None) -> Optional[ReshardPlan]:
    """The ReshardPlan of a plan ROOT's canonical re-lay under this
    config (the executor constrains every root output to the canonical
    sharding — ``_root_reshard_cost``'s leg), or None when nothing
    stages: budget 0, single device, an already-canonical/replicated
    root, or a padded shape no state divides. ONE derivation shared by
    ``executor._stage_root_relay`` and MV109, the
    ``staged_matmul_moves`` contract."""
    budget = config.reshard_peak_budget_bytes
    if budget <= 0:
        return None
    import numpy as np
    from matrel_tpu.core import mesh as mesh_lib, padding
    from matrel_tpu.parallel import planner
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    if gx * gy <= 1:
        return None
    memo = {} if layout_memo is None else layout_memo
    dmemo = {} if dtype_memo is None else dtype_memo
    src = normalize_layout(planner.infer_layout(root, mesh, memo,
                                                config))
    if src in (None, "2d", "rep"):
        return None
    pshape = padding.padded_shape(root.shape, mesh)
    dt = planner.infer_dtype(root, config, dmemo)
    isz = np.dtype(dt).itemsize if dt is not None else 4
    plan = compile_reshard(src, "2d", float(pshape[0]) * pshape[1] * isz,
                           gx, gy, mesh_lib.axis_weights(mesh, config),
                           peak_budget=float(budget))
    if not plan.steps or not plan_stageable(plan, pshape):
        return None
    return plan


def moves_record(moves) -> Optional[dict]:
    """The observability record of a matmul's staged moves (the
    ``rec["reshard"]`` field of planner.matmul_decisions → obs query
    events, explain(analyze=True), the history roll-up): step kinds,
    raw per-axis bytes, and the worst per-device peak."""
    if not moves:
        return None
    return {
        "steps": [k for _i, p in moves for k in p.step_kinds],
        "bytes_by_axis": [sum(p.bytes_x for _i, p in moves),
                          sum(p.bytes_y for _i, p in moves)],
        "peak_bytes": max(p.peak_bytes for _i, p in moves),
        "moves": [{"operand": i, "src": p.src, "dst": p.dst}
                  for i, p in moves],
    }


# ---------------------------------------------------------------------------
# Execution — staged lowering inside the executor's traced program
# ---------------------------------------------------------------------------


def _state_spec(state: str, mesh):
    """PartitionSpec of a layout state on ``mesh``."""
    from jax.sharding import PartitionSpec as P
    x, y = mesh.axis_names
    return {"2d": P(x, y), "row": P((x, y), None),
            "col": P(None, (x, y)), "rep": P(),
            "rowx": P(x, None), "coly": P(None, y)}[state]


def _state_divisible(state: str, pshape, gx: int, gy: int) -> bool:
    p = gx * gy
    if state == "rep":
        return True
    if state == "row":
        return pshape[0] % p == 0
    if state == "col":
        return pshape[1] % p == 0
    if state == "rowx":
        return pshape[0] % gx == 0
    if state == "coly":
        return pshape[1] % gy == 0
    return pshape[0] % gx == 0 and pshape[1] % gy == 0   # 2d


def plan_stageable(plan: ReshardPlan, pshape) -> bool:
    """Can every intermediate state of the plan actually shard this
    padded shape evenly? Size-1 (vector) dims stay unpadded
    (padding.py), so vector moves keep the legacy path."""
    gx, gy = plan.grid
    states = [plan.src] + [s.dst_state for s in plan.steps]
    return all(_state_divisible(st, pshape, gx, gy) for st in states)


def apply_staged(arr, plan: ReshardPlan, mesh):
    """Lower a compiled plan inside the executor's traced program: one
    sharding constraint per step, each under its ``annotate`` label, so
    XLA emits the step's collective instead of its own one-shot choice
    (an all_to_all chain where the naive constraint may gather). The
    value is bit-identical — resharding never changes entries."""
    import jax
    from jax.sharding import NamedSharding
    from matrel_tpu.utils.profiling import annotate
    for step in plan.steps:
        with annotate(f"matrel.reshard:{step.kind}"):
            arr = jax.lax.with_sharding_constraint(
                arr, NamedSharding(mesh, _state_spec(step.dst_state,
                                                     mesh)))
    return arr
