"""Cost-based physical planning — the MatfastPlanner analogue
(SURVEY.md §2 "Physical planner", §3.2 "strategy choice per multiply").

The reference chooses BMM vs CPMM vs RMM per multiply from dimensions,
sparsity, and partitioning. Here the choice is made per matmul node before
tracing, from the same statistics, using a communication-cost model over the
mesh (comm bytes moved across ICI per strategy — the shuffle-bytes analogue).
The chosen strategy is recorded on the node (``attrs["strategy"]``) so plan
tests can assert it, mirroring the reference's Catalyst plan assertions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
from jax.sharding import Mesh

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core import mesh as mesh_lib
from matrel_tpu.ir.expr import MatExpr


def _bytes(shape: Tuple[int, int], density: float, itemsize: int = 4) -> float:
    return shape[0] * shape[1] * itemsize * max(density, 0.0)


def _to_2d_reshard(bytes_: float, layout: str, gx: int, gy: int) -> float:
    """Per-device ICI bytes to re-lay an operand into the canonical
    P(x, y) tiling that cpmm/summa kernels consume. Replicated operands
    already hold every tile (free); 1D-sharded ones gather along the
    perpendicular axis (the same closed form as the bmm reshard
    terms); canonical/"other" inputs are assumed in place. The gather
    rides ONE mesh axis — ``_to_2d_axis`` names it for the weighted
    model."""
    p = max(gx * gy, 1)
    if layout == "rep":
        return 0.0
    if layout == "row":
        return (bytes_ / p) * (1 - 1 / gy)
    if layout == "col":
        return (bytes_ / p) * (1 - 1 / gx)
    return 0.0


def _to_2d_axis(layout: str) -> str:
    """Mesh axis a ``_to_2d_reshard`` gather moves data over: a
    row-sharded operand gathers its missing columns along y, a
    col-sharded one along x. (For the free layouts the axis is
    irrelevant — the term is 0.)"""
    return "y" if layout == "row" else "x"


def _split_full_mesh(src_bytes: float, gx: int, gy: int,
                     wx: float, wy: float
                     ) -> Tuple[float, float, float]:
    """(weighted cost, x_bytes, y_bytes) of a FULL-MESH collective that
    replicates ``src_bytes`` from an even p-way shard — the bmm
    broadcast, the join all-gathers, and (scaled) the row↔col
    all-to-all. Flat bill: src·(p−1)/p per device.

    On a hierarchical mesh the collective decomposes into one stage per
    axis, and the stage ORDER decides which axis carries the big late
    stage: gathering along axis A first moves src·(gA−1)/p (shards
    still small), the second stage along B moves src·(gB−1)/gB (near
    the full array). The expensive axis therefore rides the FIRST
    stage — exactly what a topology-aware collective (XLA's
    hierarchical DCN all-gathers) does — so the weighted cost is the
    cheaper of the two orders. Both orders sum to the flat bill, so
    uniform weights reproduce it bit-identically (the fast path keeps
    the flat closed form's float arithmetic)."""
    p = gx * gy
    bx_yfirst = src_bytes * (gx - 1) / gx
    by_yfirst = src_bytes * (gy - 1) / p
    if wx == wy:
        # homogeneous mesh: the flat closed form, scaled (scale 1.0 is
        # the pre-topology model, bit for bit). Axis attribution uses
        # the y-first order — arbitrary but deterministic.
        return src_bytes * (p - 1) / p * wx, bx_yfirst, by_yfirst
    bx_xfirst = src_bytes * (gx - 1) / p
    by_xfirst = src_bytes * (gy - 1) / gy
    cost_yf = wx * bx_yfirst + wy * by_yfirst
    cost_xf = wx * bx_xfirst + wy * by_xfirst
    if cost_yf <= cost_xf:
        return cost_yf, bx_yfirst, by_yfirst
    return cost_xf, bx_xfirst, by_xfirst


def _comm_detail(strategy: str, n: int, k: int, m: int,
                 da: float, db: float, gx: int, gy: int,
                 itemsize: int = 4,
                 a_layout: str = "2d", b_layout: str = "2d",
                 alpha_bytes: float = 0.0,
                 weights: Tuple[float, float] = (1.0, 1.0)
                 ) -> Tuple[float, float, float]:
    """(weighted cost, x_bytes, y_bytes) — the one implementation
    behind :func:`comm_cost` (the scalar the planner ranks by) and
    :func:`comm_cost_axes` (the per-axis bytes obs records). Every
    collective leg is attributed to the mesh axis it moves data over
    and billed bytes × weights[axis]; α steps are weighted the same way
    (a ppermute hop over DCN costs its latency ratio too, and a
    full-mesh collective's latency rides its slowest stage). With
    uniform weights every branch reproduces the flat model's floats
    exactly — the per-term arithmetic and summation order are the
    pre-topology code's."""
    a_bytes = _bytes((n, k), da, itemsize)
    b_bytes = _bytes((k, m), db, itemsize)
    c_bytes = _bytes((n, m), 1.0, itemsize)
    p = gx * gy
    wx, wy = weights
    ax = {"x": 0.0, "y": 0.0}

    def leg(bytes_: float, axis: str) -> Tuple[float, float]:
        """(weighted cost, α-step weight) of a single-axis leg."""
        w = wx if axis == "x" else wy
        ax[axis] += bytes_
        return bytes_ * w, w

    def bcast(src_bytes: float) -> Tuple[float, float]:
        """Full-mesh replication of ``src_bytes``; its latency rides
        the slower of its two stages."""
        cost, bx, by = _split_full_mesh(src_bytes, gx, gy, wx, wy)
        ax["x"] += bx
        ax["y"] += by
        return cost, max(wx, wy)

    FREE = (0.0, 0.0)

    def total(*terms, extra_steps_w: float = 0.0):
        steps_w = sum(w for t, w in terms if t > 0.0) + extra_steps_w
        return sum(t for t, _w in terms) + alpha_bytes * steps_w

    def to2d(bytes_: float, layout: str) -> Tuple[float, float]:
        amt = _to_2d_reshard(bytes_, layout, gx, gy)
        return leg(amt, _to_2d_axis(layout)) if amt > 0.0 else FREE

    if strategy == "bmm_right":
        # replicate B everywhere (all-gather to every device) + reshard A
        # to row-sharding over all devices (free when already row-sharded
        # — and when replicated: slicing holds-everything down to a row
        # shard moves nothing, review r5). The A-reshard gathers along y.
        t_bcast = FREE if b_layout == "rep" else bcast(b_bytes)
        t_resh = (FREE if a_layout in ("row", "rep")
                  else leg((a_bytes / p) * (1 - 1 / gy), "y"))
        return total(t_bcast, t_resh), ax["x"], ax["y"]
    if strategy == "bmm_left":
        t_bcast = FREE if a_layout == "rep" else bcast(a_bytes)
        t_resh = (FREE if b_layout in ("col", "rep")
                  else leg((b_bytes / p) * (1 - 1 / gx), "x"))
        return total(t_bcast, t_resh), ax["x"], ax["y"]
    if strategy == "cpmm":
        # A consumed P(x, y) in place (re-laid if 1D-sharded); B resharded
        # to P(y, None): each device gathers b_bytes/gy of B rows
        # replicated along x (an x-axis gather, free when B is already
        # replicated), then a reduce-scatter of partial C over y —
        # the collective that rides the slow axis of a (slices, chips)
        # mesh. rs_c > 0 exactly when the reduce-scatter exists (gy > 1
        # — c_bytes is never 0), so the nonzero-term count in total()
        # already charges its α step.
        t_a = to2d(a_bytes, a_layout)
        t_b = (FREE if b_layout == "rep"
               else leg((b_bytes / gy) * (gx - 1) / gx, "x"))
        t_c = leg((c_bytes / gx) * (gy - 1) / gy, "y")
        return total(t_a, t_b, t_c), ax["x"], ax["y"]
    if strategy in ("rmm", "xla"):
        # all-gather A along y (each device ends with n/gx × k) and B
        # along x; replicated operands already hold their gather target.
        # xla is unknown until the SPMD partitioner runs; modelled as RMM
        # (its usual pick).
        t_a = (FREE if a_layout == "rep"
               else leg((a_bytes / gx) * (gy - 1) / gy, "y"))
        t_b = (FREE if b_layout == "rep"
               else leg((b_bytes / gy) * (gx - 1) / gx, "x"))
        return total(t_a, t_b), ax["x"], ax["y"]
    if strategy == "summa":
        # inputs re-laid to the P(x, y) tiles the ring consumes, then
        # Cannon: g−1 execution steps, each a ppermute of one A tile AND
        # one B tile per device — the stepped strategy the α term exists
        # for (VERDICT r5 "Missing #4"). A tiles shift along y, B tiles
        # along x, so each operand's ring traffic (and its g−1 hop
        # latencies) is billed on its own axis.
        g = max(gx, gy)
        ring_a = (a_bytes / p) * (g - 1)
        ring_b = (b_bytes / p) * (g - 1)
        ax["y"] += ring_a
        ax["x"] += ring_b
        if wx == wy:
            # flat fast path: the pre-topology float arithmetic
            ring = (a_bytes / p + b_bytes / p) * (g - 1) * wx
        else:
            ring = ring_a * wy + ring_b * wx
        cost = ring + total(to2d(a_bytes, a_layout),
                            to2d(b_bytes, b_layout),
                            extra_steps_w=(g - 1) * wy + (g - 1) * wx)
        return cost, ax["x"], ax["y"]
    if strategy == "spgemm":
        # S×S tile-intersection (ops/spgemm.py): both tile stacks are
        # replicated (the broadcast side of the SpMM plan family), the
        # pair compute is device-local and the canonical-output
        # constraint slices a replicated result — no ICI, no steps.
        # nnz-proportionality lives in the FLOP side of the model
        # (matmul_cost's density credit); this prices the comm bill.
        return 0.0, 0.0, 0.0
    raise ValueError(f"unknown strategy {strategy}")


def comm_cost(strategy: str, n: int, k: int, m: int,
              da: float, db: float, gx: int, gy: int,
              itemsize: int = 4,
              a_layout: str = "2d", b_layout: str = "2d",
              alpha_bytes: float = 0.0,
              weights: Tuple[float, float] = (1.0, 1.0),
              coeff: Optional[dict] = None) -> float:
    """Estimated per-device interconnect cost of each strategy, in
    weighted byte-equivalents — or in calibrated MILLISECONDS when a
    ``coeff`` row is passed (see below).

    ``a_layout``/``b_layout`` describe how the operand already lives on the
    mesh ("2d", "row", "col", "rep", "other"): co-partitioned inputs make
    their reshard terms free — the analogue of the reference's
    partitioner-aware planning that skips shuffles for co-partitioned RDDs
    (SURVEY.md §2 "Partitioners", "co-partitioning"). EVERY strategy
    branch reads the layouts (round 5 — previously only the bmm branches
    did): a replicated operand costs nothing to gather for rmm/cpmm
    either, and a 1D-sharded operand pays its way back to the 2D tiling
    cpmm/summa consume. Costs count resharding all-gathers plus
    execution-time collectives; the closed forms recast the reference's
    shuffle-size formulas for a gx × gy mesh.

    ``alpha_bytes`` is the per-collective-STEP latency charge in
    byte-equivalents (the α of an α-β model, VERDICT r5 "Missing #4"):
    each nonzero reshard/gather term counts one step, cpmm's
    reduce-scatter one, and SUMMA's Cannon ring 2·(g−1) ppermute steps
    — so small latency-bound multiplies stop ranking purely by bytes.
    Default 0.0 keeps the pure-β closed forms the chain DP's native
    mirror is equivalence-fuzzed against; the PLANNER passes
    config.comm_alpha_bytes (choose_strategy_ex).

    ``weights`` are the per-mesh-axis inverse-bandwidth weights
    (core/mesh.MeshTopology): each collective leg is billed on the axis
    it actually moves data over, so on a hierarchical ICI/DCN mesh a
    slow-axis reduce-scatter is priced like the DCN traffic it is. The
    default (1.0, 1.0) reproduces the flat byte model bit-identically
    (same per-term arithmetic, same summation order); α steps are
    weighted the same way.

    ``coeff`` (a drift-calibrated row from parallel/coeffs.py — the
    ML018 seam) converts the weighted bill into measured milliseconds:
    the row's ms/est-MiB ratio was calibrated against exactly this
    quantity (the drift samples' ``est_bytes``), so the scale applies
    to what it was measured on. None (the default) keeps the raw
    byte-equivalents every existing caller ranks by — bit-identical.
    """
    cost = _comm_detail(strategy, n, k, m, da, db, gx, gy, itemsize,
                        a_layout, b_layout, alpha_bytes, weights)[0]
    if coeff is not None:
        from matrel_tpu.parallel import coeffs as coeffs_lib
        cm = coeff.get("ms_per_mib")
        if cm is None:
            cm = coeffs_lib.ANALYTIC_MS_PER_MIB
        return float(cm) * (cost / (1 << 20))
    return cost


def comm_cost_axes(strategy: str, n: int, k: int, m: int,
                   da: float, db: float, gx: int, gy: int,
                   itemsize: int = 4,
                   a_layout: str = "2d", b_layout: str = "2d",
                   weights: Tuple[float, float] = (1.0, 1.0),
                   coeff: Optional[dict] = None
                   ) -> Tuple[float, float]:
    """Raw (unweighted) per-device bytes a strategy moves over each
    mesh axis, as (x_bytes, y_bytes) — the per-axis decomposition of
    :func:`comm_cost`'s bill, recorded by ``matmul_decisions`` so
    slow-axis traffic is auditable per decision. ``weights`` only
    influence which stage order a full-mesh collective's bytes are
    attributed under (the split the weighted cost actually uses).
    ``coeff`` (the parallel/coeffs.py seam row, same contract as
    :func:`comm_cost`) scales both axes into calibrated milliseconds;
    None keeps raw bytes — bit-identical."""
    _, bx, by = _comm_detail(strategy, n, k, m, da, db, gx, gy,
                             itemsize, a_layout, b_layout, 0.0, weights)
    if coeff is not None:
        from matrel_tpu.parallel import coeffs as coeffs_lib
        cm = coeff.get("ms_per_mib")
        if cm is None:
            cm = coeffs_lib.ANALYTIC_MS_PER_MIB
        scale = float(cm) / (1 << 20)
        return bx * scale, by * scale
    return bx, by


def _norm_axes(e):
    """Normalise one PartitionSpec entry: 1-tuples to their element,
    multi-axis tuples kept as tuples."""
    if isinstance(e, tuple):
        if len(e) == 0:
            return None
        if len(e) == 1:
            return e[0]
        return tuple(e)
    return e


def _layout_of(node: MatExpr, mesh: Mesh) -> str:
    """How a LEAF operand already lives on the mesh, from its real
    PartitionSpec. Interior nodes go through :func:`infer_layout`."""
    if node.kind != "leaf":
        return "2d"
    spec = node.attrs["matrix"].spec
    x, y = mesh.axis_names
    row = _norm_axes(spec[0] if len(spec) > 0 else None)
    col = _norm_axes(spec[1] if len(spec) > 1 else None)
    if row is None and col is None:
        return "rep"
    flat = ((x, y), (y, x))
    if col is None and row in flat:
        return "row"
    if row is None and col in flat:
        return "col"
    # "2d" means THE CANONICAL spec for this shape on this mesh — the
    # layout autotune probes are measured at (BlockMatrix.random uses
    # canonical specs). On a (2,4) grid that's P(x, y) for matrices and
    # P(x, None) for column vectors; on a 1×N grid it's P(None, y).
    # Anything else — e.g. P(x, None) on a matrix whose canonical spec
    # is P(x, y) — is a real, non-canonical placement: "other"
    # (review r5: reading partials as "2d" let the measured winner be
    # applied to a layout it was never measured on).
    from matrel_tpu.core import padding
    cspec = padding.canonical_spec(padding.padded_shape(node.shape, mesh),
                                   mesh)
    crow = _norm_axes(cspec[0] if len(cspec) > 0 else None)
    ccol = _norm_axes(cspec[1] if len(cspec) > 1 else None)
    return "2d" if (row, col) == (crow, ccol) else "other"


#: Vocabulary of the planner's layout model. "2d" = the canonical spec
#: for the shape on this mesh (what autotune probes measure);
#: "row"/"col" = 1D-sharded over ALL devices on that matrix axis;
#: "rep" = fully replicated; "other" = a real placement matching none
#: of these — costed like "2d" (no credit) but gated OUT of the
#: measured-winner consult.
LAYOUTS = ("2d", "row", "col", "rep", "other")


def infer_layout(node: MatExpr, mesh: Mesh,
                 memo: Optional[dict] = None,
                 config: Optional[MatrelConfig] = None) -> str:
    """Best-effort output layout of ANY expression node's lowering.

    Bottom-up propagation mirroring the executor's actual sharding
    behaviour, exactly the way :func:`infer_dtype` mirrors its dtype
    behaviour (VERDICT r4 "what's missing" #2: the old leaf-only
    ``_layout_of`` hardcoded "2d" for every interior node, so the
    co-partitioning credit — the analogue of the reference's
    partitioner-aware planning that skips shuffles for co-partitioned
    RDDs, SURVEY.md §2 "Partitioners" — never fired for the interior of
    a chain or for a join feeding a matmul):

    - leaves: the real PartitionSpec (``_layout_of``);
    - matmul: by the stamped strategy's out_specs — bmm_right emits
      P((x,y), None) = "row", bmm_left "col"; cpmm/rmm/summa emit
      P(x, y) and the xla fallback constrains to it = "2d"
      (strategies.py out_specs). A matmul dispatching a narrow COO
      SpMV emits replicated results = "rep" — but ONLY where the
      lowering actually pins that: the multi-device compact Pallas
      path's out_specs=P() (executor._coo_compact_sharded) or a
      single-device mesh; the multi-device expanded XLA path leaves
      the sharding to GSPMD and reads "2d" (review r5). An
      UN-annotated matmul reads "2d" — annotate_strategies stamps
      children before parents, so interior nodes are always stamped
      by the time a parent asks;
    - transpose swaps row/col; entrywise ops (scalar, selects,
      join_index) preserve their operand's layout; elemwise preserves
      a layout its operands agree on (XLA aligns the other operand);
    - row/col joins: by the stamped scheme — "align" emits the join
      axis's 1D sharding (executor._join_axis constraint); replicate-
      left/right emit the KEPT side's layout;
    - agg: "all"/"diag" produce a replicated 1x1; row-agg of a
      row-sharded operand stays row-sharded (resp. col);
    - everything else (vec's reshape, solve/inverse local solves,
      materialised value-joins, sparse/coo leaves used densified):
      "2d" — the conservative status quo; free-ness is only ever
      claimed where the lowering pins it.

    Memoised per uid and threaded through annotate_strategies like the
    dtype memo, so planning stays O(nodes).
    """
    if memo is None:
        memo = {}
    cfg = config or default_config()

    def walk(n: MatExpr) -> str:
        if n.uid in memo:
            return memo[n.uid]
        memo[n.uid] = l = _infer(n)
        return l

    def _infer(n: MatExpr) -> str:
        k = n.kind
        if k == "leaf":
            return _layout_of(n, mesh)
        if k == "matmul":
            # the lowering IGNORES the stamped strategy for sparse_leaf
            # matmuls (the SpMM path) and for wide/refused COO matmuls
            # (densify path runs hard-coded "xla") — consulting
            # STRATEGY_OUT_LAYOUT there claimed a "row"/"col" the
            # executor never produces, an unearned free-consume credit
            # (advisor r5 medium). Free-ness is only claimed where the
            # lowering pins it: both off-strategy dispatches read "2d".
            # Branch ORDER mirrors Lowerer._matmul exactly (review r6):
            # spgemm, then coo_leaf on EITHER side, then sparse_leaf —
            # a mixed coo×sparse matmul takes the COO SpMV path (the
            # sparse operand densifies as its dense input), so reading
            # the sparse-first rule there claimed "2d" where the
            # compact path pins a replicated output.
            if _spgemm_matmul(n, cfg):
                return "2d"              # SpGEMM scatters canonically
            if any(c.kind == "coo_leaf" for c in n.children):
                if not _coo_narrow_matmul(n):
                    return "2d"          # densify path: hard-coded xla
                from matrel_tpu.config import pallas_enabled
                # "rep" only where the lowering PINS it: single device,
                # or the compact sharded path (out_specs=P()) is
                # guaranteed. With autotune on, a measured "expanded"
                # winner can reroute the dispatch onto the XLA path at
                # compile time (executor._coo_spmv_stack), whose output
                # sharding is GSPMD-decided — no claim then (review r5).
                if mesh.size == 1 or (pallas_enabled(cfg)
                                      and not cfg.autotune):
                    return "rep"
                return "2d"
            if any(c.kind == "sparse_leaf" for c in n.children):
                return "2d"
            return STRATEGY_OUT_LAYOUT.get(n.attrs.get("strategy"),
                                           "2d")
        if k == "transpose":
            c = walk(n.children[0])
            return {"row": "col", "col": "row"}.get(c, c)
        if k in ("scalar", "select_value", "select_index",
                 "select_block"):
            return walk(n.children[0])
        if k == "rank1":
            return walk(n.children[0])
        if k in ("elemwise", "join_index"):
            la, lb = walk(n.children[0]), walk(n.children[1])
            # broadcast: the full-shaped operand's layout carries
            if k == "elemwise" and n.children[0].shape != n.shape:
                return lb
            if k == "elemwise" and n.children[1].shape != n.shape:
                return la
            if la == lb:
                return la
            # one replicated operand: XLA computes on the other's layout
            if la == "rep":
                return lb
            if lb == "rep":
                return la
            return "2d"
        if k == "agg":
            axis = n.attrs["axis"]
            lc = walk(n.children[0])
            if axis in ("all", "diag"):
                return "rep"
            if axis == "row" and lc == "row":
                return "row"
            if axis == "col" and lc == "col":
                return "col"
            return "2d"
        if k in ("join_rows", "join_cols"):
            rep = n.attrs.get("replicate")
            if rep in ("align", "left", "right"):
                # ONE source of truth for scheme -> output layout,
                # shared with the tiebreak (review r5)
                return _scheme_out_layout(rep, n, walk(n.children[0]),
                                          walk(n.children[1]))
            return "2d"
        return "2d"

    return walk(node)


def _spgemm_matmul(n: MatExpr, config=None) -> bool:
    """Will this matmul dispatch the S×S tile-intersection SpGEMM?
    Consults executor._spgemm_dispatch — the single source of truth
    shared with the lowering (the _coo_dispatch_plan idiom), so the
    estimator, the threshold compare and any future refusal logic can
    never drift from what actually executes. Lazily imported to keep
    the executor→planner import direction."""
    l, r = n.children
    if (l.kind in ("sparse_leaf", "coo_leaf")
            and r.kind in ("sparse_leaf", "coo_leaf")):
        from matrel_tpu import executor as _exec
        return _exec._spgemm_dispatch(n, config)
    return False


def _coo_narrow_matmul(n: MatExpr) -> bool:
    """Will this matmul dispatch the narrow COO SpMV path (whose sharded
    compact executor emits REPLICATED results, out_specs=P())? Consults
    executor._coo_dispatch_plan itself — the single source of truth —
    so the plan-REFUSAL fallback (build_spmv_plan returning None on
    pathological padding, which densifies onto the 2d XLA path) is
    honoured too, not just the width threshold (review r5). The plan it
    builds is memoised on the matrix and needed at lowering anyway.
    Lazily imported to keep the executor→planner import direction."""
    l, r = n.children
    if l.kind == "coo_leaf" or r.kind == "coo_leaf":
        from matrel_tpu import executor as _exec
        return _exec._coo_dispatch_plan(n) is not None
    return False


def infer_dtype(node: MatExpr, config: Optional[MatrelConfig] = None,
                memo: Optional[dict] = None):
    """Statically-known output dtype of ANY expression node, or None.

    Bottom-up propagation mirroring the Lowerer's actual dtype
    behaviour (VERDICT r3 #3: the old leaf-only walk meant autotune's
    measured table was consulted only for leaf×leaf multiplies — the
    interior products of a reordered chain, the recurring shapes the
    closed loop exists for, always fell back to the byte model):

    - leaves: the matrix payload dtype;
    - transpose/scalar/agg/vec/select_*: dtype-preserving (the executor
      casts aggregates and scalar ops back to the operand dtype);
    - matmul: accumulates in f32 when bf16 is involved, then casts back
      to the common input dtype under ``config.keep_input_dtype``
      (executor.py matmul cast) — so bf16·bf16 is bf16 with the default
      config, f32 otherwise;
    - elemwise/rank1/join_value: jnp promotion of the operands (the
      value-join lowering casts its streamed result to exactly this);
    - solve/inverse: computed in f32, cast back to the input dtype
      under keep_input_dtype (solve: only when both operands agree);
    - join_rows/join_cols with a CALLABLE merge, and anything else
      unknown: None (conservative — the autotune consult is skipped).

    Results are memoised per uid: expressions are DAGs and chains
    re-walk shared operands. Pass a shared ``memo`` dict to amortise the
    walk across calls (annotate_strategies threads one through the whole
    pass, making planning O(nodes) instead of O(nodes^2) for deep
    chains — review r4).
    """
    cfg = config or default_config()
    import jax.numpy as jnp
    import numpy as np
    if memo is None:
        memo = {}

    def walk(n: MatExpr):
        if n.uid in memo:
            return memo[n.uid]
        memo[n.uid] = d = _infer(n)
        return d

    def _promote(*ds):
        if any(d is None for d in ds):
            return None
        out = ds[0]
        for d in ds[1:]:
            out = jnp.promote_types(out, d)
        return out

    def _infer(n: MatExpr):
        k = n.kind
        if k in ("leaf", "sparse_leaf", "coo_leaf"):
            m = n.attrs["matrix"]
            if k == "coo_leaf":
                # COOMatrix carries no dtype attribute; its payloads
                # are f32 by construction (core/coo.py from_edges) and
                # its SpMV paths accumulate f32. CHECKED here with an
                # explicit raise (VERDICT r4 "what's weak" #4; not an
                # assert — must survive python -O, review r5) so a
                # future dtype-bearing COOMatrix fails loudly instead
                # of silently keying the wrong table row.
                vals = getattr(m, "vals", None)
                if vals is not None and np.dtype(vals.dtype) != np.dtype(
                        "float32"):
                    raise TypeError(
                        f"COOMatrix payload dtype {vals.dtype} != "
                        "float32: infer_dtype's COO rule (and the SpMV "
                        "f32 accumulation it mirrors) no longer holds "
                        "— teach both paths the new dtype together")
            return getattr(m, "dtype", np.dtype("float32"))
        if k in ("transpose", "scalar", "agg", "vec", "select_value",
                 "select_index", "select_block"):
            return walk(n.children[0])
        if k == "matmul":
            # a stamped integer tier keeps its int32 accumulator as the
            # RESULT dtype (the exact integer algebra flows to
            # consumers — aggregates, further int-tier products —
            # without a lossy f32 round-trip); bf16 tiers accumulate
            # f32 and store the f32 input dtype, same as the default
            # lowering, so only the int tiers change the answer here
            if n.attrs.get("precision_tier") in ("int32", "int8"):
                return np.dtype("int32")
            da, db = walk(n.children[0]), walk(n.children[1])
            if da is None or db is None:
                return None
            if cfg.keep_input_dtype and da == db:
                return da
            if "bfloat16" in (np.dtype(da).name, np.dtype(db).name):
                return np.dtype("float32")
            return _promote(da, db)
        if k in ("elemwise", "rank1", "join_value"):
            return _promote(*(walk(c) for c in n.children))
        if k == "inverse":
            da = walk(n.children[0])
            if da is None:
                return None
            return da if cfg.keep_input_dtype else np.dtype("float32")
        if k == "solve":
            da, db = walk(n.children[0]), walk(n.children[1])
            if da is None or db is None:
                return None
            if cfg.keep_input_dtype and da == db:
                return da
            return np.dtype("float32")
        if k in ("join_rows", "join_cols", "join_index"):
            # structured merges promote; user callables may not
            if n.attrs.get("merge_kind") is not None:
                return _promote(*(walk(c) for c in n.children))
            return None
        return None

    return walk(node)


# -- precision tiers (round 8: per-query accuracy SLAs) --------------------
#
# Precision is a first-class planner dimension (ROADMAP open item 3;
# "Large Scale Distributed Linear Algebra With TPUs", arXiv:2112.09017):
# the MXU's native numeric format is bf16, and f32-class accuracy is
# RECOVERABLE from bf16 passes by splitting each f32 operand into bf16
# slices (hi = bf16(x), lo = bf16(x − hi)) and accumulating the
# significant cross-products in f32 — keeping hi·hi + hi·lo + lo·hi
# (3 MXU passes) drops only the ~2^-16-relative lo·lo term. Integer-
# shaped workloads (triangle counts, PageRank iteration counts, boolean
# semiring joins) are EXACT on the int paths. The chooser below picks
# the cheapest tier that satisfies the query's SLA; the lowering
# (executor._matmul → ops/precision.py) emits the multi-pass
# decomposition; the vocabulary/cost tables here are the one source of
# truth for the cost model, matmul_decisions, and MV108.

#: Tier vocabulary. "f32" = today's single full-precision product
#: (config.matmul_precision, i.e. XLA's 6-pass bf16 emulation on TPU);
#: "bf16x1" = one native bf16 MXU pass; "bf16x3" = the 3-pass
#: split-summation correction (~f32 accuracy); "int32"/"int8" =
#: integer-exact MXU paths (int32 accumulate).
PRECISION_TIERS = ("f32", "bf16x1", "bf16x3", "int32", "int8")

#: MXU passes a tier's lowering emits per matmul — the est pass count
#: matmul_decisions records. f32 counts XLA's HIGHEST-precision 6-pass
#: bf16 emulation of an f32 dot on the MXU (the TPU cost model the
#: planner targets; on CPU backends f32 is one native pass and the
#: numbers are a modelling convention, not a measurement).
TIER_PASSES = {"f32": 6, "bf16x1": 1, "bf16x3": 3, "int32": 1,
               "int8": 1}

#: Relative MXU time per MAC (f32-single-pass-rate units): bf16 passes
#: run at 2× the f32-class rate, so time = passes / 2 for the bf16
#: tiers; int8 runs at 4× (the int8 MXU path); int32 is conservatively
#: f32-class. This is the "3× the MACs at 2× the MXU rate" billing —
#: the model prices real pass counts, never a free speedup.
TIER_COMPUTE_UNITS = {"f32": 3.0, "bf16x1": 0.5, "bf16x3": 1.5,
                      "int32": 1.0, "int8": 0.25}

#: HBM bytes per operand element a tier's lowering reads: bf16x1
#: streams half-width operands; bf16x3 keeps BOTH bf16 slices resident
#: (hi + lo = 4 B — the split halves the per-pass bytes, not the
#: total); int8 quarters them.
TIER_ITEMSIZE = {"f32": 4, "bf16x1": 2, "bf16x3": 4, "int32": 4,
                 "int8": 1}

#: Documented per-MAC relative error bound of each tier (docs/
#: PRECISION.md): max-abs error of an (n,k)x(k,m) product is bounded by
#: TIER_EPS[tier] · k · max|A| · max|B|. The int tiers are EXACT for
#: integer-valued operands whose products/sums fit int32 (and, for the
#: f32-stored result, 2^24).
TIER_EPS = {"f32": 2.0 ** -20, "bf16x1": 2.0 ** -8,
            "bf16x3": 2.0 ** -15, "int32": 0.0, "int8": 0.0}

#: Explicit-dtype SLA spellings → the tier they pin.
_DTYPE_SLA_TIER = {"float32": "f32", "bfloat16": "bf16x1",
                   "bf16x3": "bf16x3", "int32": "int32", "int8": "int8"}


def tier_matmul_cost(tier: str, n: int, k: int, m: int,
                     da: float = 1.0, db: float = 1.0) -> float:
    """Estimated execution cost of one (n×k)·(k×m) multiply at a
    precision tier, in f32-FLOP-equivalents: the REAL per-pass MAC work
    (sparsity-credited, scaled by the tier's relative MXU time) plus
    the per-tier HBM operand/output traffic in FLOP-equivalents. This
    is the quantity the SLA chooser ranks tiers by — a 3-pass bf16
    multiply is billed 1.5× the single-pass f32-rate MACs at half the
    per-pass operand bytes, not assumed free."""
    from matrel_tpu.ir import stats
    compute = (stats.matmul_cost(n, k, m, da, db)
               * TIER_COMPUTE_UNITS[tier])
    isz = TIER_ITEMSIZE[tier]
    hbm = (n * k * max(da, 0.0) + k * m * max(db, 0.0)) * isz \
        + n * m * 4.0                     # result stored full-width
    return compute + stats.HBM_FLOPS_PER_BYTE * hbm


def tier_error_bound(tier: str, k: int, amax: float = 1.0,
                     bmax: float = 1.0) -> float:
    """Documented max-abs error bound of a k-deep product at a tier
    (TIER_EPS closed form) — shared by bench.py --precision and the
    soak battery so the asserted bound IS the documented one."""
    return TIER_EPS[tier] * float(k) * float(amax) * float(bmax)


def sla_allowed_tiers(sla: str, integral: bool,
                      config: Optional[MatrelConfig] = None) -> tuple:
    """Tiers admissible under an SLA for a dense float-f32 matmul whose
    operands are (``integral``=True) provably integer-valued. The SLA
    is an accuracy FLOOR — every allowed tier meets or beats it:

      exact  f32 always; int tiers when integral (integer-exact).
      high   + bf16x3 (~f32 accuracy at bf16 MXU rate).
      fast   + bf16x1 (documented bf16 bound).
      <dtype> exactly the pinned tier (bypasses the enable gates:
              an explicit ask is an ask).

    Tier enable flags (config.precision_enable_bf16/_int) drop their
    families from the NAMED levels; "default" returns () — nothing is
    ever stamped, the pre-tier lowering runs bit-identically.
    """
    cfg = config or default_config()
    if sla == "default":
        return ()
    pinned = _DTYPE_SLA_TIER.get(sla)
    if pinned is not None:
        return (pinned,)
    tiers = ["f32"]
    if cfg.precision_enable_int and integral:
        tiers.append("int32")
    if cfg.precision_enable_bf16:
        if sla in ("high", "fast"):
            tiers.append("bf16x3")
        if sla == "fast":
            tiers.append("bf16x1")
    return tuple(tiers)


def sla_compute_factor(config: Optional[MatrelConfig] = None) -> float:
    """Relative MXU time per MAC of the tier a dense float matmul would
    run at under the session SLA, vs the default lowering — the
    ``flop_scale`` the chain DP's step cost uses so parenthesisation
    ranks honestly when the query's FLOPs retire at bf16 rate
    (ir/chain.optimal_order; 1.0 under "default", bit-identical)."""
    cfg = config or default_config()
    tiers = sla_allowed_tiers(cfg.precision_sla, False, cfg)
    if not tiers:
        return 1.0
    best = min(tiers, key=lambda t: TIER_COMPUTE_UNITS[t])
    return TIER_COMPUTE_UNITS[best] / TIER_COMPUTE_UNITS["f32"]


#: Largest accumulated |value| the int32 tiers may provably reach: the
#: int32 accumulator's range. The chooser only auto-picks an int tier
#: when k*bound(A)*bound(B) (stats.integral_abs_bound) fits -- "exact"
#: must never silently wrap (review r8).
INT32_ACC_MAX = float(2 ** 31 - 1)


def int_tier_fits(node: MatExpr, tier: str,
                  integral_memo: Optional[dict] = None) -> bool:
    """Is an int tier PROVABLY overflow-free for this matmul? The
    accumulated product is bounded by k*bound(A)*bound(B)
    (stats.integral_abs_bound); int8 additionally needs each operand's
    entries to fit the int8 cast. Unknown bounds -> False (the chooser
    conservatively keeps f32; an unprovable explicit int pin is MV108's
    business). Shared by the chooser and the MV108 pass so gate and
    verifier cannot disagree."""
    from matrel_tpu.ir import stats
    a, b = node.children
    ba = stats.integral_abs_bound(a, integral_memo)
    bb = stats.integral_abs_bound(b, integral_memo)
    if ba is None or bb is None:
        return False
    if tier == "int8" and (ba > 127.0 or bb > 127.0):
        return False

    def exact_operand(child, bound) -> bool:
        # a FLOAT-computed integral operand is only exactly integer
        # while it fits f32's contiguous-integer range (2^24); an
        # int-tiered product carries int32 exactness instead
        if child.attrs.get("precision_tier") in ("int32", "int8"):
            return bound <= INT32_ACC_MAX
        return bound <= 2.0 ** 24

    if not (exact_operand(a, ba) and exact_operand(b, bb)):
        return False
    return a.shape[1] * ba * bb <= INT32_ACC_MAX


def choose_precision_tier(node: MatExpr,
                          config: Optional[MatrelConfig] = None,
                          dtype_memo: Optional[dict] = None,
                          integral_memo: Optional[dict] = None
                          ) -> Optional[str]:
    """The tier one matmul node will execute at under the session SLA,
    or None for the default (untier) lowering. None whenever the node
    is not a dense product the tier lowering owns:

    - "default" SLA: nothing is ever stamped (bit-identity contract);
    - sparse/COO dispatches (SpGEMM, SpMV, SpMM): their kernels own
      their numerics (bf16-split passes, f32 accumulate) already;
    - statically-unknown operand dtypes: no claim without proof;
    - non-f32 floats (bf16 leaves): already at MXU-native width.

    Integer algebra stays closed: when BOTH operands are provably
    integer-valued (integer dtype from an inner int-tier product, OR an
    integral f32 leaf -- any mix), the exact int32 tier continues,
    gated by the int32-accumulator overflow proof (int_tier_fits) --
    an unprovable magnitude keeps f32, never a silent wrap. Explicit
    int dtype SLAs pin their tier on integer data (the caller's
    claim); a float pin on integer data stamps nothing (the untier
    promotion runs).

    Among the SLA's admissible tiers (sla_allowed_tiers) the cheapest
    by tier_matmul_cost wins, deterministic ties by vocabulary order.
    ``integral_memo`` amortises the integrality/magnitude walks across
    a planning pass (the dtype-memo precedent -- review r8).
    """
    import numpy as np
    cfg = config or default_config()
    sla = cfg.precision_sla
    if sla == "default" or node.kind != "matmul":
        return None
    a, b = node.children
    if _spgemm_matmul(node, cfg) or any(
            c.kind in ("sparse_leaf", "coo_leaf") for c in node.children):
        return None
    da = infer_dtype(a, cfg, dtype_memo)
    db = infer_dtype(b, cfg, dtype_memo)
    if da is None or db is None:
        return None
    da, db = np.dtype(da), np.dtype(db)
    f32 = np.dtype("float32")

    def _ok(d):
        return d == f32 or np.issubdtype(d, np.integer)

    if not (_ok(da) and _ok(db)):
        return None
    from matrel_tpu.ir import stats
    pinned = _DTYPE_SLA_TIER.get(sla)
    any_int_dtype = (np.issubdtype(da, np.integer)
                     or np.issubdtype(db, np.integer))
    if any_int_dtype:
        # integer-dtype operands ARE integral (inner int-tier
        # products); a mixed f32 side must prove its own integrality
        # for the exact algebra to continue
        integral = all(
            np.issubdtype(d, np.integer)
            or stats.infer_integral(c, integral_memo)
            for d, c in ((da, a), (db, b)))
        if pinned in ("int32", "int8"):
            return pinned            # explicit ask: the caller's claim
        if pinned is not None:
            return None              # float pin on int data: untier
        if integral and cfg.precision_enable_int \
                and int_tier_fits(node, "int32", integral_memo):
            return "int32"
        return None
    integral = stats.infer_integral(node, integral_memo)
    tiers = sla_allowed_tiers(sla, integral, cfg)
    # the overflow proof gates the AUTO int pick; an explicit int pin
    # stays (MV108 warns/errors on unprovable or overflowing stamps)
    if pinned is None:
        tiers = tuple(t for t in tiers
                      if t not in ("int32", "int8")
                      or int_tier_fits(node, t, integral_memo))
    if not tiers:
        return None
    n, k = a.shape
    m = b.shape[1]
    dens_a = a.density if a.density is not None else 1.0
    dens_b = b.density if b.density is not None else 1.0
    best, best_cost = None, None
    for t in tiers:
        c = tier_matmul_cost(t, n, k, m, dens_a, dens_b)
        if best_cost is None or c < best_cost:
            best, best_cost = t, c
    return best


def strategy_hbm_bytes(strategy: str, pn: int, pk: int, pm: int,
                       gx: int, gy: int, itemsize: int = 4) -> float:
    """Per-device HBM working set of one strategy's shard_map program,
    in bytes: operand shards × their replication factor + the output
    accumulator, at the padded dims the specs actually carve
    (strategies.py in_specs/out_specs). Dense bytes on purpose — every
    strategy here consumes materialised dense operands, so a density
    credit would under-count exactly the plans the feasibility gate
    exists to drop (per-chip memory is THE binding constraint for
    distributed linear algebra on TPUs, arXiv:2112.09017).

    xla is 0: the GSPMD partitioner picks its own decomposition and is
    the fallback that must survive every gate; spgemm is 0 too — its
    working set is the sparse pair list, priced by spgemm_estimates,
    not a dense replication factor."""
    p = max(gx * gy, 1)
    a = float(pn) * pk * itemsize
    b = float(pk) * pm * itemsize
    c = float(pn) * pm * itemsize
    if strategy == "bmm_right":
        return b + a / p + c / p          # B replicated everywhere
    if strategy == "bmm_left":
        return a + b / p + c / p
    if strategy == "cpmm":
        # A P(x,y); B P(y,None) — replicated along x; partial C
        # (pn/gx × pm) lives until the reduce-scatter
        return a / p + b / gy + c / gx
    if strategy == "rmm":
        # the replication strategy: A holds every y-slice, B every
        # x-slice (VERDICT r5 Weak #3 — the case that OOMs first)
        return a / gx + b / gy + c / p
    if strategy == "summa":
        # P(x,y) tiles double-buffered through the ppermute ring
        return 2.0 * (a / p + b / p) + c / p
    return 0.0                            # xla / spgemm / unknown


def admissible(strategy: str, pn: int, pk: int, pm: int,
               gx: int, gy: int, itemsize: int = 4,
               hbm_budget_bytes: int = 0) -> bool:
    """Can this strategy's shard_map specs divide the padded dims evenly
    — and, when ``hbm_budget_bytes`` > 0, does its per-device working
    set (strategy_hbm_bytes) fit the budget?

    Size-1 (vector/scalar) dims stay unpadded (padding.py), so matvec-shaped
    multiplies are only eligible for strategies that keep those dims
    replicated — everything else falls through to the XLA SPMD path.
    The HBM gate (VERDICT r5 Weak #3 / Next #6) drops over-replicating
    plans BEFORE costing: a byte model that ranks RMM cheapest on ICI
    traffic must never hand the executor a plan whose replicated
    operands cannot exist on the chip. xla is exempt — it is the
    fallback GSPMD decomposes itself.
    """
    p = gx * gy
    if (hbm_budget_bytes > 0 and strategy != "xla"
            and strategy_hbm_bytes(strategy, pn, pk, pm, gx, gy,
                                   itemsize) > hbm_budget_bytes):
        return False
    if strategy == "bmm_right":
        return pn % p == 0
    if strategy == "bmm_left":
        return pm % p == 0
    if strategy == "cpmm":
        return pn % gx == 0 and pk % gy == 0 and pm % gy == 0
    if strategy == "rmm":
        return pn % gx == 0 and pm % gy == 0
    if strategy == "summa":
        return (gx == gy and pn % gx == 0 and pm % gy == 0
                and pk % gx == 0 and pk % gy == 0)
    return True  # xla


def choose_strategy(node: MatExpr, mesh: Mesh,
                    config: Optional[MatrelConfig] = None,
                    dtype_memo: Optional[dict] = None,
                    layout_memo: Optional[dict] = None) -> str:
    """Pick the cheapest admissible strategy for one matmul node."""
    return choose_strategy_ex(node, mesh, config, dtype_memo,
                              layout_memo)[0]


def _root_reshard_cost(strategy: str, n: int, m: int,
                       gx: int, gy: int,
                       transposed: bool = False,
                       weights: Tuple[float, float] = (1.0, 1.0)
                       ) -> float:
    """Per-device ICI bytes to re-lay a strategy's OUTPUT to the
    canonical sharding. The executor constrains every ROOT output to
    canonical_sharding (lower_multi), so a root-level bmm really pays
    this row/col→2d move after computing; interior consumers instead
    see the producer's layout through their own per-layout credit and
    must NOT be charged here (round 5). ``transposed`` marks an ODD
    number of transposes between this matmul and the root: the
    transpose swaps row↔col, so the re-lay gathers along the OTHER
    perpendicular axis (review r5 — matters on non-square grids).
    Same closed forms as comm_cost's reshard terms; the gather is a
    single-axis collective, billed at that axis's topology weight."""
    p = gx * gy
    c_bytes = _bytes((n, m), 1.0)
    out_row = (strategy == "bmm_right") != transposed
    if strategy == "bmm_right" or strategy == "bmm_left":
        g_perp = gy if out_row else gx
        w = weights[1] if out_row else weights[0]
        return (c_bytes / p) * (1 - 1 / g_perp) * w
    return 0.0                         # cpmm/rmm/summa/xla emit 2d


#: Output layout each matmul strategy emits (strategies.py out_specs) —
#: the ONE mapping shared by infer_layout's matmul rule and the
#: consumer-aware tiebreak (review r5).
STRATEGY_OUT_LAYOUT = {"bmm_right": "row", "bmm_left": "col",
                       "cpmm": "2d", "rmm": "2d", "summa": "2d",
                       "xla": "2d", "spgemm": "2d"}

#: Near-tie band for the consumer-aware STRATEGY tiebreak (the matmul
#: analogue of JOIN_TIE_REL): candidates within this margin of the
#: cheapest may be flipped toward the layout the consumer reads free.
STRATEGY_TIE_REL = 0.10


def _hint_tiebreak(costs: dict, best, out_layout_of,
                   hint: Optional[str], tie_rel: float):
    """Shared near-tie flip for the consumer-aware tiebreaks (join
    schemes and matmul strategies — review r5: one band/epsilon rule,
    not two drifting copies): among candidates within ``tie_rel`` of
    the cheapest, return the cheapest one whose output layout (per
    ``out_layout_of``) matches ``hint``; otherwise ``best``."""
    if hint is None:
        return best
    near = sorted(
        (s for s in costs
         if costs[s] <= costs[best] * (1.0 + tie_rel) + 1e-9),
        key=costs.get)
    for s in near:
        if out_layout_of(s) == hint:
            return s
    return best


def choose_strategy_ex(node: MatExpr, mesh: Mesh,
                       config: Optional[MatrelConfig] = None,
                       dtype_memo: Optional[dict] = None,
                       layout_memo: Optional[dict] = None,
                       root_output: bool = False,
                       root_transposed: bool = False,
                       consumer_hint: Optional[str] = None,
                       root_scale: float = 1.0,
                       cost_detail: Optional[dict] = None
                       ) -> Tuple[str, str]:
    """(strategy, source) for one matmul node. ``source`` records WHY —
    the observability side of the closed loop (physical EXPLAIN prints
    it): "override" (config.strategy_override), "dispatch" (an S×S
    SpGEMM the lowering takes regardless of the byte model), "measured"
    (autotune table hit), "model" (byte-model argmin), "default"
    (single device / no admissible candidates).

    ``cost_detail`` (an out-param dict, the return tuple stays a
    2-tuple for the existing callers — analysis passes unpack it
    positionally) reports WHICH cost model priced a "model" decision
    when ``config.coeff_planner_enable``: ``{"cost": "measured"}``
    when the learned-coefficient ranking ran (every admissible
    candidate had a warm parallel/coeffs.py row), ``{"cost":
    "analytic"}`` when any candidate was cold and the closed forms
    decided (docs/COST_MODEL.md)."""
    cfg = config or default_config()
    if _spgemm_matmul(node, cfg):
        # S×S below the density crossover: the LOWERING dispatches the
        # tile-intersection SpGEMM unconditionally (_spgemm_dispatch is
        # the shared truth, the _coo_dispatch_plan pattern), so the
        # stamp must say so — obs/explain then report what executes.
        # Checked BEFORE strategy_override: an override cannot reroute
        # this dispatch (same as the COO SpMV path), so stamping the
        # override string would misreport what runs and price a comm
        # bill that never executes. Forcing the densify path is the
        # documented kill switch config.spgemm_density_threshold = 0.
        # Its comm bill is comm_cost("spgemm") = 0 (replicated tile
        # stacks, device-local pairs); the nnz-proportional FLOP side
        # lives in spgemm_estimates.
        return "spgemm", "dispatch"
    if cfg.strategy_override != "auto":
        return cfg.strategy_override, "override"
    a, b = node.children
    n, k = a.shape
    _, m = b.shape
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    if gx * gy == 1:
        return "xla", "default"  # single device: plain local dot
    from matrel_tpu.core import padding
    pn, pk = padding.padded_shape((n, k), mesh)
    _, pm = padding.padded_shape((k, m), mesh)
    la = infer_layout(a, mesh, layout_memo, cfg)
    lb = infer_layout(b, mesh, layout_memo, cfg)
    if cfg.autotune:
        # MEASURED winner beats the byte model (closed autotune loop);
        # admissibility is re-checked against THESE dims — the table
        # keys by shape class, the divisibility constraint is exact.
        # Only consulted when BOTH operand dtypes are statically known
        # (leaves, possibly through transposes) and equal: keying a
        # bf16 multiply into the f32 table row — or measuring f32
        # operands for a bf16 chain step — would violate the
        # measured-beats-model premise. Density-credited operands skip
        # the table too (advisor r3): it measures DENSE probes, and the
        # byte model's density credit would be bypassed on a hit.
        # Layout gates the consult the same way (VERDICT r4 "what's
        # missing" #3): the table measures canonically-2D-sharded
        # operands, so a winner is only applied when BOTH operands
        # actually lie 2D — a row-sharded bmm output or a replicated
        # leaf gets the byte model, whose per-layout credit sees the
        # real placement. No measured winner is ever applied to a
        # layout it wasn't measured on.
        dta = infer_dtype(a, cfg, dtype_memo)
        dtb = infer_dtype(b, cfg, dtype_memo)
        dense = ((a.density is None or a.density >= 1.0)
                 and (b.density is None or b.density >= 1.0))
        if (dense and dta is not None and dta == dtb
                and la == "2d" and lb == "2d"):
            from matrel_tpu.parallel import autotune
            best = autotune.lookup_or_measure(n, k, m, mesh, str(dta),
                                              cfg)
            if (best is not None
                    and admissible(best, pn, pk, pm, gx, gy,
                                   itemsize=np.dtype(dta).itemsize,
                                   hbm_budget_bytes=cfg.hbm_budget_bytes)
                    and not (root_output
                             and STRATEGY_OUT_LAYOUT.get(best) != "2d")):
                # a measured 1D-emitting winner is NOT applied at a
                # plan ROOT: the probes never pay the canonical-output
                # re-lay the executor performs there, so the premise
                # doesn't cover this context (review r5) — the model,
                # which charges _root_reshard_cost, decides instead
                return best, "measured"
    da, db = a.density, b.density
    cands = {}
    a_bytes = _bytes((n, k), da)
    b_bytes = _bytes((k, m), db)
    # per-step latency charge (α-β model, VERDICT r5 "Missing #4") —
    # the planner is the one caller that prices REAL choices, so it
    # passes the configured α; the chain DP's comm proxy stays β-only
    # (its native mirror is fuzzed against the alpha-free closed forms)
    al = cfg.comm_alpha_bytes
    # per-axis topology weights (core/mesh.MeshTopology): on a
    # hierarchical ICI/DCN mesh every candidate's collective legs are
    # billed on the axis they actually ride — the piece that keeps the
    # ranking honest the moment the fabric stops being homogeneous
    wts = mesh_lib.axis_weights(mesh, cfg)
    # BMM is only admissible when the broadcast side fits the threshold —
    # the reference's broadcast-variable size gate.
    if b_bytes <= cfg.broadcast_threshold_bytes:
        cands["bmm_right"] = comm_cost("bmm_right", n, k, m, da, db, gx, gy,
                                       a_layout=la, b_layout=lb,
                                       alpha_bytes=al, weights=wts)
    if a_bytes <= cfg.broadcast_threshold_bytes:
        cands["bmm_left"] = comm_cost("bmm_left", n, k, m, da, db, gx, gy,
                                      a_layout=la, b_layout=lb,
                                      alpha_bytes=al, weights=wts)
    cands["cpmm"] = comm_cost("cpmm", n, k, m, da, db, gx, gy,
                              a_layout=la, b_layout=lb, alpha_bytes=al,
                              weights=wts)
    cands["rmm"] = comm_cost("rmm", n, k, m, da, db, gx, gy,
                             a_layout=la, b_layout=lb, alpha_bytes=al,
                             weights=wts)
    # SUMMA needs a square grid and pays latency per step; prefer it when
    # replication would not fit HBM (big square operands).
    if gx == gy and gx > 1:
        cands["summa"] = comm_cost("summa", n, k, m, da, db, gx, gy,
                                   a_layout=la, b_layout=lb,
                                   alpha_bytes=al, weights=wts)
    # the HBM gate reads the real accumulation itemsize where it is
    # statically known (bf16 operands still accumulate/store f32-sized
    # working sets only when promotion says so — infer_dtype is the
    # one mirror of that); unknown dtypes assume f32
    dt_out = infer_dtype(node, cfg, dtype_memo)
    isz = np.dtype(dt_out).itemsize if dt_out is not None else 4
    # a stamped precision tier changes the operand WIDTH the strategy's
    # working set is built from (bf16x1 replicates half the bytes, so
    # plans the f32 budget refuses become feasible; int8 a quarter) —
    # the gate must see the tier's real itemsize, not the f32 one
    tier = node.attrs.get("precision_tier")
    if tier in TIER_ITEMSIZE:
        isz = TIER_ITEMSIZE[tier]
    cands = {s: c for s, c in cands.items()
             if admissible(s, pn, pk, pm, gx, gy, itemsize=isz,
                           hbm_budget_bytes=cfg.hbm_budget_bytes)}
    if root_output:
        # the executor re-lays ROOT outputs to the canonical sharding;
        # a bmm's 1D-sharded result pays that move, 2d emitters do
        # not. ``root_scale`` (annotate's _child_root_scale) weights
        # the charge by how much of the root's output bytes this
        # node's layout actually reaches — half under a root elemwise
        # (at most one operand's re-lay occurs), the element-count
        # ratio under shape-changing wrappers (ADVICE r5).
        cands = {s: c + _root_reshard_cost(s, n, m, gx, gy,
                                           root_transposed,
                                           weights=wts) * root_scale
                 for s, c in cands.items()}
    if not cands:
        return "xla", "default"
    if cfg.coeff_planner_enable:
        # learned-coefficient ranking (parallel/coeffs.py — the ML018
        # seam; docs/COST_MODEL.md): when EVERY admissible candidate
        # has a warm calibration row for this (strategy[@tier],
        # shape-class, backend) population, rank by predicted
        # milliseconds — ms/GFLOP × FLOPs + ms/est-MiB × the weighted
        # bill each candidate was just priced at (the exact quantity
        # the drift auditor calibrated the ratio against, root-reshard
        # charge included). Partial coverage stays analytic: comparing
        # one candidate's measured milliseconds against another's raw
        # byte-equivalents would be a units error, not a ranking —
        # the cold-class fallback the placement model set.
        from matrel_tpu.parallel import coeffs as coeffs_lib
        from matrel_tpu.obs import drift as drift_lib
        import jax
        cost_src = "analytic"
        path = drift_lib.table_path(cfg)
        cls = drift_lib.shape_class((n, k, m))
        backend = jax.default_backend()
        gf = 2.0 * n * k * m / 1e9
        measured: Optional[dict] = {}
        for s, c in cands.items():
            row = coeffs_lib.strategy_row(s, cls, backend, path,
                                          tier=tier or "")
            if row is None or row["count"] < cfg.coeff_min_samples:
                measured = None
                break
            measured[s] = coeffs_lib.predict_ms(row, gf, c)
        if measured:
            cands = measured
            cost_src = "measured"
        if cost_detail is not None:
            cost_detail["cost"] = cost_src
    best = min(cands, key=cands.get)
    if not root_output:
        # consumer-aware tiebreak (the matmul analogue of the join
        # scheme's, round 5): among near-tied candidates prefer the one
        # whose output layout the PARENT consumes in place — e.g. a
        # left-child multiply flips an ε-worse bmm_right over rmm
        # because the parent reads its row-sharded result for free.
        best = _hint_tiebreak(cands, best, STRATEGY_OUT_LAYOUT.get,
                              consumer_hint, STRATEGY_TIE_REL)
    return best, "model"


def _reshard_to_axis(bytes_: float, layout: str, axis: str,
                     gx: int, gy: int,
                     weights: Tuple[float, float] = (1.0, 1.0),
                     config: Optional[MatrelConfig] = None) -> float:
    """Per-device ICI bytes to re-lay an operand as 1D-sharded over all
    devices along ``axis`` ("row"/"col") from its current ``layout`` —
    the join-side analogue of comm_cost's per-layout reshard terms,
    billed at the topology weight of the mesh axis each move rides.

    With ``config.reshard_peak_budget_bytes`` > 0 the price comes from
    the REAL ReshardPlan the lowering will run (parallel/reshard.py)
    instead of these closed forms: for single-axis moves the two are
    bit-identical by construction (the plan compiler reuses this
    module's float expressions verbatim — equality-tested), and for
    the one move where they can differ — the opposite-1D flip whose
    bounded decomposition routes through 2d when the direct move's
    transient would blow the budget — the plan's honestly higher
    staged bill is what the join scheme must rank by. The default
    config never constructs a plan (closed forms stay the fast path).
    """
    p = max(gx * gy, 1)
    wx, wy = weights
    if layout == axis or layout == "rep":
        return 0.0
    if config is not None and config.reshard_peak_budget_bytes > 0:
        from matrel_tpu.parallel import reshard as reshard_lib
        return reshard_lib.compile_reshard(
            layout, axis, bytes_, gx, gy, weights,
            peak_budget=float(config.reshard_peak_budget_bytes)
        ).weighted_cost
    if layout in ("2d", "other"):
        # gather along the perpendicular mesh axis (same closed form as
        # comm_cost's bmm reshard terms). "other" (a real non-canonical
        # placement) is costed exactly like "2d" per the LAYOUTS
        # contract — no credit, no penalty (review r5: this branch and
        # the doc must agree)
        g_perp = gy if axis == "row" else gx
        w_perp = wy if axis == "row" else wx
        return (bytes_ / p) * (1 - 1 / g_perp) * w_perp
    # opposite 1D sharding: all-to-all redistribution of the local
    # shard — a full-mesh collective with source bytes_/p, split per
    # axis like the broadcasts (_split_full_mesh; flat form preserved
    # at uniform weights)
    return _split_full_mesh(bytes_ / p, gx, gy, wx, wy)[0]


#: Near-tie band for the consumer-aware join-scheme tiebreak: schemes
#: within this relative margin of the cheapest are considered equal-cost
#: and the one whose OUTPUT layout the consumer reads in place wins.
JOIN_TIE_REL = 0.10


def _scheme_out_layout(scheme: str, node: MatExpr,
                       la: str, lb: str) -> str:
    """Output layout each join scheme produces (mirrors infer_layout's
    join case, phrased over candidate schemes instead of the stamped
    one)."""
    if scheme == "align":
        return "row" if node.kind == "join_rows" else "col"
    return lb if scheme == "left" else la


def choose_join_scheme(node: MatExpr, mesh: Mesh,
                       config: Optional[MatrelConfig] = None,
                       layout_memo: Optional[dict] = None,
                       consumer_hint: Optional[str] = None) -> str:
    """Scheme selection for row/col index joins — the reference's
    cost-based choice of which operand to replicate (SURVEY.md §2
    "Physical: relational execs": "join-scheme selection to minimize
    replication"), v3 with PER-LAYOUT cost terms (VERDICT r3 #5; v2
    credited only fully-replicated operands).

    Three schemes, costed like comm_cost does for matmuls:
      "left"/"right" — all-gather that side everywhere (free when it is
        already replicated). The KEPT side pays nothing: with the other
        operand fully replicated, the broadcast-merge computes on the
        kept side's existing layout and the output inherits it;
      "align" — replicate NOTHING: both operands re-laid 1D-sharded
        along the join axis, the join computes shard-locally. This is
        the scheme that wins when a large operand's existing row/col
        sharding can be consumed in place (its reshard term is zero)
        and also for similar-sized 2D operands, where two cheap
        redistributions beat one full broadcast.
    Bytes are density-credited. Returns "left" | "right" | "align".

    ``consumer_hint`` (VERDICT r4 #7) is the layout the PARENT node
    would consume in place ("row" for a matmul's left operand, "col"
    for its right — the bmm credits); among schemes within JOIN_TIE_REL
    of the cheapest, the one whose output layout matches the hint wins,
    so an align output feeding a matmul is not thrown away for a
    same-cost replicate whose output the parent must reshard."""
    a, b = node.children
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    p = max(gx * gy, 1)
    axis = "row" if node.kind == "join_rows" else "col"
    la = infer_layout(a, mesh, layout_memo, config)
    lb = infer_layout(b, mesh, layout_memo, config)
    a_bytes = _bytes(a.shape, a.density if a.density is not None else 1.0)
    b_bytes = _bytes(b.shape, b.density if b.density is not None else 1.0)
    # same topology weighting as the matmul model: a replicate scheme's
    # full-mesh all-gather and align's per-axis reshards are billed on
    # the axes they ride, so joins stop broadcasting over the DCN axis
    # when an in-slice redistribution is cheaper
    wts = mesh_lib.axis_weights(mesh, config)

    def ag(bytes_: float, layout: str) -> float:
        if layout == "rep":
            return 0.0
        return _split_full_mesh(bytes_, gx, gy, wts[0], wts[1])[0]

    cost = {
        "left": ag(a_bytes, la),
        "right": ag(b_bytes, lb),
    }
    # align needs the join axis to actually shard p ways: with fewer
    # rows/cols than devices the 1D constraint degenerates to XLA
    # involuntary full rematerialization (replicate both operands, then
    # repartition) — strictly worse than the broadcast it was meant to
    # avoid (review r4, reproduced on the 8-device CPU mesh)
    # the join constructors enforce equal extents on the join axis
    # (relational/ops.py), so reading operand a alone is sound; assert
    # it here so a future join kind with unequal extents cannot
    # silently break the gate (VERDICT r4 "what's weak" #5)
    a_extent = a.shape[0] if axis == "row" else a.shape[1]
    b_extent = b.shape[0] if axis == "row" else b.shape[1]
    if a_extent != b_extent:      # explicit raise, not assert: must
        raise ValueError(         # survive python -O (review r5)
            f"{node.kind} operands disagree on the join axis extent "
            f"({a_extent} vs {b_extent}) — the align gate assumes the "
            f"constructor-enforced equality (relational/ops.py)")
    if a_extent >= p:
        cost["align"] = (
            _reshard_to_axis(a_bytes, la, axis, gx, gy, weights=wts,
                             config=config)
            + _reshard_to_axis(b_bytes, lb, axis, gx, gy, weights=wts,
                               config=config))
    best = min(cost, key=cost.get)
    return _hint_tiebreak(
        cost, best, lambda s: _scheme_out_layout(s, node, la, lb),
        consumer_hint, JOIN_TIE_REL)


def _child_root_scale(e: MatExpr, i: int, scale: float) -> float:
    """Fraction of the plan-ROOT canonical-resharding charge child
    ``i``'s output layout is exposed to (0.0 = none — the v1 bool,
    review r5, is now a weight, ADVICE r5). The executor re-lays only
    the ROOT output (lower_multi), so exposure flows through
    entrywise/layout-preserving wrappers — a scalar op over a bmm
    output still pays the row→canonical move at the root — and stops
    under a matmul/join/agg, whose own cost model sees the child's
    layout instead. Two corrections over the bool version:

    * elemwise/join_index exposed BOTH children to the FULL charge,
      though at most one root re-lay ever occurs; which operand's
      layout carries is unknowable here (children are not yet
      annotated), so each side now carries half — except under
      broadcast, where only the full-shaped operand's layout can flow
      to the root at all (infer_layout's elemwise rule) and it carries
      the whole charge;
    * the charge was priced on the child's own (n, m) bytes even when
      a shape-changing wrapper sits between it and the root — the real
      re-lay acts on the WRAPPER's output. The element-count ratio
      rescales it (identity for today's shape-preserving masked
      selects; exact for transpose and any future shrinking select)."""
    if scale <= 0.0:
        return 0.0

    def _elems(shape) -> float:
        return float(max(shape[0] * shape[1], 1))

    k = e.kind
    child = e.children[i]
    if k in ("scalar", "select_value", "select_index",
             "select_block", "transpose"):
        return scale * _elems(e.shape) / _elems(child.shape)
    if k == "rank1":
        return scale if i == 0 else 0.0
    if k in ("elemwise", "join_index"):
        if k == "elemwise" and e.children[0].shape != e.children[1].shape:
            return scale if child.shape == e.shape else 0.0
        return scale * 0.5
    return 0.0


def _child_layout_hints(e: MatExpr, mesh: Optional[Mesh] = None,
                        config: Optional[MatrelConfig] = None,
                        dtype_memo: Optional[dict] = None
                        ) -> Tuple[Optional[str], ...]:
    """Layout each child's output would be consumed in-place at by this
    node, for the consumer-aware tiebreaks: a matmul reads its left
    operand row-sharded for free (bmm_right's reshard credit) and its
    right operand col-sharded (bmm_left). A hint is only emitted when
    the parent could actually RUN that bmm — its broadcast side under
    the threshold, not a sparse/COO dispatch (whose SpMV/SpMM
    lowerings ignore the hinted layout entirely) — review r5 — AND
    admissible on the mesh's grid for the parent's PADDED dims
    (ADVICE r5: a bmm whose sharded dim does not divide by the device
    count never runs, so its hint steered the child toward a layout
    the parent could not consume). An unusable hint flips the child to
    a worse pick AND leaves the parent paying a 1D→2d re-lay, a
    double loss. ``mesh=None`` skips only the divisibility gate (for
    callers without one in hand). Other parents express no
    preference."""
    if e.kind == "matmul":
        if any(c.kind in ("sparse_leaf", "coo_leaf") for c in e.children):
            return (None, None)
        cfg = config or default_config()
        a, b = e.children
        b_fits = _bytes(b.shape, b.density) <= cfg.broadcast_threshold_bytes
        a_fits = _bytes(a.shape, a.density) <= cfg.broadcast_threshold_bytes
        right_ok, left_ok = b_fits, a_fits
        if mesh is not None:
            from matrel_tpu.core import padding
            gx, gy = mesh_lib.mesh_grid_shape(mesh)
            n, k = a.shape
            m = b.shape[1]
            pn, pk = padding.padded_shape((n, k), mesh)
            _, pm = padding.padded_shape((k, m), mesh)
            # the SAME itemsize choose_strategy_ex will gate the parent
            # with (review r6): an itemsize-4 hint on f64 operands
            # would steer the child toward a layout the parent's own
            # budget gate then refuses — the double loss again
            dt_out = infer_dtype(e, cfg, dtype_memo)
            isz = np.dtype(dt_out).itemsize if dt_out is not None else 4
            budget = cfg.hbm_budget_bytes
            right_ok = right_ok and admissible(
                "bmm_right", pn, pk, pm, gx, gy, itemsize=isz,
                hbm_budget_bytes=budget)
            left_ok = left_ok and admissible(
                "bmm_left", pn, pk, pm, gx, gy, itemsize=isz,
                hbm_budget_bytes=budget)
        return ("row" if right_ok else None,    # parent bmm_right viable
                "col" if left_ok else None)     # parent bmm_left viable
    return (None,) * len(e.children)


def annotate_strategies(e: MatExpr, mesh: Mesh,
                        config: Optional[MatrelConfig] = None,
                        _dtype_memo: Optional[dict] = None,
                        _layout_memo: Optional[dict] = None,
                        _consumer_hint: Optional[str] = None,
                        _root_scale: float = 1.0,
                        _root_swap: bool = False,
                        _integral_memo: Optional[dict] = None) -> MatExpr:
    """Bottom-up pass stamping attrs['strategy'] on every matmul node
    and attrs['replicate'] on every row/col index join. One dtype memo
    and one layout memo are threaded through the whole pass and seeded
    as each rewritten node is produced, so every choose_strategy
    dtype/layout lookup is O(1). ``_consumer_hint`` carries the parent's
    in-place-consumable layout down to BOTH join-scheme and matmul
    strategy near-ties (_hint_tiebreak); a matmul whose output layout
    flows to the plan ROOT is additionally charged the fraction
    ``_root_scale`` (_child_root_scale) of the canonical-output reshard
    its lowering really pays there (_root_reshard_cost)."""
    memo = {} if _dtype_memo is None else _dtype_memo
    lmemo = {} if _layout_memo is None else _layout_memo
    imemo = {} if _integral_memo is None else _integral_memo
    hints = _child_layout_hints(e, mesh, config, dtype_memo=memo)
    swap = _root_swap != (e.kind == "transpose")   # odd transposes flip
    new_children = tuple(
        annotate_strategies(c, mesh, config, memo, lmemo, h,
                            _child_root_scale(e, i, _root_scale), swap,
                            imemo)
        for i, (c, h) in enumerate(zip(e.children, hints)))
    if any(nc is not oc for nc, oc in zip(new_children, e.children)):
        e = e.with_children(new_children)
    if e.kind == "matmul" and "precision_tier" not in e.attrs:
        # tier BEFORE strategy: the strategy choice's HBM-feasibility
        # gate reads the stamped tier's operand itemsize. Under the
        # "default" SLA choose_precision_tier returns None and nothing
        # is stamped — the bit-identity contract (plan snapshots
        # unchanged, zero new attrs). The shared integral memo keeps
        # the integrality/magnitude walks O(nodes) over deep chains.
        tier = choose_precision_tier(e, config, dtype_memo=memo,
                                     integral_memo=imemo)
        if tier is not None:
            e = e.with_attrs(precision_tier=tier)
    if e.kind == "matmul" and "strategy" not in e.attrs:
        # cost-model provenance (docs/COST_MODEL.md): only requested —
        # and only stamped — under coeff_planner_enable, so default
        # plans carry zero new attrs (the bit-identity snapshot
        # contract)
        detail = ({} if config is not None
                  and config.coeff_planner_enable else None)
        strat, source = choose_strategy_ex(e, mesh, config,
                                           dtype_memo=memo,
                                           layout_memo=lmemo,
                                           root_output=_root_scale > 0.0,
                                           root_transposed=_root_swap,
                                           consumer_hint=_consumer_hint,
                                           root_scale=_root_scale,
                                           cost_detail=detail)
        stamp = {"strategy": strat, "strategy_source": source}
        if detail is not None and detail.get("cost"):
            stamp["cost_model"] = detail["cost"]
        e = e.with_attrs(**stamp)
        if strat == "spgemm":
            # registry dispatch (ops/kernel_registry.py): stamp WHICH
            # kernel the S×S lowering will run — chosen from the
            # registry's cost estimates over the operand pair's
            # structure class, overridden by a measured autotune
            # winner (the MV106 "measured"-stamp precedent) or the
            # config forcing knob. The lowering honors the stamp and
            # MV110 verifies it; the shared chooser
            # (executor.spgemm_kernel_choice) is the single source of
            # truth so the three can never drift.
            from matrel_tpu import executor as _exec
            kid, struct, ksrc = _exec.spgemm_kernel_choice(e, config,
                                                           mesh)
            e = e.with_attrs(spgemm_kernel=kid,
                             spgemm_structure=struct,
                             spgemm_kernel_source=ksrc)
    if e.kind in ("join_rows", "join_cols") and "replicate" not in e.attrs:
        e = e.with_attrs(replicate=choose_join_scheme(
            e, mesh, config, layout_memo=lmemo,
            consumer_hint=_consumer_hint))
    infer_dtype(e, config, memo)     # seed this (possibly new-uid) node
    infer_layout(e, mesh, lmemo, config)
    return e


def matmul_decisions(root: MatExpr, mesh: Mesh,
                     config: Optional[MatrelConfig] = None) -> list:
    """Per-matmul planner-decision records for an ANNOTATED plan — the
    observability feed (obs/ event log, explain(analyze=True)): for
    every matmul node, the chosen strategy, WHY (strategy_source), the
    operand layouts the choice saw, the model's estimated per-device
    ICI bytes for that strategy under those layouts, and the multiply's
    FLOPs. Pure read — never re-chooses; shared DAG nodes appear once.
    Dispatches the byte model ignores (sparse/COO fast paths) are
    tagged ``dispatch`` so readers don't attribute ICI estimates to
    lowerings that bypass the strategy."""
    cfg = config or default_config()
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    topo = mesh_lib.mesh_topology(mesh, cfg)
    wts = topo.axis_weights
    lmemo: dict = {}
    dmemo: dict = {}
    out: list = []
    seen: set = set()
    # fused-region prepass (ir/fusion.py stamps live on region ROOTS,
    # which may be elementwise/agg nodes): map each anchor matmul's uid
    # to its region record so the matmul's decision carries the chosen
    # boundary — fused_region, member census, est saved dispatches/HBM
    # — into the obs event stream. Empty with fusion off (no stamps):
    # zero extra fields, the bit-identity obs contract.
    fused_of: dict = {}
    fseen: set = set()

    def fwalk(node: MatExpr):
        if node.uid in fseen:
            return
        fseen.add(node.uid)
        for c in node.children:
            fwalk(c)
        a_uid = node.attrs.get("fused_anchor")
        if "fused_region" in node.attrs and a_uid is not None:
            fused_of[a_uid] = node.attrs

    fwalk(root)

    def walk(n: MatExpr):
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            walk(c)
        if n.kind != "matmul":
            return
        a, b = n.children
        nn, kk = a.shape
        mm = b.shape[1]
        rec = {"uid": n.uid, "dims": [nn, kk, mm],
               "strategy": n.attrs.get("strategy", "xla"),
               "source": n.attrs.get("strategy_source", "unknown"),
               "flops": 2.0 * nn * kk * mm}
        cm = n.attrs.get("cost_model")
        if cm:
            # WHICH cost model priced the ranking: "measured" (learned
            # parallel/coeffs.py coefficients) or "analytic" (closed
            # forms) — absent with coeff_planner_enable off, the
            # bit-identity obs contract (docs/COST_MODEL.md)
            rec["cost"] = cm
        tier = n.attrs.get("precision_tier")
        if tier is not None:
            # the chosen precision tier + what it really costs/promises
            # (obs events, explain(analyze=True), history --summary,
            # the drift auditor's tier-keyed calibration rows)
            rec["precision_tier"] = tier
            rec["est_passes"] = TIER_PASSES.get(tier)
            rec["est_tier_cost"] = tier_matmul_cost(
                tier, nn, kk, mm,
                a.density if a.density is not None else 1.0,
                b.density if b.density is not None else 1.0) \
                if tier in TIER_COMPUTE_UNITS else None
            rec["est_rel_err"] = TIER_EPS.get(tier)
        # result-cache reuse (serve/): an operand that entered planning
        # as a materialized-result leaf never re-pays its subplan — the
        # decision record says which side(s) got that credit, so the
        # obs roll-up can attribute layout credits to cache reuse
        rc_ops = [bool(c.kind == "leaf" and c.attrs.get("result_cache"))
                  for c in n.children]
        if any(rc_ops):
            rec["rc_operands"] = rc_ops
        # cross-query CSE reuse (serve/mqo.py): an operand fed by a
        # batch-shared hoisted interior gets the same layout credit as
        # a result-cache leaf — the decision record says which side(s)
        cse_ops = [bool(c.kind == "leaf" and c.attrs.get("cse"))
                   for c in n.children]
        if any(cse_ops):
            rec["cse_operands"] = cse_ops
        if _spgemm_matmul(n, cfg):
            # the S×S tile-intersection dispatch: record the estimated
            # FLOPs/HBM bytes it avoids vs the densify fallback — the
            # obs/ surface (query events, explain(analyze=True),
            # history roll-up) where the SpGEMM win is visible
            from matrel_tpu import executor as _exec
            rec["dispatch"] = "spgemm"
            rec.update(_exec.spgemm_estimates(n, cfg))
            # registry dispatch record: WHICH kernel runs, over WHAT
            # structure class, and whether a measurement or the cost
            # estimate picked it — the obs surface (query events,
            # explain(analyze=True), history's kernel census, the
            # drift auditor's spgemm:<kernel_id> calibration rows)
            kid = n.attrs.get("spgemm_kernel")
            struct = n.attrs.get("spgemm_structure")
            ksrc = n.attrs.get("spgemm_kernel_source")
            if kid is None:
                kid, struct, ksrc = _exec.spgemm_kernel_choice(
                    n, cfg, mesh)
            rec["kernel_id"] = kid
            rec["structure_class"] = struct
            rec["kernel_source"] = ksrc
            rec["est_vs_measured"] = ("measured" if ksrc == "measured"
                                      else "estimate")
        elif any(c.kind == "coo_leaf" for c in n.children):
            # checked BEFORE sparse_leaf — Lowerer._matmul's order: a
            # mixed coo×sparse matmul runs the COO SpMV path (review r6)
            rec["dispatch"] = ("coo_spmv" if _coo_narrow_matmul(n)
                               else "densify")
        elif any(c.kind == "sparse_leaf" for c in n.children):
            rec["dispatch"] = "spmm"
        else:
            la = infer_layout(a, mesh, lmemo, cfg)
            lb = infer_layout(b, mesh, lmemo, cfg)
            rec["layouts"] = [la, lb]
            try:
                # est_ici_bytes stays in RAW byte-equivalents (flat
                # weights) whatever the mesh: its consumers (history's
                # MiB column, cross-session comparisons) sum it as
                # bytes moved, and a weighted value would inflate by
                # the weight ratio (review r7)
                rec["est_ici_bytes"] = comm_cost(
                    rec["strategy"], nn, kk, mm, a.density, b.density,
                    gx, gy, a_layout=la, b_layout=lb,
                    alpha_bytes=cfg.comm_alpha_bytes)
                # per-axis decomposition of the same bill (raw bytes,
                # pre-weight): the auditable record of how much of the
                # decision's traffic rides each mesh axis — history's
                # roll-up turns this into the slow-axis regression
                # signal (docs/TOPOLOGY.md)
                rec["est_axis_bytes"] = list(comm_cost_axes(
                    rec["strategy"], nn, kk, mm, a.density, b.density,
                    gx, gy, a_layout=la, b_layout=lb, weights=wts))
                if not topo.uniform:
                    # the quantity the weighted ranking actually
                    # minimised — a separate field, separate unit
                    rec["est_weighted_cost"] = comm_cost(
                        rec["strategy"], nn, kk, mm, a.density,
                        b.density, gx, gy, a_layout=la, b_layout=lb,
                        alpha_bytes=cfg.comm_alpha_bytes, weights=wts)
                    rec["axis_weights"] = list(wts)
                    rec["topology_source"] = topo.source
                if cfg.reshard_peak_budget_bytes > 0:
                    # the staged reshard moves this decision's lowering
                    # will actually run (parallel/reshard.py — the ONE
                    # derivation the executor and MV109 share): step
                    # kinds, raw per-axis bytes, worst per-device peak
                    from matrel_tpu.parallel import reshard as _resh
                    rr = _resh.moves_record(_resh.staged_matmul_moves(
                        n, mesh, cfg, lmemo, dmemo))
                    if rr is not None:
                        rec["reshard"] = rr
            except ValueError:       # an override string the model
                rec["est_ici_bytes"] = None   # doesn't know
        ivm = root.attrs.get("ivm_patch")
        if isinstance(ivm, dict):
            # this plan IS a delta patch (serve/ivm.py stamps the root;
            # docs/IVM.md): every decision record carries the pricing
            # that chose patching over recompute, so the obs surfaces
            # (query events, explain(analyze=True), the history IVM
            # roll-up) can audit the patch-vs-recompute call the way
            # they audit strategy choices
            rec["delta_rule"] = ivm.get("rule")
            rec["delta_est_saved_flops"] = ivm.get("est_saved_flops")
        fr = fused_of.get(n.uid)
        if fr is not None:
            # this matmul anchors a fused region: the decision record
            # carries the chosen boundary so obs/history/drift see it
            # (the drift auditor keys these rows ``fused:<sig>`` — a
            # miscalibrated fused estimate must not poison the
            # per-strategy calibration rows). setdefault on the HBM
            # field: a SpGEMM anchor's est_saved_hbm_bytes already
            # means "saved vs densify" and keeps that meaning.
            rec["fused_region"] = fr.get("fused_region")
            rec["fused_census"] = dict(fr.get("fused_census") or {})
            rec["est_saved_dispatches"] = fr.get(
                "fused_saved_dispatches")
            rec.setdefault("est_saved_hbm_bytes",
                           fr.get("fused_saved_hbm_bytes"))
        out.append(rec)

    walk(root)
    return out
