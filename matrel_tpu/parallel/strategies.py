"""Physical matmul strategies — the TPU rebuild of MatRel's strategy trio
(SURVEY.md §2 "Physical: Broadcast-MM / Cross-Product-MM / Replication-MM").

Reference semantics → collective duality (SURVEY.md §5 "Distributed comm
backend"):

  BMM  (broadcast small operand; map-side multiply, zero shuffle of the big
        side)            →  replicate small operand across the mesh; big side
                            row-sharded over ALL devices; local dot; no
                            execution-time collective.
  CPMM (outer-product: co-shuffle A's k-blocks with B's k-blocks, multiply,
        reduceByKey sums partial C blocks — reduce-scatter-shaped)
                         →  contraction dim sharded on mesh axis y; local
                            partial C; `psum_scatter` over y.
  RMM  (replicate blocks so each reducer owns every input of its C block;
        one cogroup shuffle — all-gather-shaped)
                         →  A replicated along y, B replicated along x
                            (the resharding IS the all-gather); local full-k
                            dot produces C sharded P(x, y) with no further
                            comm.
  SUMMA/Cannon (not in the reference; the long-context/ring analogue,
        SURVEY.md §5 "Long-context")
                         →  A, B, C all stay P(x, y); k advances by a
                            `ppermute` ring; memory O(N²/P) per chip.

Each strategy is a function (a, b, mesh, precision) -> c over the full padded
arrays, implemented with `shard_map` so the collective schedule is explicit
and assertable from HLO (SURVEY.md §4 "plan shape" tests).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

from matrel_tpu.utils import compat
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from matrel_tpu.utils.compat import shard_map

from matrel_tpu.config import MatrelConfig, default_config

STRATEGIES = ("bmm_left", "bmm_right", "cpmm", "rmm", "summa", "xla")


def _precision(cfg: Optional[MatrelConfig]):
    cfg = cfg or default_config()
    return getattr(jax.lax.Precision, cfg.matmul_precision.upper(),
                   jax.lax.Precision.HIGHEST)


def _acc_dtype(a, b):
    # accumulate bf16 inputs in f32 on the MXU
    if a.dtype == jnp.bfloat16 or b.dtype == jnp.bfloat16:
        return jnp.float32
    # integer inputs accumulate at least int32 (the MXU's int8×int8→
    # int32 contract; an int8 accumulator would wrap on the first k>1
    # contraction) — the precision-tier int paths rely on this
    if (jnp.issubdtype(a.dtype, jnp.integer)
            and jnp.issubdtype(b.dtype, jnp.integer)):
        return jnp.result_type(a.dtype, b.dtype, jnp.int32)
    return jnp.result_type(a.dtype, b.dtype)


def _local_dot(a, b, prec, out_dtype):
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        precision=prec, preferred_element_type=out_dtype)


def matmul_xla(a: jax.Array, b: jax.Array, mesh: Mesh,
               config: Optional[MatrelConfig] = None) -> jax.Array:
    """Fallback: one einsum, XLA SPMD chooses the collectives.

    Output constrained to the canonical 2D sharding so downstream ops
    compose; inputs keep whatever sharding they arrived with.
    """
    x, y = mesh.axis_names
    out = jnp.einsum("nk,km->nm", a, b, precision=_precision(config),
                     preferred_element_type=_acc_dtype(a, b))
    return jax.lax.with_sharding_constraint(out, NamedSharding(mesh, P(x, y)))


def matmul_bmm(a: jax.Array, b: jax.Array, mesh: Mesh,
               config: Optional[MatrelConfig] = None,
               broadcast_side: str = "right") -> jax.Array:
    """Broadcast-MM: replicate the small operand, row-shard the big one over
    the whole mesh, multiply map-side. Zero execution-time collectives —
    the broadcast happens once in input resharding, like Spark's torrent
    broadcast of the small matrix (SURVEY.md §2 BMM)."""
    x, y = mesh.axis_names
    prec = _precision(config)
    out_dtype = _acc_dtype(a, b)
    if broadcast_side == "right":
        in_specs = (P((x, y), None), P())   # big A row-sharded, B everywhere
        out_specs = P((x, y), None)

        def kernel(ab, bb):
            return _local_dot(ab, bb, prec, out_dtype)
    else:
        in_specs = (P(), P(None, (x, y)))   # A everywhere, big B col-sharded
        out_specs = P(None, (x, y))

        def kernel(ab, bb):
            return _local_dot(ab, bb, prec, out_dtype)

    f = shard_map(kernel, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return f(a, b)


def matmul_cpmm(a: jax.Array, b: jax.Array, mesh: Mesh,
                config: Optional[MatrelConfig] = None) -> jax.Array:
    """Cross-Product-MM: contraction dim sharded over mesh axis y.

    Each device holds A[n/gx, k/gy] and B[k/gy, m]; the local outer-product
    partial C[n/gx, m] is summed-and-scattered over y with `psum_scatter` —
    the direct analogue of the reference's reduceByKey over partial C blocks
    (SURVEY.md §2 CPMM)."""
    x, y = mesh.axis_names
    prec = _precision(config)
    out_dtype = _acc_dtype(a, b)

    def kernel(ab, bb):
        partial = _local_dot(ab, bb, prec, out_dtype)  # (n/gx, m) partial
        # reduce-scatter partial C over the contraction axis; scatter cols
        return jax.lax.psum_scatter(partial, y, scatter_dimension=1,
                                    tiled=True)

    f = shard_map(kernel, mesh=mesh,
                  in_specs=(P(x, y), P(y, None)),
                  out_specs=P(x, y))
    return f(a, b)


def matmul_rmm(a: jax.Array, b: jax.Array, mesh: Mesh,
               config: Optional[MatrelConfig] = None) -> jax.Array:
    """Replication-MM: A replicated along y, B replicated along x; each
    device owns every input of its C tile and computes it in one local dot.
    The input resharding is the all-gather-shaped cogroup of the reference
    (SURVEY.md §2 RMM)."""
    x, y = mesh.axis_names
    prec = _precision(config)
    out_dtype = _acc_dtype(a, b)

    def kernel(ab, bb):
        return _local_dot(ab, bb, prec, out_dtype)

    f = shard_map(kernel, mesh=mesh,
                  in_specs=(P(x, None), P(None, y)),
                  out_specs=P(x, y))
    return f(a, b)


def matmul_summa(a: jax.Array, b: jax.Array, mesh: Mesh,
                 config: Optional[MatrelConfig] = None) -> jax.Array:
    """Cannon-style ring matmul: A, B, C all stay fully 2D-sharded P(x, y);
    the contraction advances by ppermute rings, so per-chip memory stays
    O(N²/P) with no replication. This is the SUMMA/ring component SURVEY.md
    §5 maps to ring-attention's role in the template.

    Requires a mesh where gx == gy (square grid); callers fall back to CPMM
    otherwise. Block-aligned: k must divide evenly over both axes (true for
    BlockMatrix padding).
    """
    x, y = mesh.axis_names
    gx, gy = mesh.shape[x], mesh.shape[y]
    if gx != gy:
        return matmul_cpmm(a, b, mesh, config)
    prec = _precision(config)
    out_dtype = _acc_dtype(a, b)
    g = gx

    def kernel(ab, bb):
        # Cannon's initial skew: rotate A left by its row index i along y,
        # and B up by its column index j along x, so step t multiplies
        # A[i, i+j+t] with B[i+j+t, j]. The shift amount is device-varying,
        # so every device runs the SAME g-1 ppermute steps (collectives must
        # be uniform across the mesh) and commits the shifted value only
        # while t < i (resp. t < j) via a local `where` — no divergent
        # control flow around collectives.
        i = jax.lax.axis_index(x)
        j = jax.lax.axis_index(y)

        def shift_a(arr):  # rotate one step left along mesh axis y
            return jax.lax.ppermute(
                arr, y, [(c, (c - 1) % g) for c in range(g)])

        def shift_b(arr):  # rotate one step up along mesh axis x
            return jax.lax.ppermute(
                arr, x, [(r, (r - 1) % g) for r in range(g)])

        def skew(t, carry):
            aa, bb_ = carry
            aa = jnp.where(t < i, shift_a(aa), aa)
            bb_ = jnp.where(t < j, shift_b(bb_), bb_)
            return aa, bb_

        if g > 1:
            ab, bb = jax.lax.fori_loop(0, g - 1, skew, (ab, bb))

        def step(t, carry):
            aa, bb_, acc = carry
            acc = acc + _local_dot(aa, bb_, prec, out_dtype)
            aa = shift_a(aa)
            bb_ = shift_b(bb_)
            return aa, bb_, acc

        acc0 = jnp.zeros((ab.shape[0], bb.shape[1]), dtype=out_dtype)
        # mark the fresh accumulator as varying over the mesh axes so the
        # fori_loop carry types line up with the per-device dot results
        pcast = getattr(jax.lax, "pcast", None)
        if pcast is not None:
            acc0 = pcast(acc0, (x, y), to="varying")
        else:
            acc0 = compat.pvary(acc0, (x, y))
        if g == 1:
            return _local_dot(ab, bb, prec, out_dtype)
        _, _, acc = jax.lax.fori_loop(0, g, step, (ab, bb, acc0))
        return acc

    f = shard_map(kernel, mesh=mesh,
                  in_specs=(P(x, y), P(x, y)),
                  out_specs=P(x, y))
    return f(a, b)


MATMUL_IMPLS = {
    "bmm_left": functools.partial(matmul_bmm, broadcast_side="left"),
    "bmm_right": functools.partial(matmul_bmm, broadcast_side="right"),
    "cpmm": matmul_cpmm,
    "rmm": matmul_rmm,
    "summa": matmul_summa,
    "xla": matmul_xla,
}


def run_matmul(strategy: str, a: jax.Array, b: jax.Array, mesh: Mesh,
               config: Optional[MatrelConfig] = None,
               epilogue=None) -> jax.Array:
    """``epilogue`` is the fused-region slot (ir/fusion.py /
    docs/FUSION.md): a traceable callable applied to the strategy's
    output INSIDE the same traced computation, so an absorbed
    elementwise/scalar/reduction chain compiles as the contraction's
    epilogue instead of its own dispatch. None (the default) is the
    historical path, bit-identically."""
    # fault site "strategy": the resilience harness's hook at strategy
    # execution (trace time). One truthiness test when injection is off.
    from matrel_tpu.resilience import faults as faults_lib
    faults_lib.check("strategy", config)
    impl = MATMUL_IMPLS[strategy]
    if strategy.startswith("bmm"):
        side = "left" if strategy == "bmm_left" else "right"
        out = matmul_bmm(a, b, mesh, config, broadcast_side=side)
    else:
        out = impl(a, b, mesh, config)
    return out if epilogue is None else epilogue(out)
