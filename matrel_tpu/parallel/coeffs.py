"""Learned planner coefficients — THE seam between the drift table and
every cost consult in the package (docs/COST_MODEL.md).

The drift auditor (obs/drift.py) calibrates per-(strategy, shape-class,
backend) ms/GFLOP and ms/est-MiB ratios from live query events. PR 15's
fleet placement was the first consumer; this module promotes the
pattern into the ONE place any planner/serve code reads those
coefficients (matlint ML018 enforces it — no direct ``drift.load_table``
outside this file), at the two altitudes its consumers need:

- :func:`strategy_coefficients` — the table's own per-strategy rows,
  keyed exactly the way ``drift.calibrate`` keys them
  (``"strategy|class|backend"``, tiered strategies ``rmm@bf16x3``).
  ``choose_strategy_ex`` ranks CANDIDATE strategies with these, so the
  consult must resolve per strategy, not per class.
- :func:`class_coefficients` — the count-weighted per-(shape-class,
  backend, tier) blend PR 15 introduced for placement (strategies are
  the planner's concern; the span/slice trade is per query). The chain
  DP's comm-weight consult uses the same altitude: a parenthesisation
  step has no stamped strategy yet.

Both are memoised on the table file's stat signature (the
placement_coefficients idiom), so per-decision consults never re-parse
an unchanged table. :func:`epoch` digests the DECISION-RELEVANT values
only (the blended ratios, not counts/timestamps) into the short token
the session's ``coeffv:`` plan-key prefix embeds: plans compiled under
different coefficients never share a cache slot, and a re-calibration
invalidates lazily — old plans keep serving in-flight queries, new
keys miss and recompile (the axisw:/prec:/delta: prefix discipline).

Cold classes fall back to the analytic closed forms — the constants
below only ever decide rankings, never numerics. Provenance: every
row carries ``source: "measured"``; consumers stamp decisions
``"measured"``/``"analytic"`` exactly like autotune winners (MV106's
exemption precedent).
"""

from __future__ import annotations

import hashlib
import math
import os
from typing import Dict, Optional, Tuple

from matrel_tpu.utils import lockdep

#: Analytic fallback coefficients (moved here from serve/placement.py,
#: which re-exports them): deliberately round numbers in the planner's
#: "relative units are what matter" tradition — ~1 TFLOP/s effective
#: per device and ~50 GB/s effective collective bandwidth. A
#: drift-calibrated row replaces both the moment one exists.
ANALYTIC_MS_PER_GFLOP = 1.0
ANALYTIC_MS_PER_MIB = 0.02

#: Transfer legs of the result-cache spill hierarchy
#: (docs/DURABILITY.md) — each calibrates its own ``spill:<leg>``
#: drift row (obs/drift.py ingests live ``spill`` events and bench
#: ``spill_sweep`` rows the same way it ingests ``reshard_sweep``).
SPILL_LEGS = ("d2h", "h2d", "disk_write", "disk_read")

#: Analytic fallback ms/MiB per spill leg — round numbers in the same
#: "relative units" tradition as above: ~20 GB/s effective PCIe DMA
#: each direction and ~2 GB/s effective disk, so the ranking a cold
#: table produces (HBM ≪ host ≪ disk) is right even before the first
#: calibration. A drift-calibrated ``spill:<leg>`` row replaces a leg
#: the moment one exists.
ANALYTIC_SPILL_MS_PER_MIB = {
    "d2h": 0.05, "h2d": 0.05, "disk_write": 0.5, "disk_read": 0.5,
}

#: Epoch token of a missing/empty table — a fixed literal (not a hash
#: of ``{}``) so the cold ``coeffv:`` prefix is self-describing in a
#: dumped plan-cache key.
COLD_EPOCH = "cold"

_lock = lockdep.make_lock("parallel.coeffs")
_cache: dict = {}


def _payload(path: str) -> dict:
    """The parsed-and-derived view of one drift table, memoised on the
    file's stat signature (the export-endpoint drift-cache idiom):
    ``{"strategy": rows, "class": rows, "epoch": token}``. A missing /
    unreadable table is the normal cold case — empty rows, COLD_EPOCH."""
    try:
        st = os.stat(path)
        sig = (st.st_size, st.st_mtime_ns)
    except OSError:
        return {"strategy": {}, "class": {}, "epoch": COLD_EPOCH}
    with _lock:
        hit = _cache.get(path)
        if hit is not None and hit[0] == sig:
            return hit[1]
    from matrel_tpu.obs import drift
    entries = drift.load_table(path).get("entries", {})
    strat_rows: Dict[str, dict] = {}
    acc: Dict[Tuple[str, str, str], dict] = {}
    digest_parts = []
    for key in sorted(entries):
        row = entries[key]
        if not isinstance(row, dict):
            continue
        n = int(row.get("count") or 0)
        if n <= 0:
            continue
        gf = row.get("ms_per_gflop")
        mib = row.get("ms_per_est_mib")
        gf = float(gf) if isinstance(gf, (int, float)) else None
        mib = float(mib) if isinstance(mib, (int, float)) else None
        # NaN/inf ratios (a poisoned or hand-edited table) must never
        # reach a ranking: min() over a dict with one NaN cost is
        # order-dependent — drop the bad field, keep the row
        if gf is not None and not math.isfinite(gf):
            gf = None
        if mib is not None and not math.isfinite(mib):
            mib = None
        if gf is None and mib is None:
            continue
        strat_rows[key] = {"ms_per_gflop": gf, "ms_per_mib": mib,
                           "count": n, "source": "measured"}
        # the epoch digests VALUES, not counts: a count-only merge
        # (same blended ratios) must not shatter every live plan key
        digest_parts.append(f"{key}={gf}:{mib}")
        strat = str(row.get("strategy") or "")
        tier = strat.split("@", 1)[1] if "@" in strat else ""
        ckey = (str(row.get("class") or "?"),
                str(row.get("backend") or "?"), tier)
        slot = acc.setdefault(ckey, {"_gf": 0.0, "_gfn": 0,
                                     "_mib": 0.0, "_mibn": 0})
        if gf is not None:
            slot["_gf"] += gf * n
            slot["_gfn"] += n
        if mib is not None:
            slot["_mib"] += mib * n
            slot["_mibn"] += n
    class_rows: Dict[Tuple[str, str, str], dict] = {}
    for ckey, slot in acc.items():
        if not slot["_gfn"] and not slot["_mibn"]:
            continue
        class_rows[ckey] = {
            "ms_per_gflop": (slot["_gf"] / slot["_gfn"]
                             if slot["_gfn"] else None),
            "ms_per_mib": (slot["_mib"] / slot["_mibn"]
                           if slot["_mibn"] else None),
            "count": max(slot["_gfn"], slot["_mibn"]),
            "source": "measured",
        }
    if digest_parts:
        epoch_tok = hashlib.sha1(
            "|".join(digest_parts).encode()).hexdigest()[:12]
    else:
        epoch_tok = COLD_EPOCH
    payload = {"strategy": strat_rows, "class": class_rows,
               "epoch": epoch_tok}
    with _lock:
        _cache[path] = (sig, payload)
    return payload


def strategy_coefficients(path: str) -> Dict[str, dict]:
    """Per-strategy calibration rows keyed ``"strategy|class|backend"``
    (the drift table's own key format; tiered strategies carry their
    ``@tier`` suffix inside the strategy token). Rows:
    ``{"ms_per_gflop", "ms_per_mib", "count", "source": "measured"}``
    with non-finite ratios dropped. Empty when the table is cold."""
    return _payload(path)["strategy"]


def strategy_row(strategy: str, cls: str, backend: str, path: str,
                 tier: str = "") -> Optional[dict]:
    """The calibration row one candidate strategy would be priced by,
    or None (cold). ``tier`` joins the strategy token the way the
    drift auditor keys tiered samples (``rmm@bf16x3``); the empty tier
    keeps the historical bare-strategy key."""
    tok = f"{strategy}@{tier}" if tier else strategy
    return _payload(path)["strategy"].get(f"{tok}|{cls}|{backend}")


def class_coefficients(path: str) -> Dict[Tuple[str, str, str], dict]:
    """The per-(shape-class, backend, tier) count-weighted blend —
    PR 15's ``placement_coefficients``, now served from the seam
    (serve/placement.py delegates here). Rows: ``{"ms_per_gflop",
    "ms_per_mib", "count", "source": "measured"}``."""
    return _payload(path)["class"]


def epoch(path: str) -> str:
    """Short content token of the table's decision-relevant values —
    what the session's ``coeffv:`` plan-key prefix embeds and the
    provenance ledger records per answer. Stable across count-only
    merges and ``updated`` re-stamps; changes exactly when a blended
    ratio changes (a re-plan round). :data:`COLD_EPOCH` for a
    missing/empty table."""
    return _payload(path)["epoch"]


def predict_ms(row: dict, gflops: float, weighted_cost: float) -> float:
    """One candidate's predicted milliseconds under a calibration row:
    compute term (ms/GFLOP × GFLOPs) + comm term (ms/est-MiB × the
    weighted byte-equivalents the analytic model priced — the same
    quantity the drift samples' ``est_bytes`` carried, so the ratio
    applies to what it was calibrated against). A row missing one
    ratio prices that term analytically (the cold-term fallback)."""
    gf = row.get("ms_per_gflop")
    mib = row.get("ms_per_mib")
    cg = float(gf) if gf is not None else ANALYTIC_MS_PER_GFLOP
    cm = float(mib) if mib is not None else ANALYTIC_MS_PER_MIB
    return cg * gflops + cm * (weighted_cost / (1 << 20))


def spill_leg_row(leg: str, cls: str, backend: str,
                  path: str) -> Optional[dict]:
    """The calibration row one spill transfer leg is priced by, or
    None (cold). Legs key the drift table as ``spill:<leg>`` strategy
    tokens — the ``reshard:<kind>`` precedent — so the same
    drift-driven loop (live ``spill`` events + ``bench.py --spill``
    sweeps → ``calibrate`` → this seam) closes over them."""
    return strategy_row(f"spill:{leg}", cls, backend, path)


def spill_cost_ms(legs, nbytes: float, cls: str, backend: str,
                  path: str) -> Tuple[float, str]:
    """Predicted milliseconds of a spill plan's transfer legs (the
    bill a lower-tier hit pays INSTEAD of recompute) and its
    provenance token: ``"measured"`` when every leg priced from a
    calibrated row, ``"analytic"`` when any leg fell back to
    :data:`ANALYTIC_SPILL_MS_PER_MIB` — the all-or-nothing stamp
    discipline ``choose_strategy_ex`` uses, applied per plan."""
    mib = float(nbytes) / (1 << 20)
    total = 0.0
    source = "measured"
    for leg in legs:
        row = spill_leg_row(leg, cls, backend, path)
        coef = row.get("ms_per_mib") if row is not None else None
        if coef is None:
            coef = ANALYTIC_SPILL_MS_PER_MIB.get(
                leg, ANALYTIC_MS_PER_MIB)
            source = "analytic"
        total += float(coef) * mib
    return total, source


def chain_comm_weights(path: str, backend: str,
                       min_samples: int = 1) -> Dict[str, float]:
    """Per-shape-class measured comm weight for the chain DP's step
    cost: FLOP-equivalents per byte, derived from the class blend as
    ``(ms_per_mib / 2^20) / (ms_per_gflop / 1e9)`` — how many MXU
    FLOPs buy the time of one interconnect byte ON THIS BACKEND, by
    measurement. Classes missing either ratio (or under
    ``min_samples``) are absent — the DP falls back to the analytic
    ``stats.COMM_FLOPS_PER_BYTE`` for them. Untier rows only: the
    DP prices un-annotated interior steps."""
    out: Dict[str, float] = {}
    for (cls, bk, tier), row in class_coefficients(path).items():
        if bk != backend or tier:
            continue
        if int(row.get("count") or 0) < min_samples:
            continue
        gf, mib = row.get("ms_per_gflop"), row.get("ms_per_mib")
        if gf is None or mib is None or gf <= 0 or mib <= 0:
            continue
        out[cls] = (mib / (1 << 20)) / (gf / 1e9)
    return out


def reset_coefficient_cache() -> None:
    """Test hook: drop the stat-signature memo (kept name-compatible
    with the placement predecessor — serve/placement.py aliases it)."""
    with _lock:
        _cache.clear()
