"""Strategy autotuning — empirical answer to SURVEY.md §7's hard part:
"proving the explicit SUMMA/psum_scatter paths beat XLA's choice (and
detecting when not)".

The cost model (planner.py) is an estimate; this module MEASURES. For a
given (n, k, m, mesh) it times every admissible strategy on-device
(marginal timing: chained dependent runs with a forced fetch, cancelling
dispatch latency — see bench.py methodology) and caches the winner.

The loop is CLOSED via ``config.autotune``: with the flag on, the
planner consults ``lookup_or_measure`` before trusting its byte model —
a recurring shape class is measured once, the winner overrides the
model's pick, and the table persists as JSON (config.autotune_table_path)
so later sessions inherit the measurement. ``config.strategy_override``
still wins over both.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

_log = logging.getLogger("matrel_tpu.autotune")

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core import mesh as mesh_lib, padding
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.parallel import planner, strategies

# (best, times) per shape class; best is None when the measured winner was
# within TIE_REL of the runner-up — a tie is recorded as a tie and the
# planner's byte model decides (VERDICT r3: noise must not become winners).
_CACHE: Dict[tuple, Tuple[Optional[str], Dict[str, float]]] = {}

TIE_REL = 0.10

_DEFAULT_TABLE = ".matrel_autotune.json"


def _table_path(config: Optional[MatrelConfig] = None) -> str:
    cfg = config or default_config()
    return cfg.autotune_table_path or _DEFAULT_TABLE


def _table_key(side: int, gx: int, gy: int, dtype: str,
               weights: Tuple[float, float] = (1.0, 1.0)) -> str:
    # backend is part of the key, mirroring _spmv_key's rationale
    # (advisor r4): a shared table must never serve one backend's
    # winner to the other — a persisted CPU-mesh winner has nothing to
    # say about Mosaic. Old un-suffixed entries simply never hit; they
    # linger in the JSON (persist rewrites the whole table) but are
    # inert — delete the file to reclaim the bytes.
    #
    # Non-uniform topology weights (core/mesh.MeshTopology) suffix the
    # key too: a winner measured on (or planned for) a hierarchical
    # ICI/DCN mesh must never collide with the homogeneous mesh's row
    # for the same grid shape. Uniform weights keep the historical
    # 4-field format, so existing tables stay live.
    key = f"{side}|{gx}x{gy}|{dtype}|{jax.default_backend()}"
    if weights != (1.0, 1.0):
        key += f"|w{weights[0]:g}x{weights[1]:g}"
    return key


def load_table(path: str) -> Dict[str, dict]:
    """Persisted {key: {"best": strategy, "times": {...}}} or {}.
    A corrupt/absent file is an empty table, never an error.

    Tables written BEFORE the backend key suffix landed are migrated on
    load by PRUNING their un-suffixed entries (advisor r5 low): those
    keys can never hit again — `_table_key`/`_spmv_key` always emit the
    suffixed form — so left in place they would ride every whole-table
    rewrite forever as dead bytes. Dropping them here means the next
    `_persist` rewrites a clean table; the one-time re-measure cost of
    the orphaned winners is the accepted price of backend-safe keys."""
    try:
        with open(path) as f:
            t = json.load(f)
    except OSError:
        return {}            # absent table: the normal first-run case
    except ValueError as e:
        # corrupt/truncated table: WARN and rebuild from empty — the
        # session must survive a torn write (a crash mid-_persist, a
        # disk hiccup); the next _persist rewrites a clean file
        # (docs/RESILIENCE.md robust-reader contract)
        _log.warning("autotune table %s is corrupt (%s); "
                     "rebuilding from empty", path, e)
        return {}
    if not isinstance(t, dict):
        _log.warning("autotune table %s has unexpected shape (%s); "
                     "rebuilding from empty", path, type(t).__name__)
        return {}
    return {k: v for k, v in t.items() if _current_key_format(k)}


def _current_key_format(key: str) -> bool:
    """Does a persisted key match the CURRENT (backend-suffixed) key
    formats? Matmul keys are ``side|gxXgy|dtype|backend`` (4 fields);
    SpMV keys ``spmv|backend|rows x cols|nb|cap|blk|grid`` (7 fields);
    reshard keys ``reshard|src>dst|side|grid|backend`` (5 fields);
    SpGEMM kernel keys ``spgemm|<=side|structure|bs|grid|backend``
    (6 fields — the structure class must be in the CURRENT classifier
    vocabulary, so keys from a retired taxonomy are pruned too).
    Any may carry one extra trailing ``w<wx>x<wy>`` field — the
    topology-weight suffix of a non-uniform mesh. Legacy un-suffixed
    entries (one field short) and anything unknown read as stale."""
    if not isinstance(key, str):
        return False
    fields = key.split("|")
    n = len(fields)
    if key.startswith("spmv|"):
        base = 7
    elif key.startswith("reshard|"):
        base = 5
    elif key.startswith("spgemm|"):
        from matrel_tpu.ir import stats
        base = 6
        if n >= 3 and fields[2] not in stats.STRUCTURE_CLASSES:
            return False
    elif key.startswith("fuse|"):
        base = 5         # fuse|<sig>|<=side|grid|backend (round 12)
    elif key.startswith("ivm|"):
        base = 5         # ivm|<rule>|<=side|grid|backend (round 14);
        # rules from a retired vocabulary prune like spgemm structures
        from matrel_tpu.ir import delta as delta_lib
        if n >= 2 and fields[1] not in delta_lib.DELTA_RULES:
            return False
    else:
        base = 4
    if n == base:
        return True
    return n == base + 1 and fields[-1].startswith("w")


_TABLE_CACHE: Dict[str, Tuple[float, Dict[str, dict]]] = {}


def _load_table_cached(path: str) -> Dict[str, dict]:
    """load_table memoised on (path, mtime): the planner consults the
    table on EVERY matmul when config.autotune is on, and un-measured
    shapes (including everything above autotune_max_dim) would
    otherwise re-open and re-parse the JSON on each compile."""
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        mtime = -1.0
    hit = _TABLE_CACHE.get(path)
    if hit is not None and hit[0] == mtime:
        return hit[1]
    table = load_table(path)
    _TABLE_CACHE[path] = (mtime, table)
    return table


def _persist(path: str, key: str, best: Optional[str],
             times: Dict[str, float]) -> None:
    """Merge one measurement into the JSON table (atomic rename).

    A best-effort O_CREAT|O_EXCL lock file guards the read-merge-replace
    window (advisor r3: two concurrent processes could interleave
    load/merge/replace and silently drop each other's measurements).
    On contention the persist is SKIPPED — losing one merge is benign
    (the in-process cache still holds it and a later call re-persists),
    and rename atomicity already rules out corruption. A lock older
    than 60 s is presumed dead and broken; after the break the breaker
    re-stats the lock path and proceeds only when the inode matches its
    own freshly-created fd (advisor r4: two processes can both observe
    the stale lock, both unlink-and-recreate — one unlinking the
    other's fresh lock — and both enter the merge window; the st_ino
    check makes exactly one of them win)."""
    lock = f"{path}.lock"
    fd = None
    try:
        fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        try:
            st0 = os.stat(lock)
            if time.time() - st0.st_mtime <= 60.0:
                return
            # re-stat immediately before the unlink: if the inode
            # changed since the staleness check, another breaker got
            # here first — never unlink ITS fresh lock (review r5; the
            # remaining stat→unlink window is unavoidable without
            # flock, but every exit below re-checks ownership so a
            # lost race costs one skipped persist, never two writers)
            if os.stat(lock).st_ino != st0.st_ino:
                return
            os.unlink(lock)
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            if os.stat(lock).st_ino != os.fstat(fd).st_ino:
                os.close(fd)   # a racing breaker re-created over ours;
                return         # it owns the window — skip, don't unlink
        except OSError:
            if fd is not None:
                os.close(fd)
            return
    except OSError:
        fd = None    # lock unsupported (read-only FS): try unguarded
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        # (re-)load under the lock so a concurrent writer's just-merged
        # entries survive into this replace
        table = load_table(path)
        table[key] = {"best": best, "times": times}
        with open(tmp, "w") as f:
            json.dump(table, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:      # read-only FS etc.: in-process cache still holds it
        try:
            os.unlink(tmp)
        except OSError:
            pass
    finally:
        if fd is not None:
            try:
                # release ONLY a lock we still own: a racing breaker
                # may have replaced ours mid-merge (review r5) — its
                # inode differs and must not be unlinked
                if os.stat(lock).st_ino == os.fstat(fd).st_ino:
                    os.unlink(lock)
            except OSError:
                pass
            os.close(fd)


def measure_strategy(strategy: str, A: BlockMatrix, B: BlockMatrix,
                     config: MatrelConfig, reps: Tuple[int, int] = (2, 8),
                     n_estimates: int = 3, min_window_s: float = 0.05
                     ) -> float:
    """Marginal seconds per multiply for one strategy: the MEDIAN of
    ``n_estimates`` independent marginal estimates (bench_all
    methodology — a single marginal on a shared chip records noise as
    winners, VERDICT r3). The chained-reps budget is floored: when the
    long chain completes under ``min_window_s`` the reps are scaled up
    so the marginal rises above dispatch jitter. May return a
    NON-POSITIVE value on a hopelessly noisy host — callers must treat
    that as "no measurement", never clamp it into a fake winner."""
    mesh = A.mesh
    f = jax.jit(lambda x, y: strategies.run_matmul(strategy, x, y, mesh,  # matlint: disable=ML010 measurement probe — the autotune loop times candidates outside the plan path
                                                   config))
    fetch = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))  # matlint: disable=ML010 measurement probe — the autotune loop times candidates outside the plan path

    def chained(n: int):
        cur = A.data
        for i in range(n):
            cur = f(cur, B.data).astype(A.dtype)
            if (i + 1) % 8 == 0:
                # bound in-flight programs: the CPU in-process
                # communicator's rendezvous starves (fatal abort) with
                # tens of queued collective executions; a sync every 8
                # reps costs the same per rep for every strategy, so
                # the ranking is unaffected
                cur.block_until_ready()
        float(fetch(cur))

    def marginal(lo: int, hi: int) -> Tuple[float, float]:
        t0 = time.perf_counter()
        chained(lo)
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        chained(hi)
        t_hi = time.perf_counter() - t0
        return (t_hi - t_lo) / (hi - lo), t_hi

    chained(2)  # compile + warm
    lo, hi = reps
    est, t_hi = marginal(lo, hi)
    if t_hi < min_window_s:
        # bounded: the whole re-measure must stay cheap even on a slow
        # host (a CPU-mesh run pays ~ms dispatch per chained call), so
        # the chain never exceeds 48 multiplies however short the window
        scale = min(max(2, round(min_window_s / max(t_hi, 1e-4))),
                    max(48 // hi, 1))
        if scale > 1:
            lo, hi = lo * scale, hi * scale
            est, t_hi = marginal(lo, hi)
    ests = [est]
    for _ in range(max(n_estimates, 1) - 1):
        ests.append(marginal(lo, hi)[0])
    ests.sort()
    return ests[len(ests) // 2]


def autotune_matmul(n: int, k: int, m: int,
                    mesh=None, dtype="float32",
                    config: Optional[MatrelConfig] = None
                    ) -> Tuple[str, Dict[str, float]]:
    """Times every admissible strategy for an (n×k)·(k×m) multiply on this
    mesh; returns (best_strategy, {strategy: seconds}). Results cached per
    (dims, mesh shape, dtype). Chained timing needs n == m == k for the
    feedback loop, so non-square requests are measured square at
    max(n, k, m) — the MXU/collective behaviour is shape-dominated."""
    cfg = config or default_config()
    mesh = mesh or mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
    side = max(n, k, m)
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    wts = mesh_lib.axis_weights(mesh, cfg)
    key = (side, gx, gy, str(dtype), jax.default_backend(), wts)
    if key in _CACHE:
        _maybe_persist_cached(cfg, key)
        return _CACHE[key]
    A = BlockMatrix.random((side, side), mesh=mesh, seed=0, dtype=dtype)
    B = BlockMatrix.random((side, side), mesh=mesh, seed=1, dtype=dtype)
    pn, pk = padding.padded_shape((side, side), mesh)
    results: Dict[str, float] = {}
    for s in strategies.STRATEGIES:
        if s == "summa" and gx != gy:
            continue
        if not planner.admissible(s, pn, pk, pn, gx, gy):
            continue
        try:
            t = measure_strategy(s, A, B, cfg)
        except Exception:  # noqa: BLE001  # matlint: disable=ML007 measurement loop — a strategy failing to compile
            continue       # on this backend just drops out of the table
        if t > 0.0:        # non-positive median = noise, not a time
            results[s] = t
    # _pick_winner owns the one-variant and tie gates (advisor r4):
    # a compile-failure-reduced lone survivor records best=None —
    # times still persist for observability, the model decides
    best = _pick_winner(results)
    _CACHE[key] = (best, results)
    if results and (cfg.autotune or cfg.autotune_table_path):
        # an EMPTY result set (every strategy failed or measured pure
        # noise) is never persisted — a persisted empty entry would read
        # as "measured: no winner" and permanently disable re-measurement
        # of the shape class on later, healthy processes
        # persist only when the closed loop is on or the caller named a
        # table explicitly — a one-off measurement call (the original
        # API contract, also the CLI) must not drop a hidden JSON file
        # into the working directory as a side effect
        _persist(_table_path(cfg),
                 _table_key(side, gx, gy, str(dtype), wts),
                 best, results)
    return best, results


def _pick_winner(results: Dict[str, float]) -> Optional[str]:
    """argmin with two guards, BOTH owned here (review r5 — one policy,
    not copies at each call site): a one-variant "comparison" proves
    nothing (None — the lone survivor of compile failures/noise must
    not become a measured preference), and a winner within TIE_REL of
    the runner-up is recorded as None ("no measured winner") so the
    byte model decides — on meshes where strategies compile identically
    (e.g. 1 device) every marginal is pure noise."""
    if len(results) < 2:
        return None
    order = sorted(results, key=results.get)
    best, runner = order[0], order[1]
    if results[runner] <= results[best] * (1.0 + TIE_REL):
        return None
    return best


def _maybe_persist_cached(config: Optional[MatrelConfig],
                          key: tuple) -> None:
    """A shape first measured with persistence OFF (one-off call) must
    still reach the table when a later caller enables the closed loop —
    both cache-hit early-returns route through here."""
    cfg = config or default_config()
    if not (cfg.autotune or cfg.autotune_table_path):
        return
    side, gx, gy, dtype, _backend, wts = key
    best, results = _CACHE[key]
    if not results:
        return
    path = _table_path(cfg)
    tkey = _table_key(side, gx, gy, dtype, wts)
    if tkey not in _load_table_cached(path):
        _persist(path, tkey, best, results)


def lookup_or_measure(n: int, k: int, m: int, mesh,
                      dtype: str = "float32",
                      config: Optional[MatrelConfig] = None
                      ) -> Optional[str]:
    """The planner's entry point (config.autotune=True): the measured
    winner for this shape class, or None when the cost model should
    decide. Order: in-process cache → persisted table → measure once
    (small shapes only — measuring allocates two side² operands, so
    shapes above config.autotune_max_dim are never measured inline)."""
    cfg = config or default_config()
    side = max(n, k, m)
    # strongly rectangular shapes are gated out (advisor r3): the table
    # keys and measures SQUARE side-sized operands, so a 64x8192 matvec
    # chain would both allocate two side-squared probes at compile time
    # and inherit a square-dense winner that can mispick for it — the
    # byte model (which sees the true dims) decides instead
    if min(n, k, m) * 4 < side:
        return None
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    wts = mesh_lib.axis_weights(mesh, cfg)
    key = (side, gx, gy, str(dtype), jax.default_backend(), wts)
    if key in _CACHE:
        _maybe_persist_cached(cfg, key)
        return _CACHE[key][0]
    entry = _load_table_cached(_table_path(cfg)).get(
        _table_key(side, gx, gy, str(dtype), wts))
    if isinstance(entry, dict) and entry.get("times"):
        # a persisted TIE (best null) is a measurement too: cache it and
        # let the model decide — do NOT re-measure every compile
        best = entry.get("best")
        best = best if isinstance(best, str) else None
        _CACHE[key] = (best, dict(entry.get("times", {})))
        return best
    if side > cfg.autotune_max_dim:
        return None
    best, _ = autotune_matmul(n, k, m, mesh=mesh, dtype=dtype, config=cfg)
    return best


# -- SpMV executor autotuning -------------------------------------------------
# The largest hand-pinned constants in the codebase are the COO SpMV
# executor choices (SURVEY.md §7 "detecting when XLA's choice is
# beaten"): compact-table Pallas scatter vs expanded-table XLA one-hots.
# The hand default (compact wherever Pallas is available — measured
# 18.8 ms vs 29.4 per matvec at BASELINE row-5 scale on v5e) stays the
# fallback; with config.autotune on, the choice is measured once per
# plan shape class and persisted in the same JSON table.

_SPMV_CACHE: Dict[str, Optional[str]] = {}

# expanded tables cost ~224 B per padded slot; refuse to even MEASURE
# the expanded variant past this budget (a 10x-graph table would blow
# the chip's HBM just to lose the comparison)
SPMV_EXPANDED_BUDGET_BYTES = 2 * 1024 ** 3

SPMV_VARIANTS = ("compact", "expanded")


def _spmv_key(plan, gx: int, gy: int,
              weights: Tuple[float, float] = (1.0, 1.0)) -> str:
    # backend is part of the key: the compact/expanded trade-off FLIPS
    # between real Mosaic (compact wins, BASELINE row 5) and CPU
    # interpret mode (expanded wins ~20x) — a shared table must never
    # serve one backend's winner to the other. Non-uniform topology
    # weights suffix the key like _table_key's matmul rows: the sharded
    # executors' gather bills differ on a hierarchical mesh.
    nb, cap = plan.src8.shape if hasattr(plan.src8, "shape") else (0, 0)
    key = (f"spmv|{jax.default_backend()}|{plan.n_rows}x{plan.n_cols}"
           f"|nb{nb}|cap{cap}|blk{plan.block}|{gx}x{gy}")
    if weights != (1.0, 1.0):
        key += f"|w{weights[0]:g}x{weights[1]:g}"
    return key


def measure_spmv_variant(variant: str, plan, mesh,
                         config: Optional[MatrelConfig] = None,
                         n_times: int = 5) -> float:
    """Median seconds per matvec for one executor variant, timed through
    the REAL lowering path (Lowerer._coo_spmv_stack with the choice
    forced). Sync timing with a forced scalar fetch — both variants pay
    the identical fetch, so the ranking is unaffected."""
    import numpy as np
    from matrel_tpu import executor as executor_lib
    cfg = config or default_config()
    low = executor_lib.Lowerer(mesh, cfg)
    low.spmv_choice = {id(plan): (plan, variant)}
    x = jnp.asarray(np.random.default_rng(0)
                    .standard_normal(plan.n_cols).astype(np.float32))
    # snapshot the plan's expanded-table caches: the expanded probe
    # calls plan.arrays(), which eagerly expands and CACHES the ~224
    # B/slot one-hot tables on the plan — left in place they would pin
    # up to the measurement budget of HBM for the whole session even
    # when compact wins (review r4). The winner re-expands on first
    # real use (one fused program).
    saved = (plan._tables, plan._spmm_tables)
    try:
        f = jax.jit(lambda v: jnp.sum(low._coo_spmv_stack(plan, [v])))  # matlint: disable=ML010 measurement probe — the autotune loop times candidates outside the plan path
        float(f(x))    # compile + warm (also table upload/expansion)
        ts = []
        for _ in range(max(n_times, 1)):
            t0 = time.perf_counter()
            float(f(x))
            ts.append(time.perf_counter() - t0)
    finally:
        if variant == "expanded":
            plan._tables, plan._spmm_tables = saved
    ts.sort()
    return ts[len(ts) // 2]


def _spmv_admissible(variant: str, plan, config: MatrelConfig) -> bool:
    from matrel_tpu.config import pallas_enabled
    if variant == "compact":
        return pallas_enabled(config)
    # expanded: gate on the materialised-table budget
    nb, cap = plan.src8.shape
    return nb * cap * 224 <= SPMV_EXPANDED_BUDGET_BYTES


def lookup_or_measure_spmv(plan, mesh,
                           config: Optional[MatrelConfig] = None
                           ) -> Optional[str]:
    """The compile-time entry point (config.autotune=True): the measured
    executor variant for this plan shape class, or None when the hand
    default should stand. Same table discipline as the matmul loop:
    in-process cache → persisted table → measure once; ties and empty
    result sets resolve to None and are never fake winners."""
    cfg = config or default_config()
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    key = _spmv_key(plan, gx, gy, mesh_lib.axis_weights(mesh, cfg))
    if key in _SPMV_CACHE:
        return _SPMV_CACHE[key]
    entry = _load_table_cached(_table_path(cfg)).get(key)
    if isinstance(entry, dict) and entry.get("times"):
        best = entry.get("best")
        best = best if isinstance(best, str) else None
        _SPMV_CACHE[key] = best
        return best
    results: Dict[str, float] = {}
    for v in SPMV_VARIANTS:
        if not _spmv_admissible(v, plan, cfg):
            continue
        try:
            t = measure_spmv_variant(v, plan, mesh, cfg)
        except Exception:  # noqa: BLE001  # matlint: disable=ML007 measurement loop — a variant failing to compile
            continue       # on this backend drops out of the table
        if t > 0.0:
            results[v] = t
    # a one-variant "comparison" proves nothing, and which variants are
    # admissible depends on CONFIG state (use_pallas, the expanded
    # budget) that the table key does not encode — persisting it would
    # poison shared tables across configs (review r4). Hand default
    # stands; nothing is written.
    if len(results) < 2:
        _SPMV_CACHE[key] = None
        return None
    best = _pick_winner(results)
    _SPMV_CACHE[key] = best
    if cfg.autotune or cfg.autotune_table_path:
        _persist(_table_path(cfg), key, best, results)
    return best


# ---------------------------------------------------------------------------
# SpGEMM kernel measurement (round 11) — the closed loop for the sparse
# kernel registry (ops/kernel_registry.py): per (shape class, structure
# class, backend) the registered variants are timed over a synthetic
# operand pair EXHIBITING that structure (the same generator the bench
# and soak batteries draw from), and the winner persists exactly like
# matmul strategies. ``kernel_registry.select_kernel`` consults this
# before trusting its cost model (the "measured" stamp source).
# ---------------------------------------------------------------------------

_SPGEMM_CACHE: Dict[str, Optional[str]] = {}

#: Probe block-density seed for the synthetic structure pair — fixed so
#: the measured population is reproducible per key.
SPGEMM_PROBE_SEEDS = (0, 1)


def _spgemm_side_class(side: int) -> int:
    """Power-of-two side bucket — the drift auditor's shape-class
    granularity, so a 3800² and a 4096² S×S share a row."""
    return 1 << max(0, math.ceil(math.log2(max(int(side), 1))))


def _spgemm_key(side: int, structure: str, bs: int, gx: int, gy: int,
                weights: Tuple[float, float] = (1.0, 1.0)) -> str:
    """``spgemm|<=side|structure|bs|grid|backend[|w..]`` — the issue'd
    key format: side bucketed, structure class explicit, backend (and
    non-uniform weights) suffixed like every other table row. Keys in
    any OTHER spgemm format (including a retired structure taxonomy)
    are legacy and pruned on load (_current_key_format)."""
    key = (f"spgemm|<={_spgemm_side_class(side)}|{structure}|bs{bs}"
           f"|{gx}x{gy}|{jax.default_backend()}")
    if weights != (1.0, 1.0):
        key += f"|w{weights[0]:g}x{weights[1]:g}"
    return key


def measure_spgemm_kernel(kernel_id: str, A, B,
                          config: Optional[MatrelConfig] = None,
                          n_times: int = 5) -> float:
    """Median seconds for one forced-kernel SpGEMM over the probe pair,
    through the REAL ops path (spgemm_tiles with the registry choice
    pinned). Sync timing with a forced scalar fetch — every kernel
    pays the identical fetch, so the ranking is unaffected."""
    from matrel_tpu.ops import spgemm as spgemm_lib
    cfg = config or default_config()
    fetch = jax.jit(lambda t: jnp.sum(t.astype(jnp.float32)))  # matlint: disable=ML010 measurement probe — the autotune loop times candidates outside the plan path

    def go():
        tiles, _, _ = spgemm_lib.spgemm_tiles(A, B, cfg,
                                              kernel=kernel_id)
        float(fetch(tiles))

    go()                        # compile + warm (runner cache fill)
    ts = []
    for _ in range(max(n_times, 1)):
        t0 = time.perf_counter()
        go()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def lookup_or_measure_spgemm(side: int, structure: str, bs: int, mesh,
                             config: Optional[MatrelConfig] = None
                             ) -> Optional[str]:
    """The registry's compile-time entry point (config.autotune=True):
    the measured kernel id for this (shape class, structure class,
    backend), or None when the cost model should decide. Same table
    discipline as the matmul/SpMV/reshard loops: in-process cache →
    persisted table → measure once (bounded probe — shapes above
    autotune_max_dim are never measured inline); ties and
    single-variant result sets resolve to None and are never fake
    winners."""
    from matrel_tpu.ops import kernel_registry as kr
    cfg = config or default_config()
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    wts = mesh_lib.axis_weights(mesh, cfg)
    key = _spgemm_key(side, structure, bs, gx, gy, wts)
    if key in _SPGEMM_CACHE:
        return _SPGEMM_CACHE[key]
    entry = _load_table_cached(_table_path(cfg)).get(key)
    if isinstance(entry, dict) and entry.get("times"):
        best = entry.get("best")
        best = best if isinstance(best, str) else None
        _SPGEMM_CACHE[key] = best
        return best
    if side > cfg.autotune_max_dim:
        _SPGEMM_CACHE[key] = None
        return None
    probe_n = int(side)
    A = kr.synthesize_structure(structure, probe_n, bs, mesh,
                                seed=SPGEMM_PROBE_SEEDS[0])
    B = kr.synthesize_structure(structure, probe_n, bs, mesh,
                                seed=SPGEMM_PROBE_SEEDS[1])
    npairs = 1              # admissibility probe: eligibility, not size
    results: Dict[str, float] = {}
    for kid in kr.kernel_ids():
        spec = kr.get_kernel(kid)
        if not (spec.universal or structure in spec.structures):
            continue        # foreign specializations aren't candidates
        if not kr.admissible(kid, bs, npairs, cfg):
            continue
        try:
            t = measure_spgemm_kernel(kid, A, B, cfg)
        except Exception:  # noqa: BLE001  # matlint: disable=ML007 measurement loop — a kernel failing to compile on this backend drops out of the table
            continue
        if t > 0.0:
            results[kid] = t
    # which kernels are admissible depends on CONFIG state (use_pallas,
    # interpret) the key does not encode — a single-variant "result"
    # proves nothing and is never persisted (the SpMV-loop precedent)
    if len(results) < 2:
        _SPGEMM_CACHE[key] = None
        return None
    best = _pick_winner(results)
    _SPGEMM_CACHE[key] = best
    if cfg.autotune or cfg.autotune_table_path:
        _persist(_table_path(cfg), key, best, results)
    return best


# ---------------------------------------------------------------------------
# Fused-vs-staged region measurement (round 12) — the closed loop for
# the whole-plan fusion pass (ir/fusion.py; docs/FUSION.md): per
# (region signature, shape class, backend), the region is emitted BOTH
# ways through the executor's unit-program seam — one jitted program
# for the whole segment vs one per member op — over synthetic padded
# probes, and the winner persists under the ``fuse|`` key family.
# ``fusion.annotate_fusion`` consults this before stamping: a measured
# "staged" winner SUPPRESSES the region (fusion boundaries are planner
# decisions, and the closed measurement loop overrules the model).
# ---------------------------------------------------------------------------

_FUSION_CACHE: Dict[str, Optional[str]] = {}

FUSION_VARIANTS = ("fused", "staged")


def _fusion_key(sig: str, side: int, gx: int, gy: int,
                weights: Tuple[float, float] = (1.0, 1.0)) -> str:
    """``fuse|<sig>|<=side|grid|backend[|w..]`` — the region signature
    is '|'-free by construction (ir/fusion.region_sig); side bucketed
    to the drift auditor's power-of-two class like every other row."""
    cls = 1 << max(0, math.ceil(math.log2(max(int(side), 1))))
    key = (f"fuse|{sig}|<={cls}|{gx}x{gy}"
           f"|{jax.default_backend()}")
    if weights != (1.0, 1.0):
        key += f"|w{weights[0]:g}x{weights[1]:g}"
    return key


def measure_fusion_region(region, root_tree, mesh,
                          config: Optional[MatrelConfig] = None,
                          n_times: int = 5) -> Dict[str, float]:
    """{'fused': s, 'staged': s} medians for ONE region, both lowered
    through the executor's unit-program seam over synthetic padded
    probes (region_probe_programs). Empty dict when the region is not
    probeable (sparse-payload inputs) or a variant fails to build."""
    from matrel_tpu import executor as executor_lib
    cfg = config or default_config()
    node = _find_region_root(root_tree, region.root_uid)
    if node is None:
        return {}
    probe = executor_lib.region_probe_programs(
        node, region.member_uids, mesh, cfg)
    if probe is None:
        return {}
    fused, staged, input_uids, arrays, root_uid = probe

    def run_fused():
        jax.block_until_ready(fused(*(arrays[u] for u in input_uids)))

    def run_staged():
        env = dict(arrays)
        for n, fn, ins in staged:
            env[n.uid] = fn(*(env[u] for u in ins))
        jax.block_until_ready(env[root_uid])

    results: Dict[str, float] = {}
    for name, go in (("fused", run_fused), ("staged", run_staged)):
        try:
            go()                      # compile + warm every unit
            ts = []
            for _ in range(max(n_times, 1)):
                t0 = time.perf_counter()
                go()
                ts.append(time.perf_counter() - t0)
            ts.sort()
            t = ts[len(ts) // 2]
        except Exception:  # noqa: BLE001  # matlint: disable=ML007 measurement loop — a region variant failing to build/compile drops out of the table
            continue
        if t > 0.0:
            results[name] = t
    return results


def _find_region_root(root_tree, uid: int):
    from matrel_tpu.ir import fusion as fusion_lib
    return fusion_lib._find_uid(root_tree, uid)


def lookup_or_measure_fusion(region, root_tree, mesh,
                             config: Optional[MatrelConfig] = None
                             ) -> Optional[str]:
    """The fusion pass's boundary consult (config.autotune on):
    "fused" / "staged" / None (no measured preference — the region
    stamps by default, the model's pick). Same table discipline as the
    matmul/SpMV/SpGEMM/reshard loops: in-process cache → persisted
    table → measure once (bounded probe side); ties and one-variant
    result sets resolve to None and are never fake winners."""
    cfg = config or default_config()
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    node = _find_region_root(root_tree, region.root_uid)
    side = max([1] + [d for u in (region.member_uids
                                  + (region.root_uid,))
                      for d in _member_dims(root_tree, u)])
    key = _fusion_key(region.sig, side, gx, gy,
                      mesh_lib.axis_weights(mesh, cfg))
    if key in _FUSION_CACHE:
        return _FUSION_CACHE[key]
    entry = _load_table_cached(_table_path(cfg)).get(key)
    if isinstance(entry, dict) and entry.get("times"):
        best = entry.get("best")
        best = best if isinstance(best, str) else None
        _FUSION_CACHE[key] = best
        return best
    if node is None or side > cfg.autotune_max_dim:
        _FUSION_CACHE[key] = None
        return None
    results = measure_fusion_region(region, root_tree, mesh, cfg)
    if len(results) < 2:
        _FUSION_CACHE[key] = None
        return None
    best = _pick_winner(results)
    _FUSION_CACHE[key] = best
    if cfg.autotune or cfg.autotune_table_path:
        _persist(_table_path(cfg), key, best, results)
    return best


def _member_dims(root_tree, uid: int):
    n = _find_region_root(root_tree, uid)
    return tuple(n.shape) if n is not None else ()


# ---------------------------------------------------------------------------
# Reshard plan-vs-naive measurement (round 10) — the closed loop for the
# staged redistribution planner (parallel/reshard.py): per
# (src->dst, side class, grid, backend) shape class, time the compiled
# step sequence against the legacy one-shot sharding constraint and
# persist the winner like matmul strategies, so a backend where XLA's
# own one-shot move beats the staged chain keeps it (the executor's
# staged lowering consults this before applying steps).
# ---------------------------------------------------------------------------

_RESHARD_CACHE: Dict[str, Optional[str]] = {}

RESHARD_VARIANTS = ("staged", "naive")


def _reshard_key(plan, gx: int, gy: int,
                 weights: Tuple[float, float] = (1.0, 1.0)) -> str:
    """``reshard|src>dst|<=side|gxXgy|backend[|w..]`` — side bucketed
    to the power of two above sqrt(nbytes/4), the drift auditor's
    shape-class granularity, so a 3800² and a 4096² move share a row.
    Backend (and non-uniform weights) key like every other table row:
    a CPU winner has nothing to say about Mosaic."""
    side = math.sqrt(max(plan.nbytes / 4.0, 1.0))
    cls = 1 << max(0, math.ceil(math.log2(max(side, 1.0))))
    key = (f"reshard|{plan.src}>{plan.dst}|{cls}|{gx}x{gy}"
           f"|{jax.default_backend()}")
    if weights != (1.0, 1.0):
        key += f"|w{weights[0]:g}x{weights[1]:g}"
    return key


def measure_reshard_variant(variant: str, plan, mesh,
                            config: Optional[MatrelConfig] = None,
                            n_times: int = 5) -> float:
    """Median seconds for one lowering of the plan's move at its shape
    class, on a square f32 probe padded to the mesh (the matmul-probe
    discipline). "naive" is a single constraint to the destination
    sharding (XLA's own collective choice); "staged" applies the
    compiled step sequence."""
    import numpy as np
    from jax.sharding import NamedSharding
    from matrel_tpu.core import padding
    from matrel_tpu.parallel import reshard as reshard_lib
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    p = max(gx * gy, 1)
    side = int(round(math.sqrt(max(plan.nbytes / 4.0, 1.0))))
    side = max(p, -(-side // p) * p)            # divisible probe
    probe = reshard_lib.compile_reshard(
        plan.src, plan.dst, float(side) * side * 4, gx, gy,
        plan.weights, peak_budget=plan.peak_bytes or 0.0)
    src_sh = NamedSharding(mesh, reshard_lib._state_spec(plan.src,
                                                         mesh))
    dst_sh = NamedSharding(mesh, reshard_lib._state_spec(plan.dst,
                                                         mesh))
    x = jax.device_put(  # matlint: disable=ML008 measurement-probe input placement — the harness's own array, not a lowering re-lay
        np.random.default_rng(0).standard_normal(
            (side, side)).astype(np.float32), src_sh)
    if variant == "naive":
        f = jax.jit(lambda v: jax.lax.with_sharding_constraint(v,  # matlint: disable=ML010 measurement probe — the autotune loop times candidates outside the plan path
                                                               dst_sh))
    else:
        f = jax.jit(lambda v: reshard_lib.apply_staged(v, probe, mesh))  # matlint: disable=ML010 measurement probe — the autotune loop times candidates outside the plan path
    f(x).block_until_ready()                    # compile + warm
    ts = []
    for _ in range(max(n_times, 1)):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def lookup_or_measure_reshard(plan, mesh,
                              config: Optional[MatrelConfig] = None
                              ) -> Optional[str]:
    """Measured lowering for this reshard's shape class ("staged" /
    "naive"), or None when the model's pick should stand (ties, shapes
    above autotune_max_dim — measuring would allocate the probe —
    single-step plans, or a variant failing to compile). Same table
    discipline as the matmul/SpMV loops."""
    cfg = config or default_config()
    if len(plan.steps) < 2:
        return None          # staged == naive: nothing to compare
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    key = _reshard_key(plan, gx, gy, mesh_lib.axis_weights(mesh, cfg))
    if key in _RESHARD_CACHE:
        return _RESHARD_CACHE[key]
    entry = _load_table_cached(_table_path(cfg)).get(key)
    if isinstance(entry, dict) and entry.get("times"):
        best = entry.get("best")
        best = best if isinstance(best, str) else None
        _RESHARD_CACHE[key] = best
        return best
    if math.sqrt(max(plan.nbytes / 4.0, 1.0)) > cfg.autotune_max_dim:
        _RESHARD_CACHE[key] = None
        return None
    results: Dict[str, float] = {}
    for v in RESHARD_VARIANTS:
        try:
            t = measure_reshard_variant(v, plan, mesh, cfg)
        except Exception:  # noqa: BLE001  # matlint: disable=ML007 measurement loop — a variant failing to compile on this backend drops out of the table
            continue
        if t > 0.0:
            results[v] = t
    if len(results) < 2:
        _RESHARD_CACHE[key] = None
        return None
    best = _pick_winner(results)
    _RESHARD_CACHE[key] = best
    if cfg.autotune or cfg.autotune_table_path:
        _persist(_table_path(cfg), key, best, results)
    return best


# ---------------------------------------------------------------------------
# IVM patch-vs-recompute measurement (round 14) — the closed loop for the
# delta plane (serve/ivm.py; docs/IVM.md): per (delta rule, shape class,
# grid, backend), time the compiled patch plan's steady-state run against
# a fresh full-recompute plan's run and persist the winner like every
# other table family, so a backend where recompute beats the algebraic
# patch (tiny shapes, fat deltas) KILLS the entry instead of patching at
# a loss — the measured winner overrides the flop estimate, the `fuse|`
# precedent.
# ---------------------------------------------------------------------------

_IVM_CACHE: Dict[str, Optional[str]] = {}

IVM_VARIANTS = ("patch", "recompute")


def _ivm_key(rule: str, side: int, gx: int, gy: int,
             weights: Tuple[float, float] = (1.0, 1.0)) -> str:
    """``ivm|<rule>|<=side|gxXgy|backend[|w..]`` — side bucketed to the
    power of two at or above it (the drift auditor's shape-class
    granularity), rule from ir/delta.DELTA_RULES."""
    cls = 1 << max(0, math.ceil(math.log2(max(side, 1))))
    key = f"ivm|{rule}|{cls}|{gx}x{gy}|{jax.default_backend()}"
    if weights != (1.0, 1.0):
        key += f"|w{weights[0]:g}x{weights[1]:g}"
    return key


def lookup_or_measure_ivm(rule: str, side: int, mesh,
                          config: Optional[MatrelConfig] = None,
                          patch_s=None, full_s=None) -> Optional[str]:
    """Measured patch-vs-recompute winner for one (rule, shape class):
    "patch" / "recompute" / None (no measured preference — the flop
    estimate decides). ``patch_s``/``full_s`` are zero-arg callables
    returning median steady-state seconds for the two forms, invoked
    at most once each (the delta plane passes timed runs of plans it
    holds anyway); lookups without runners never measure. Ties and
    one-variant sets resolve to None and are never fake winners —
    the fusion loop's discipline verbatim."""
    cfg = config or default_config()
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    key = _ivm_key(rule, side, gx, gy, mesh_lib.axis_weights(mesh, cfg))
    if key in _IVM_CACHE:
        return _IVM_CACHE[key]
    entry = _load_table_cached(_table_path(cfg)).get(key)
    if isinstance(entry, dict) and entry.get("times"):
        best = entry.get("best")
        best = best if isinstance(best, str) else None
        _IVM_CACHE[key] = best
        return best
    if patch_s is None or full_s is None or side > cfg.autotune_max_dim:
        # no negative caching without a measurement: a later call that
        # CAN measure (runners in hand) must still get its chance
        return None
    results: Dict[str, float] = {}
    for name, fn in (("patch", patch_s), ("recompute", full_s)):
        try:
            t = float(fn())
        except Exception:  # noqa: BLE001  # matlint: disable=ML007 measurement loop — a variant failing on this backend drops out of the table
            continue
        if t > 0.0:
            results[name] = t
    if len(results) < 2:
        _IVM_CACHE[key] = None
        return None
    best = _pick_winner(results)
    _IVM_CACHE[key] = best
    if cfg.autotune or cfg.autotune_table_path:
        _persist(_table_path(cfg), key, best, results)
    return best
