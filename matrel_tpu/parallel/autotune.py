"""Strategy autotuning — empirical answer to SURVEY.md §7's hard part:
"proving the explicit SUMMA/psum_scatter paths beat XLA's choice (and
detecting when not)".

The cost model (planner.py) is an estimate; this module MEASURES. For a
given (n, k, m, mesh) it times every admissible strategy on-device
(marginal timing: chained dependent runs with a forced fetch, cancelling
dispatch latency — see bench.py methodology) and caches the winner. Use
``config.strategy_override`` per-session, or consult the returned table.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core import mesh as mesh_lib, padding
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.parallel import planner, strategies

_CACHE: Dict[tuple, Tuple[str, Dict[str, float]]] = {}


def measure_strategy(strategy: str, A: BlockMatrix, B: BlockMatrix,
                     config: MatrelConfig, reps: Tuple[int, int] = (2, 8)
                     ) -> float:
    """Marginal seconds per multiply for one strategy."""
    mesh = A.mesh
    f = jax.jit(lambda x, y: strategies.run_matmul(strategy, x, y, mesh,
                                                   config))
    fetch = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))

    def chained(n: int):
        cur = A.data
        for _ in range(n):
            cur = f(cur, B.data).astype(A.dtype)
        float(fetch(cur))

    chained(2)  # compile + warm
    lo, hi = reps
    t0 = time.perf_counter()
    chained(lo)
    t_lo = time.perf_counter() - t0
    t0 = time.perf_counter()
    chained(hi)
    t_hi = time.perf_counter() - t0
    return max((t_hi - t_lo) / (hi - lo), 1e-9)


def autotune_matmul(n: int, k: int, m: int,
                    mesh=None, dtype="float32",
                    config: Optional[MatrelConfig] = None
                    ) -> Tuple[str, Dict[str, float]]:
    """Times every admissible strategy for an (n×k)·(k×m) multiply on this
    mesh; returns (best_strategy, {strategy: seconds}). Results cached per
    (dims, mesh shape, dtype). Chained timing needs n == m == k for the
    feedback loop, so non-square requests are measured square at
    max(n, k, m) — the MXU/collective behaviour is shape-dominated."""
    cfg = config or default_config()
    mesh = mesh or mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names)
    side = max(n, k, m)
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    key = (side, gx, gy, str(dtype))
    if key in _CACHE:
        return _CACHE[key]
    A = BlockMatrix.random((side, side), mesh=mesh, seed=0, dtype=dtype)
    B = BlockMatrix.random((side, side), mesh=mesh, seed=1, dtype=dtype)
    pn, pk = padding.padded_shape((side, side), mesh)
    results: Dict[str, float] = {}
    for s in strategies.STRATEGIES:
        if s == "summa" and gx != gy:
            continue
        if not planner.admissible(s, pn, pk, pn, gx, gy):
            continue
        try:
            results[s] = measure_strategy(s, A, B, cfg)
        except Exception:  # noqa: BLE001 — a strategy failing to compile
            continue       # on this backend just drops out of the table
    best = min(results, key=results.get)
    _CACHE[key] = (best, results)
    return best, results
