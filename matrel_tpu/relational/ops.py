"""Relational operators over matrices — MatRel's contribution on top of
MatFast (SURVEY.md §2 "Physical: relational execs", §3.4; paper P1).

A matrix is viewed as the relation (i, j, v). MatRel provides:
  σ (selection)   on entry values, row/col indices, or blocks
  γ (aggregation) sum/count/avg/max/min over row/col/all/diag
  ⋈ (join)        of two matrices on index equality or value predicates,
                  entries combined by a merge function

Static-shape semantics (the XLA design decision flagged in SURVEY.md §7.6):
selections return same-shaped matrices with non-matching entries at 0 (the
relation's "missing"), plus nnz counts — never dynamically-shaped results.
The executor keeps 0 exactly representable (zero-padding invariant), so
σ/γ compose exactly with the linear-algebra ops.

This module is the user-facing surface; the nodes live in ir/expr.py and
lower in executor.py.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import expr as E

MatLike = Union[BlockMatrix, E.MatExpr]


# -- σ selection ------------------------------------------------------------


def select_entries(m: MatLike, predicate: Callable, fill: float = 0.0) -> E.MatExpr:
    """σ_pred on entry values: entries failing ``predicate(v)`` become
    ``fill`` (default 0 = missing)."""
    return E.as_expr(m).select_value(predicate, fill=fill)


def select_rows(m: MatLike, predicate: Callable) -> E.MatExpr:
    """σ on row index: keep rows i where ``predicate(i)`` (vectorised)."""
    return E.as_expr(m).select_index(rows=predicate)


def select_cols(m: MatLike, predicate: Callable) -> E.MatExpr:
    return E.as_expr(m).select_index(cols=predicate)


def select_blocks(m: MatLike, predicate: Callable,
                  block_size: Optional[int] = None) -> E.MatExpr:
    """σ on block index: keep entries whose (row_block, col_block) =
    (i // bs, j // bs) satisfies ``predicate(bi, bj)`` — the reference's
    block-granular selection, expressed through index predicates."""
    e = E.as_expr(m)
    if block_size is None:
        from matrel_tpu.config import default_config
        block_size = getattr(m, "block_size", None)
        if block_size is None:
            block_size = default_config().block_size
    bs = block_size
    return E.MatExpr("select_block", (e,), e.shape, e.nnz,
                     {"predicate": predicate, "block_size": bs})


# -- γ aggregation ----------------------------------------------------------


def aggregate(m: MatLike, kind: str, axis: str) -> E.MatExpr:
    """γ_kind over axis ∈ {row, col, all, diag}; kind ∈ {sum, count, avg,
    max, min}. count counts nonzero entries (the relation's tuples)."""
    return E.agg(E.as_expr(m), kind, axis)


# -- ⋈ joins ---------------------------------------------------------------


def join_on_index(a: MatLike, b: MatLike, merge: Callable) -> E.MatExpr:
    """⋈ on (i, j) equality — the co-partitioned cogroup join:
    C[i,j] = merge(A[i,j], B[i,j])."""
    return E.as_expr(a).join_on_index(E.as_expr(b), merge)


def join_on_rows(a: MatLike, b: MatLike, merge) -> E.MatExpr:
    """⋈ on row index only: C[i, (j_a, j_b)] pairs — statically shaped as
    the (n, m_a*m_b) matrix C[i, j_a*m_b + j_b] = merge(A[i,j_a], B[i,j_b]).
    The replication-scheme row join of the reference. ``merge`` is a
    callable or a structured string ("left"/"right"/"add"/"mul");
    structured kinds let the planner infer the output dtype."""
    ae, be = E.as_expr(a), E.as_expr(b)
    if ae.shape[0] != be.shape[0]:
        raise ValueError(f"row join needs equal row counts: {ae.shape} vs {be.shape}")
    shape = (ae.shape[0], ae.shape[1] * be.shape[1])
    merge_kind, merge_fn = E.resolve_join_merge(merge)
    return E.MatExpr("join_rows", (ae, be), shape, None,
                     {"merge": merge_fn, "merge_kind": merge_kind})


def join_on_cols(a: MatLike, b: MatLike, merge) -> E.MatExpr:
    """⋈ on column index: C[(i_a, i_b), j] = merge(A[i_a,j], B[i_b,j]),
    statically shaped (n_a*n_b, m). ``merge`` as in join_on_rows."""
    ae, be = E.as_expr(a), E.as_expr(b)
    if ae.shape[1] != be.shape[1]:
        raise ValueError(f"col join needs equal col counts: {ae.shape} vs {be.shape}")
    shape = (ae.shape[0] * be.shape[0], ae.shape[1])
    merge_kind, merge_fn = E.resolve_join_merge(merge)
    return E.MatExpr("join_cols", (ae, be), shape, None,
                     {"merge": merge_fn, "merge_kind": merge_kind})


def join_on_values(a: MatLike, b: MatLike, merge,
                   predicate=None) -> E.MatExpr:
    """⋈ on value predicate over all entry pairs; see ir.expr.join_on_value
    for the static pair-matrix semantics. ``merge``/``predicate`` may be
    callables OR structured strings (merge in "left"/"right"/"add"/
    "mul", predicate in "eq"/"lt"/"le"/"gt"/"ge") — structured forms let
    aggregated joins stream in O(n log n) without materialising pairs."""
    return E.as_expr(a).join_on_value(E.as_expr(b), merge, predicate)
