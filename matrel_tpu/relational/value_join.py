"""Scalable value-join evaluation — the streaming/sort kernels behind
``agg(join_on_value(A, B, ...), kind, axis)``.

The reference joins matrices on value predicates with join-scheme
selection so the pair relation never fully materialises (SURVEY.md §2
"Physical: relational execs"). The TPU-native equivalent here: the pair
matrix is an IR node that only EXISTS logically; when its consumer is an
aggregate, the executor calls into this module instead of materialising
(na, nb) entries:

- STRUCTURED predicate ("eq"/"lt"/"le"/"gt"/"ge" on ``va ? vb``) and
  merge ("left"/"right"/"add"/"mul"): sort B's entries once, then every
  per-A-entry aggregate over its matched set is a contiguous range of
  the sorted array — counts/sums/extrema come from prefix tables and
  ``searchsorted`` in O((na+nb)·log nb) with O(na+nb) memory. A 4k×4k ⋈
  4k×4k (16.7M × 16.7M pairs) aggregates without any pair allocation.
- CALLABLE merge/predicate (black boxes): chunked enumeration with a
  bounded live tile (config.join_chunk_entries), refused above
  config.join_bruteforce_max_pairs with a pointer at the structured
  forms.

Semantics match the dense lowering exactly (executor._join_value +
_agg): the pair matrix holds merge(va, vb) where the predicate holds
and 0 elsewhere, over ALL logical entries (zeros of A/B included);
"count" counts nonzero MERGED values; max/min see the implicit zeros of
unmatched pairs; avg = sum/count.

One DEFINED divergence: the streaming "count" decides nonzero-ness of a
merged pair in EXACT arithmetic (range counts of vb == 0 / vb == -va on
the sorted table), while the dense path tests the f32-ROUNDED merge —
when add/mul underflows (tiny + (-tiny), tiny * tiny → f32 0) or
overflows, the dense count drops/keeps pairs the exact count keeps. The
exact-arithmetic answer is the semantics of the streaming path: it is
scale-invariant and matches the relation's "merged value is zero"
meaning rather than an artifact of f32 rounding at 16M+ pair scale.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_PRED_SWAP = {"eq": "eq", "lt": "gt", "le": "ge", "gt": "lt",
              "ge": "le", "always": "always"}
_MERGE_SWAP = {"left": "right", "right": "left", "add": "add",
               "mul": "mul"}

AGG_KINDS = ("sum", "count", "avg", "max", "min")


def match_range(sv, x, pred: str, xp=jnp):
    """[lo, hi) into ascending-sorted ``sv`` (NaNs sorted last) of the
    entries matching predicate(x, vb) — every structured predicate
    selects a contiguous run. x: (q,) query values → (lo, hi): (q,)
    int32. The SINGLE implementation of the predicate→range semantics,
    shared by the streaming executor path (xp=jnp) and
    COOMatrix.join_on_value (xp=np).

    IEEE comparison semantics: NaN on either side matches NOTHING for
    the five comparison predicates (sort puts B's NaNs last; ranges
    clamp to the non-NaN prefix, NaN queries get empty ranges) —
    matching the dense masked lowering, where pred(NaN, ·) is False.
    "always" (predicate omitted) keeps every pair incl. NaNs, again
    like the dense path."""
    nb = sv.shape[0]
    i32 = (lambda a: a.astype(xp.int32))
    if pred == "always":      # predicate omitted: every pair matches
        z = i32(xp.zeros(x.shape))
        return z, xp.full_like(z, nb)
    n_valid = i32(nb - xp.isnan(sv).sum())
    left = i32(xp.searchsorted(sv, x, side="left"))
    right = i32(xp.searchsorted(sv, x, side="right"))
    if pred == "eq":
        lo, hi = left, right
    elif pred == "lt":        # vb > x
        lo, hi = right, xp.full_like(right, nb)
    elif pred == "le":        # vb >= x
        lo, hi = left, xp.full_like(left, nb)
    elif pred == "gt":        # vb < x
        lo, hi = xp.zeros_like(left), left
    elif pred == "ge":        # vb <= x
        lo, hi = xp.zeros_like(right), right
    else:
        raise ValueError(f"unknown structured predicate {pred!r}")
    lo = xp.minimum(lo, n_valid)
    hi = xp.minimum(hi, n_valid)
    hi = xp.where(xp.isnan(x), lo, hi)    # NaN query: empty range
    return lo, hi


_match_range = match_range


def _range_eq_count(sv, v, lo, hi):
    """#entries equal to v INSIDE [lo, hi) of sorted sv (int32-exact)."""
    zl = jnp.searchsorted(sv, v, side="left").astype(jnp.int32)
    zr = jnp.searchsorted(sv, v, side="right").astype(jnp.int32)
    return jnp.maximum(jnp.minimum(zr, hi) - jnp.maximum(zl, lo), 0)


def entry_stats(va, vb, pred: str, merge: str):
    """Per-A-entry aggregates of merge(va, ·) over the matched B set.

    Returns dict with float32 arrays shaped like ``va``:
      cnt      — matched-pair count
      nnz      — matched pairs whose MERGED value is nonzero
      sum      — Σ merge over matches
      mx / mn  — max / min of the PAIR-MATRIX ROW (merge over matches,
                 0 for every unmatched pair, 0 when the row is empty) —
                 i.e. exactly what the dense lowering's masked row
                 reduction sees.
    """
    va = jnp.asarray(va, jnp.float32)
    vb = jnp.asarray(vb, jnp.float32)
    nb = vb.shape[0]
    sv = jnp.sort(vb)
    # prefix sums over CENTERED values: a raw f32 cumsum of ~2^24
    # same-sign entries reaches ~n·|mean| and the range sum
    # ps[hi]-ps[lo] cancels catastrophically (observed: a 1-pair match
    # off by 20% at 16.7M entries); centering keeps the cumsum at
    # random-walk magnitude and restores the mean term exactly as
    # cnt·mean (cnt is integer-exact below 2^24 per range)
    # nanmean + NaNs-last sorting: the comparison predicates clamp
    # their ranges to the non-NaN prefix, so the poisoned cumsum tail
    # is never read (and "always" keeps dense NaN propagation)
    mean = jnp.nanmean(sv)
    ps = jnp.concatenate([jnp.zeros(1, jnp.float32),
                          jnp.cumsum(sv - mean, dtype=jnp.float32)])
    lo, hi = _match_range(sv, va, pred)
    # counts stay int32 through the arithmetic — float32 rounds above
    # 2^24; the final f32 CAST of the result rounds exactly like the
    # dense f32 lowering's own count output would
    cnt_i = hi - lo
    cnt = cnt_i.astype(jnp.float32)
    some = cnt_i > 0
    sum_vb = (ps[hi] - ps[lo]) + cnt * mean
    # extrema of the matched vb range (safe-read 0 when empty)
    mn_vb = jnp.where(some, sv[jnp.clip(lo, 0, nb - 1)], 0.0)
    mx_vb = jnp.where(some, sv[jnp.clip(hi - 1, 0, nb - 1)], 0.0)
    zeros_i = jnp.zeros_like(cnt_i)

    if merge == "left":
        m_sum = cnt * va
        m_nnz = jnp.where(va != 0, cnt_i, zeros_i)
        m_mx = m_mn = va
    elif merge == "right":
        m_sum = sum_vb
        m_nnz = cnt_i - _range_eq_count(sv, jnp.zeros_like(va), lo, hi)
        m_mx, m_mn = mx_vb, mn_vb
    elif merge == "add":
        m_sum = cnt * va + sum_vb
        m_nnz = cnt_i - _range_eq_count(sv, -va, lo, hi)
        m_mx, m_mn = va + mx_vb, va + mn_vb
    elif merge == "mul":
        m_sum = va * sum_vb
        m_nnz = jnp.where(
            va != 0,
            cnt_i - _range_eq_count(sv, jnp.zeros_like(va), lo, hi),
            zeros_i)
        pos = va >= 0
        m_mx = va * jnp.where(pos, mx_vb, mn_vb)
        m_mn = va * jnp.where(pos, mn_vb, mx_vb)
    else:
        raise ValueError(f"unknown structured merge {merge!r}")

    # fold the implicit zeros of unmatched pairs into the row extrema
    full = cnt_i >= nb
    mx = jnp.where(some, jnp.where(full, m_mx, jnp.maximum(m_mx, 0.0)),
                   0.0)
    mn = jnp.where(some, jnp.where(full, m_mn, jnp.minimum(m_mn, 0.0)),
                   0.0)
    zero = jnp.zeros_like(va)
    return {"cnt": cnt,
            "nnz": jnp.where(some, m_nnz, zeros_i).astype(jnp.float32),
            "sum": jnp.where(some, m_sum, zero),
            "mx": mx, "mn": mn}


def axis_agg_sorted(va, vb, pred: str, merge: str, kind: str,
                    axis: str) -> jax.Array:
    """Aggregate the (na, nb) pair matrix without building it.

    axis "row" → (na,) per-A-entry results; "col" → (nb,) per-B-entry
    (computed by swapping roles and mirroring predicate/merge);
    "all" → scalar ().
    """
    if kind not in AGG_KINDS:
        raise ValueError(f"unknown aggregate {kind!r}")
    if axis == "col":
        return axis_agg_sorted(vb, va, _PRED_SWAP[pred],
                               _MERGE_SWAP[merge], kind, "row")
    st = entry_stats(va, vb, pred, merge)
    if axis == "row":
        if kind == "sum":
            return st["sum"]
        if kind == "count":
            return st["nnz"]
        if kind == "avg":
            return jnp.where(st["nnz"] > 0, st["sum"] / st["nnz"], 0.0)
        return st["mx"] if kind == "max" else st["mn"]
    if axis == "all":
        if kind == "sum":
            return jnp.sum(st["sum"])
        if kind == "count":
            return jnp.sum(st["nnz"])
        if kind == "avg":
            c = jnp.sum(st["nnz"])
            return jnp.where(c > 0, jnp.sum(st["sum"]) / c, 0.0)
        # row extrema already include unmatched zeros / empty-row zeros
        return (jnp.max(st["mx"]) if kind == "max"
                else jnp.min(st["mn"]))
    raise ValueError(f"unknown axis {axis!r} for a value-join "
                     "aggregate (diag is handled elementwise upstream)")


def axis_agg_chunked(va, vb, merge_fn, pred_fn, kind: str, axis: str,
                     chunk_entries: int) -> jax.Array:
    """Black-box fallback: enumerate pair blocks (na, cb) chunkwise over
    B with a bounded live tile; callers gate total pairs with
    config.join_bruteforce_max_pairs. axis "col" swaps the roles (the
    merge/predicate argument order is preserved via wrappers); "all"
    reduces the row results."""
    if kind not in AGG_KINDS:
        raise ValueError(f"unknown aggregate {kind!r}")
    if axis == "col":
        return axis_agg_chunked(
            vb, va, lambda b, a: merge_fn(a, b),
            None if pred_fn is None else (lambda b, a: pred_fn(a, b)),
            kind, "row", chunk_entries)
    va = jnp.asarray(va, jnp.float32)
    vb = jnp.asarray(vb, jnp.float32)
    na, nb = va.shape[0], vb.shape[0]
    if nb == 0:
        # degenerate empty-B join: every row of the pair matrix is
        # empty; the scan below would leave the ∓inf extrema inits in
        # place. All aggregates of an empty row are 0.
        z = jnp.zeros(na, jnp.float32)
        return jnp.asarray(0.0) if axis == "all" else z
    cb = max(1, min(nb, chunk_entries // max(na, 1)))
    n_chunks = -(-nb // cb)
    pad = n_chunks * cb - nb
    vb_pad = jnp.pad(vb, (0, pad))
    valid_tail = jnp.arange(n_chunks * cb) < nb

    def body(carry, j):
        s, c, mx, mn = carry
        b = jax.lax.dynamic_slice(vb_pad, (j * cb,), (cb,))
        vmask = jax.lax.dynamic_slice(valid_tail, (j * cb,), (cb,))
        pairs = merge_fn(va[:, None], b[None, :])
        if pred_fn is not None:
            pairs = jnp.where(pred_fn(va[:, None], b[None, :]), pairs,
                              0.0)
        pairs = jnp.where(vmask[None, :], pairs, 0.0)
        s = s + jnp.sum(pairs, axis=1)
        c = c + jnp.sum((pairs != 0), axis=1).astype(jnp.float32)
        # PADDED slots must not leak their exact 0 into the extrema (a
        # row whose true pairs are all negative has a negative max) —
        # mask them to ∓inf; real unmatched pairs keep their 0, exactly
        # as the dense lowering's masked rows see them
        mx = jnp.maximum(mx, jnp.max(
            jnp.where(vmask[None, :], pairs, -jnp.inf), axis=1))
        mn = jnp.minimum(mn, jnp.min(
            jnp.where(vmask[None, :], pairs, jnp.inf), axis=1))
        return (s, c, mx, mn), None

    init = (jnp.zeros(na, jnp.float32), jnp.zeros(na, jnp.float32),
            jnp.full(na, -jnp.inf), jnp.full(na, jnp.inf))
    (s, c, mx, mn), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    # no finiteness masking here: a legitimate ±inf/NaN extremum (an inf
    # operand entry) must surface exactly as the dense lowering reports
    # it; the ∓inf inits cannot survive because nb >= 1 guarantees every
    # row sees at least one valid (non-padded) slot
    if axis == "all":
        if kind == "sum":
            return jnp.sum(s)
        if kind == "count":
            return jnp.sum(c)
        if kind == "avg":
            ct = jnp.sum(c)
            return jnp.where(ct > 0, jnp.sum(s) / ct, 0.0)
        return jnp.max(mx) if kind == "max" else jnp.min(mn)
    if kind == "sum":
        return s
    if kind == "count":
        return c
    if kind == "avg":
        return jnp.where(c > 0, s / c, 0.0)
    return mx if kind == "max" else mn
