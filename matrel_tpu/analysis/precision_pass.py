"""Precision pass: MV108 (stamped tier must satisfy the query SLA).

The precision tier chooser (planner.choose_precision_tier) picks
per-matmul among f32 / bf16 split-summation / integer-exact paths under
the query's accuracy SLA (config.precision_sla; docs/PRECISION.md). A
fresh annotation cannot violate the SLA — the chooser only offers
satisfying tiers — so a violating stamp is a stale cached plan, a
hand-stamped attr, or config drift between stamping and verification:
exactly the class of silent WRONG-ANSWER bug (a "fast" bf16 tier
executing an "exact" query) the static layer exists to catch before
anything runs. Severity is "error": unlike a mispriced plan, a
mis-tiered plan computes a different answer than the SLA promised.

The pass also re-derives integer-exactness (stats.infer_integral): an
int tier stamped on operands that are NOT provably integer-valued
truncates real data — flagged even under "fast" (an accuracy SLA never
licenses silent truncation; the explicit "int32"/"int8" dtype SLAs are
the caller's declaration and downgrade the finding to a warning).
"""

from __future__ import annotations

from typing import Iterator

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr
from matrel_tpu.ir import stats
from matrel_tpu.parallel import planner


def _satisfying_tiers(sla: str, integral: bool, config) -> tuple:
    """Every tier admissible under the SLA for verification purposes —
    sla_allowed_tiers WITHOUT the enable-flag gating (the flags shape
    the chooser's search space, not the accuracy contract: a bf16x3
    stamp still satisfies "high" even if the gate that would have
    chosen it is now off)."""
    if sla == "default":
        # no SLA was requested; only the untier lowering is sanctioned
        return ()
    pinned = planner._DTYPE_SLA_TIER.get(sla)
    if pinned is not None:
        return (pinned,)
    tiers = ["f32"]
    if integral:
        tiers += ["int32", "int8"]
    if sla in ("high", "fast"):
        tiers.append("bf16x3")
    if sla == "fast":
        tiers.append("bf16x1")
    return tuple(tiers)


def check_precision_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    """MV108: every stamped ``precision_tier`` is (a) in the tier
    vocabulary and (b) at least as accurate as the query SLA promises
    for these operands. Plans with no stamps verify free; the
    "default" SLA with no stamps pays one attr read per matmul."""
    sla = config.precision_sla
    seen = set()
    imemo: dict = {}    # one shared integrality/magnitude memo per
    # verification run — per-node fresh memos would make deep-chain
    # verification O(nodes²) (the infer_dtype precedent, review r8)

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind != "matmul":
            return
        tier = n.attrs.get("precision_tier")
        if tier is None:
            return                 # untier lowering satisfies any SLA
        if tier not in planner.PRECISION_TIERS:
            yield Diagnostic(
                code="MV108", severity="error", node=node_addr(n),
                message=f"stamped precision tier {tier!r} is not in "
                        f"the vocabulary {planner.PRECISION_TIERS}",
                fix_hint="re-plan (annotate_strategies stamps only "
                         "vocabulary tiers)")
            return
        integral = stats.infer_integral(n, imemo)
        explicit_int = planner._DTYPE_SLA_TIER.get(sla) in ("int32",
                                                            "int8")
        if tier in ("int32", "int8") and not integral:
            yield Diagnostic(
                code="MV108",
                # an explicit "int32"/"int8" dtype SLA is the caller's
                # own declaration that the data is integer-valued — the
                # unprovable cast is then a warning, not an error
                severity="warning" if explicit_int else "error",
                node=node_addr(n),
                message=f"integer tier {tier!r} stamped on operands "
                        "that are not provably integer-valued — the "
                        "int cast would truncate real data",
                fix_hint="mark the source matrices integral "
                         "(BlockMatrix(..., integral=True)) if they "
                         "really hold integers, or re-plan")
            return
        if tier in ("int32", "int8") and integral \
                and not planner.int_tier_fits(n, tier, imemo):
            # the magnitude half of the exactness proof: a PROVABLE
            # int32-accumulator overflow (or int8 cast overflow) wraps
            # silently — wrong answers, error always; an UNKNOWN bound
            # is the caller's risk only under an explicit int pin
            ba = stats.integral_abs_bound(n.children[0], imemo)
            bb = stats.integral_abs_bound(n.children[1], imemo)
            provable = ba is not None and bb is not None
            yield Diagnostic(
                code="MV108",
                severity=("error" if provable or not explicit_int
                          else "warning"),
                node=node_addr(n),
                message=(f"integer tier {tier!r}: accumulated product "
                         f"bound k·|A|·|B| = "
                         f"{n.children[0].shape[1]}·{ba}·{bb} "
                         f"exceeds the int32 accumulator "
                         f"({planner.INT32_ACC_MAX:.3g}) — silent "
                         "wraparound" if provable else
                         f"integer tier {tier!r} stamped without a "
                         "provable magnitude bound — overflow safety "
                         "cannot be verified"),
                fix_hint="keep f32 for this magnitude (re-plan under "
                         "the named SLA — the chooser's overflow gate "
                         "refuses unprovable int picks) or shrink the "
                         "operand values")
            return
        ok = _satisfying_tiers(sla, integral, config)
        if tier not in ok:
            oks = str(ok) if ok else "(none: default SLA stamps nothing)"
            yield Diagnostic(
                code="MV108", severity="error", node=node_addr(n),
                message=f"stamped tier {tier!r} does not satisfy the "
                        f"query SLA {sla!r} for these operands "
                        f"(integral={integral}; satisfying tiers: "
                        f"{oks}) — the lowering would "
                        "compute a less accurate answer than promised",
                fix_hint="re-plan under the query's SLA "
                         "(session.run(expr, precision=...)) or relax "
                         "the SLA if the tier is intended")

    yield from walk(root)
