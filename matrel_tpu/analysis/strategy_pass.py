"""Strategy-stamp consistency passes: MV101 (admissibility) and MV104
(SpGEMM dispatch consistency).

The planner stamps every matmul with ``attrs["strategy"]``; the
executor's shard_map recipes then carve the PADDED dims by that
strategy's specs. A stamp outside the admissible set would make the
shard_map spec fail to divide — a trace-time crash at best, a silent
GSPMD fallback at worst — and a stamp the lowering will not actually
run (the S×S SpGEMM dispatch ignores the byte model entirely) makes
every obs/ report and comm estimate describe a program that never
executes. Both are exactly the class of plan bug arXiv:2112.01075
argues must be caught before the chip sees the program.
"""

from __future__ import annotations

from typing import Iterator, Optional

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr
from matrel_tpu.core import mesh as mesh_lib, padding
from matrel_tpu.parallel import planner

#: Strategy vocabulary a stamp may carry (planner.STRATEGY_OUT_LAYOUT
#: is the one shared mapping; "spgemm" is the dispatch stamp).
KNOWN_STRATEGIES = tuple(planner.STRATEGY_OUT_LAYOUT)


def _dispatch_kind(node, config) -> Optional[str]:
    """Which off-strategy fast path the lowering takes for this matmul,
    or None for the dense shard_map path. Consults the executor's OWN
    single-source-of-truth predicates (never a re-derivation), and
    checks them in Lowerer._matmul's exact ORDER — spgemm, then
    coo_leaf on either side, then sparse_leaf: a mixed coo×sparse
    matmul takes the COO path, not SpMM (review r6 — the sparse-first
    order silently misclassified that mix)."""
    from matrel_tpu import executor as exec_lib
    if exec_lib._spgemm_dispatch(node, config):
        return "spgemm"
    if any(c.kind == "coo_leaf" for c in node.children):
        return ("coo_spmv" if exec_lib._coo_dispatch_plan(node) is not None
                else "densify")
    if any(c.kind == "sparse_leaf" for c in node.children):
        return "spmm"
    return None


def check_strategy_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    """MV101: every stamped strategy must be (a) in the known
    vocabulary and (b) admissible for the node's padded dims on this
    mesh grid — divisibility AND the HBM budget, the same
    ``planner.admissible`` gate the planner itself now runs, re-checked
    here so a plan annotated under a DIFFERENT mesh/config (a cached or
    hand-stamped plan) cannot smuggle an infeasible recipe through.
    Dispatch-overridden matmuls (SpMM/SpMV/SpGEMM paths) skip (b): the
    stamp is reporting metadata there, not a shard_map recipe."""
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    seen = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind != "matmul" or "strategy" not in n.attrs:
            return
        strat = n.attrs["strategy"]
        if strat not in KNOWN_STRATEGIES:
            yield Diagnostic(
                code="MV101", severity="error", node=node_addr(n),
                message=f"stamped strategy {strat!r} is not in the "
                        f"planner vocabulary {KNOWN_STRATEGIES}",
                fix_hint="re-run planner.annotate_strategies, or fix "
                         "the strategy_override string")
            return
        if _dispatch_kind(n, config) is not None:
            return          # fast-path dispatch: no shard_map specs run
        a, b = n.children
        nn, kk = a.shape
        mm = b.shape[1]
        pn, pk = padding.padded_shape((nn, kk), mesh)
        _, pm = padding.padded_shape((kk, mm), mesh)
        if not planner.admissible(strat, pn, pk, pm, gx, gy,
                                  hbm_budget_bytes=0):
            yield Diagnostic(
                code="MV101", severity="error", node=node_addr(n),
                message=f"stamped strategy {strat!r} cannot divide the "
                        f"padded dims ({pn}, {pk}, {pm}) on the "
                        f"{gx}x{gy} grid",
                fix_hint="the plan was annotated for a different "
                         "mesh/padding — re-plan on this mesh")

    yield from walk(root)


def check_spgemm_dispatch(root, mesh, config) -> Iterator[Diagnostic]:
    """MV104: a ``("spgemm", "dispatch")`` stamp and the executor's
    ``_spgemm_dispatch`` predicate must agree in BOTH directions.

    Stamp without dispatch: the lowering will densify (or run a
    shard_map strategy) while obs/explain report a SpGEMM that never
    ran and the comm model priced 0 bytes — the estimated-savings
    records (``spgemm_estimates``) become fiction. Dispatch without
    stamp: the lowering runs the tile-intersection kernel while the
    plan claims a dense strategy, so ``to_dense`` no-densify guarantees
    are asserted against the wrong path. The no-densify guarantee
    itself holds exactly when the stamp is truthful: the dispatch
    predicate requires both operands to be sparse leaves and the
    estimated output block density under the threshold, and the
    spgemm lowering (ops/spgemm.py) touches only the operand tile
    stacks — no ``to_dense`` is reachable from a truthfully-stamped
    node (test_spgemm.py's poisoned-to_dense test proves it
    dynamically; this pass pins it statically)."""
    seen = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind != "matmul":
            return
        stamped = n.attrs.get("strategy") == "spgemm"
        dispatches = _dispatch_kind(n, config) == "spgemm"
        if stamped and not dispatches:
            yield Diagnostic(
                code="MV104", severity="error", node=node_addr(n),
                message="stamped ('spgemm', "
                        f"{n.attrs.get('strategy_source', '?')!r}) but "
                        "executor._spgemm_dispatch refuses this node "
                        "under the verifying config — the lowering "
                        "would densify while the plan reports a "
                        "no-densify SpGEMM",
                fix_hint="re-plan under the executing config (the "
                         "spgemm_density_threshold or operand stats "
                         "changed since annotation)")
        elif dispatches and not stamped:
            yield Diagnostic(
                code="MV104", severity="error", node=node_addr(n),
                message=f"executor will dispatch the S×S SpGEMM but "
                        f"the stamp says "
                        f"{n.attrs.get('strategy', '<unstamped>')!r} — "
                        "obs/explain would misreport what executes",
                fix_hint="stamp via planner.annotate_strategies instead "
                         "of hand-setting attrs['strategy']")

    yield from walk(root)
