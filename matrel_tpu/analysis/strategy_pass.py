"""Strategy-stamp consistency passes: MV101 (admissibility) and MV104
(SpGEMM dispatch consistency).

The planner stamps every matmul with ``attrs["strategy"]``; the
executor's shard_map recipes then carve the PADDED dims by that
strategy's specs. A stamp outside the admissible set would make the
shard_map spec fail to divide — a trace-time crash at best, a silent
GSPMD fallback at worst — and a stamp the lowering will not actually
run (the S×S SpGEMM dispatch ignores the byte model entirely) makes
every obs/ report and comm estimate describe a program that never
executes. Both are exactly the class of plan bug arXiv:2112.01075
argues must be caught before the chip sees the program.
"""

from __future__ import annotations

from typing import Iterator, Optional

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr
from matrel_tpu.core import mesh as mesh_lib, padding
from matrel_tpu.parallel import planner

#: Strategy vocabulary a stamp may carry (planner.STRATEGY_OUT_LAYOUT
#: is the one shared mapping; "spgemm" is the dispatch stamp).
KNOWN_STRATEGIES = tuple(planner.STRATEGY_OUT_LAYOUT)


def _dispatch_kind(node, config) -> Optional[str]:
    """Which off-strategy fast path the lowering takes for this matmul,
    or None for the dense shard_map path. Consults the executor's OWN
    single-source-of-truth predicates (never a re-derivation), and
    checks them in Lowerer._matmul's exact ORDER — spgemm, then
    coo_leaf on either side, then sparse_leaf: a mixed coo×sparse
    matmul takes the COO path, not SpMM (review r6 — the sparse-first
    order silently misclassified that mix)."""
    from matrel_tpu import executor as exec_lib
    if exec_lib._spgemm_dispatch(node, config):
        return "spgemm"
    if any(c.kind == "coo_leaf" for c in node.children):
        return ("coo_spmv" if exec_lib._coo_dispatch_plan(node) is not None
                else "densify")
    if any(c.kind == "sparse_leaf" for c in node.children):
        return "spmm"
    return None


def check_strategy_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    """MV101: every stamped strategy must be (a) in the known
    vocabulary and (b) admissible for the node's padded dims on this
    mesh grid — divisibility AND the HBM budget, the same
    ``planner.admissible`` gate the planner itself now runs, re-checked
    here so a plan annotated under a DIFFERENT mesh/config (a cached or
    hand-stamped plan) cannot smuggle an infeasible recipe through.
    Dispatch-overridden matmuls (SpMM/SpMV/SpGEMM paths) skip (b): the
    stamp is reporting metadata there, not a shard_map recipe."""
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    seen = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind != "matmul" or "strategy" not in n.attrs:
            return
        strat = n.attrs["strategy"]
        if strat not in KNOWN_STRATEGIES:
            yield Diagnostic(
                code="MV101", severity="error", node=node_addr(n),
                message=f"stamped strategy {strat!r} is not in the "
                        f"planner vocabulary {KNOWN_STRATEGIES}",
                fix_hint="re-run planner.annotate_strategies, or fix "
                         "the strategy_override string")
            return
        if _dispatch_kind(n, config) is not None:
            return          # fast-path dispatch: no shard_map specs run
        a, b = n.children
        nn, kk = a.shape
        mm = b.shape[1]
        pn, pk = padding.padded_shape((nn, kk), mesh)
        _, pm = padding.padded_shape((kk, mm), mesh)
        if not planner.admissible(strat, pn, pk, pm, gx, gy,
                                  hbm_budget_bytes=0):
            yield Diagnostic(
                code="MV101", severity="error", node=node_addr(n),
                message=f"stamped strategy {strat!r} cannot divide the "
                        f"padded dims ({pn}, {pk}, {pm}) on the "
                        f"{gx}x{gy} grid",
                fix_hint="the plan was annotated for a different "
                         "mesh/padding — re-plan on this mesh")

    yield from walk(root)


def check_spgemm_dispatch(root, mesh, config) -> Iterator[Diagnostic]:
    """MV104: a ``("spgemm", "dispatch")`` stamp and the executor's
    ``_spgemm_dispatch`` predicate must agree in BOTH directions.

    Stamp without dispatch: the lowering will densify (or run a
    shard_map strategy) while obs/explain report a SpGEMM that never
    ran and the comm model priced 0 bytes — the estimated-savings
    records (``spgemm_estimates``) become fiction. Dispatch without
    stamp: the lowering runs the tile-intersection kernel while the
    plan claims a dense strategy, so ``to_dense`` no-densify guarantees
    are asserted against the wrong path. The no-densify guarantee
    itself holds exactly when the stamp is truthful: the dispatch
    predicate requires both operands to be sparse leaves and the
    estimated output block density under the threshold, and the
    spgemm lowering (ops/spgemm.py) touches only the operand tile
    stacks — no ``to_dense`` is reachable from a truthfully-stamped
    node (test_spgemm.py's poisoned-to_dense test proves it
    dynamically; this pass pins it statically)."""
    seen = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind != "matmul":
            return
        stamped = n.attrs.get("strategy") == "spgemm"
        dispatches = _dispatch_kind(n, config) == "spgemm"
        if stamped and not dispatches:
            yield Diagnostic(
                code="MV104", severity="error", node=node_addr(n),
                message="stamped ('spgemm', "
                        f"{n.attrs.get('strategy_source', '?')!r}) but "
                        "executor._spgemm_dispatch refuses this node "
                        "under the verifying config — the lowering "
                        "would densify while the plan reports a "
                        "no-densify SpGEMM",
                fix_hint="re-plan under the executing config (the "
                         "spgemm_density_threshold or operand stats "
                         "changed since annotation)")
        elif dispatches and not stamped:
            yield Diagnostic(
                code="MV104", severity="error", node=node_addr(n),
                message=f"executor will dispatch the S×S SpGEMM but "
                        f"the stamp says "
                        f"{n.attrs.get('strategy', '<unstamped>')!r} — "
                        "obs/explain would misreport what executes",
                fix_hint="stamp via planner.annotate_strategies instead "
                         "of hand-setting attrs['strategy']")

    yield from walk(root)


def check_spgemm_kernel(root, mesh, config) -> Iterator[Diagnostic]:
    """MV110: a stamped ``spgemm_kernel`` must be truthful in BOTH
    directions under the verifying config.

    Forward: the stamped kernel id must exist in the registry
    (ops/kernel_registry.py), be runnable here (a Pallas id stamped
    where Pallas cannot run would crash — or silently densify — at
    lowering), and be admissible for the operand pair's structure
    class: a specialized kernel stamped on a FOREIGN structure (absent
    the config forcing knob) means the plan was annotated under
    different operand statistics, so its cost record describes a
    schedule the registry would no longer pick. The stamped structure
    class itself is re-derived and compared, the MV104 re-check
    discipline. Backward: a kernel stamp on a node that does NOT
    dispatch the SpGEMM path is reporting metadata for a lowering that
    never runs."""
    from matrel_tpu import executor as exec_lib
    from matrel_tpu.ir import stats
    from matrel_tpu.ops import kernel_registry as kr
    seen = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind != "matmul":
            return
        kid = n.attrs.get("spgemm_kernel")
        if kid is None:
            # unstamped dispatch is legal: the lowering asks the
            # shared chooser itself (MV104 owns stamp/dispatch
            # agreement for the strategy)
            return
        if _dispatch_kind(n, config) != "spgemm":
            yield Diagnostic(
                code="MV110", severity="error", node=node_addr(n),
                message=f"spgemm_kernel {kid!r} stamped but the node "
                        "does not dispatch the S×S SpGEMM under the "
                        "verifying config — the kernel record "
                        "describes a lowering that never runs",
                fix_hint="re-plan under the executing config")
            return
        if kid not in kr.REGISTRY:
            yield Diagnostic(
                code="MV110", severity="error", node=node_addr(n),
                message=f"stamped spgemm_kernel {kid!r} is not in the "
                        f"kernel registry {kr.kernel_ids()}",
                fix_hint="re-run planner.annotate_strategies, or fix "
                         "the spgemm_kernel_override string")
            return
        spec = kr.get_kernel(kid)
        bs = exec_lib._spgemm_block_size(n, config)
        est = exec_lib.spgemm_estimates(n, config)
        npairs = max(int(round(est.get("est_pairs") or 0.0)), 1)
        if not kr.admissible(kid, bs, npairs, config):
            # the FULL runnability gate (the lowering's own): Pallas
            # availability, the 8-sublane block rule, VMEM-feasible
            # group — a stamp failing any of these makes the lowering
            # silently swap in the legacy default while the decision
            # record still names this kernel
            yield Diagnostic(
                code="MV110", severity="error", node=node_addr(n),
                message=f"stamped spgemm_kernel {kid!r} is not "
                        "runnable under the verifying config (Pallas "
                        "gate, 8-sublane block rule, or VMEM-feasible "
                        "group) — the lowering would silently run the "
                        "legacy default while obs records this kernel",
                fix_hint="re-plan under the executing config, or "
                         "force the XLA entry "
                         "(spgemm_kernel_override='xla_gather')")
            return
        derived = stats.pair_structure_class(
            kr.structure_of_child(n.children[0], bs),
            kr.structure_of_child(n.children[1], bs))
        stamped_struct = n.attrs.get("spgemm_structure")
        if stamped_struct is not None and stamped_struct != derived:
            yield Diagnostic(
                code="MV110", severity="error", node=node_addr(n),
                message=f"stamped structure class {stamped_struct!r} "
                        f"but the operand pair classifies "
                        f"{derived!r} — operand statistics changed "
                        "since annotation",
                fix_hint="re-plan so the kernel choice sees the "
                         "current structure")
            return
        forced = (config.spgemm_kernel_override
                  if config is not None else "")
        if (not spec.universal and derived not in spec.structures
                and forced != kid):
            yield Diagnostic(
                code="MV110", severity="error", node=node_addr(n),
                message=f"specialized kernel {kid!r} stamped on "
                        f"foreign structure class {derived!r} "
                        f"(home: {spec.structures}) without an "
                        "override — the registry would not pick this "
                        "schedule here",
                fix_hint="re-plan, or force it explicitly via "
                         "config.spgemm_kernel_override")

    yield from walk(root)
