"""Static plan verifier — pre-execution invariant analysis for
physical plans (the missing static half of the planner subsystem).

The optimizer/planner's value proposition is picking a CORRECT AND
FEASIBLE physical plan before anything runs on hardware (PAPER.md;
SURVEY.md §2 "Physical planner"); through round 5 the repo had a deep
cost model but nothing that statically checked its outputs — the
invariants (strategy admissibility, layout-claim truthfulness, the
zero-padding rule, the SpGEMM no-densify guarantee, per-chip HBM
feasibility) were enforced only by scattered dynamic tests.
Array-redistribution correctness at scale is exactly the class of bug
a static checker catches before the chip does (arXiv:2112.01075), and
per-chip memory is the binding constraint there (arXiv:2112.09017).

Usage:

    from matrel_tpu import analysis
    diags = analysis.verify_plan(annotated_expr, mesh, config)

``verify_plan`` expects a PLANNED tree (post
``planner.annotate_strategies``); the executor runs it automatically
under ``config.verify_plans`` ("warn" logs, "error" raises
:class:`VerificationError` before tracing), ``session.verify(expr)``
runs it on demand, and ``session.explain`` renders the findings.

Pass registry (each: ``fn(root, mesh, config) -> Iterator[Diagnostic]``;
codes documented in :mod:`matrel_tpu.analysis.diagnostics`):

  strategy   MV101  stamped strategy admissible on this mesh
  spgemm     MV104  SpGEMM stamp <-> dispatch predicate agreement
  spgemm_kernel MV110 stamped kernel id in-registry + admissible for
                    the stamped structure class (both directions)
  layout     MV102  infer_layout claims pinned by the lowering
  padding    MV103  zero-padding invariant restored after breakers
  hbm        MV105  per-device working set fits hbm_budget_bytes
  topology   MV106  dominant collective off the slow (DCN) mesh axis
  result_cache MV107 result-cache stamp agrees with the cached entry
  precision  MV108  stamped precision tier satisfies the query SLA
  reshard    MV109  staged reshard peaks fit reshard_peak_budget_bytes
  fusion     MV111  fused-region stamps cover exactly the regions the
                    executor lowers (both directions); tier/remask
                    preserved; fusion off stamps nothing
  brownout   MV112  brownout stamps agree with the rung that claims
                    them (tier downshift matches the compile SLA,
                    staleness only at rung >= 2, no stamps with the
                    controller off)
  delta      MV113  delta-patched result-cache provenance is coherent
                    (rule in ir/delta.DELTA_RULES, generation >= 1,
                    finite composed bound); the DYNAMIC half
                    (delta_pass.verify_patched_entries) proves every
                    surviving patched entry against fresh execution
                    within that bound — docs/IVM.md
  provenance MV115  answer-lineage stamps cohere with the mechanism
                    stamps both directions (provenance ⇔ result_cache
                    key hashes, ivm_patched ⇔ delta, fleet_replica
                    backed by fleet; unknown paths/schemas warn); the
                    DYNAMIC half (provenance_pass.verify_ledger)
                    audits a live ledger's records — docs/OBSERVABILITY.md
  cse        MV116  cross-query CSE stamps agree with the hoisted
                    result they ride (layout/dtype, uses >= 2); the
                    DYNAMIC half (cse_pass.verify_cse_executions)
                    proves recent CSE-substituted batch roots equal
                    their unshared executions — docs/SERVING.md
  spill      MV117  spill-thaw provenance stamps cohere with the tier
                    hierarchy (legs are what spill_plan stages from
                    the claimed tier, fits verdict matches the live
                    peak budget, cost provenance classifiable) —
                    docs/DURABILITY.md
"""

from __future__ import annotations

import logging
from typing import List, Optional

from matrel_tpu.analysis.brownout_pass import check_brownout_stamps
from matrel_tpu.analysis.cse_pass import check_cse_stamps
from matrel_tpu.analysis.delta_pass import check_delta_stamps
from matrel_tpu.analysis.diagnostics import (  # noqa: F401 (re-export)
    Diagnostic, VerificationError)
from matrel_tpu.analysis.fusion_pass import check_fusion_stamps
from matrel_tpu.analysis.hbm_pass import check_hbm_feasibility
from matrel_tpu.analysis.layout_pass import check_layout_claims
from matrel_tpu.analysis.padding_pass import check_padding_flow
from matrel_tpu.analysis.placement_pass import check_placement_stamps
from matrel_tpu.analysis.precision_pass import check_precision_stamps
from matrel_tpu.analysis.provenance_pass import check_provenance_stamps
from matrel_tpu.analysis.reshard_pass import check_reshard_peaks
from matrel_tpu.analysis.result_cache_pass import check_result_cache_stamps
from matrel_tpu.analysis.spill_pass import check_spill_stamps
from matrel_tpu.analysis.strategy_pass import (check_spgemm_dispatch,
                                               check_spgemm_kernel,
                                               check_strategy_stamps)
from matrel_tpu.analysis.topology_pass import check_axis_traffic
from matrel_tpu.config import MatrelConfig, default_config

log = logging.getLogger("matrel_tpu.analysis")

#: (name, pass_fn) in report order. Passes are independent reads of the
#: same annotated tree; each walks the DAG once, so a full verify is
#: O(passes x nodes) with no tracing and no device work.
PASSES = (
    ("strategy", check_strategy_stamps),
    ("spgemm", check_spgemm_dispatch),
    ("spgemm_kernel", check_spgemm_kernel),
    ("layout", check_layout_claims),
    ("padding", check_padding_flow),
    ("hbm", check_hbm_feasibility),
    ("topology", check_axis_traffic),
    ("result_cache", check_result_cache_stamps),
    ("precision", check_precision_stamps),
    ("reshard", check_reshard_peaks),
    ("fusion", check_fusion_stamps),
    ("brownout", check_brownout_stamps),
    ("delta", check_delta_stamps),
    ("placement", check_placement_stamps),
    ("provenance", check_provenance_stamps),
    ("cse", check_cse_stamps),
    ("spill", check_spill_stamps),
)


def verify_plan(root, mesh, config: Optional[MatrelConfig] = None,
                passes=None) -> List[Diagnostic]:
    """Run every verifier pass over an ANNOTATED plan; returns the
    (possibly empty) diagnostic list, errors first. Never raises on a
    bad plan — escalation is the caller's policy (see
    :func:`enforce`)."""
    cfg = config or default_config()
    out: List[Diagnostic] = []
    for _name, fn in (PASSES if passes is None else passes):
        out.extend(fn(root, mesh, cfg))
    out.sort(key=lambda d: (d.severity != "error", d.code))
    return out


def enforce(diagnostics: List[Diagnostic],
            mode: str, context: str = "plan") -> None:
    """Apply a ``config.verify_plans`` policy to a diagnostic list:
    "warn" logs each finding; "error" additionally raises
    :class:`VerificationError` when any error-severity diagnostic is
    present (warnings alone never fail a query). "off" or an empty
    list is a no-op."""
    if mode == "off" or not diagnostics:
        return
    for d in diagnostics:
        log.warning("verify(%s): %s", context, d.render())
    if mode == "error" and any(d.severity == "error"
                               for d in diagnostics):
        raise VerificationError(diagnostics)


def render(diagnostics: List[Diagnostic]) -> str:
    """The EXPLAIN section body: one line per finding, or the explicit
    all-clear (so a clean report is distinguishable from a skipped
    verify)."""
    if not diagnostics:
        return "clean (0 diagnostics)"
    return "\n".join(d.render() for d in diagnostics)
