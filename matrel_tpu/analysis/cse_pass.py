"""MV116 — cross-query CSE substitution must be provably transparent.

A consumer plan that feeds on a batch-shared hoisted interior
(serve/mqo.py) carries the ``cse`` stamp the session wrote at hoist
time (``attrs["cse"]``: the layout and dtype the hoist recorded, its
key hash, the transitive dep ids, the use count). Like MV107 for the
result cache, the planner credited the reuse on exactly the recorded
layout/dtype — a stamp that no longer agrees with the leaf's ACTUAL
matrix means the plan was costed (and will be reported by obs) on a
premise the hoist no longer backs.

The static half (:func:`check_cse_stamps`) is warning severity, the
MV107 class: the lowering reads the real matrix on the leaf, so
execution is numerically correct either way — what is wrong is the
plan's description of itself.

The dynamic half (:func:`verify_cse_executions`, the MV113
patched-entry idiom) is the acceptance proof of the whole CSE plane:
for each recent hoist-substituted batch root the session remembered
(``MqoState.recent``), compile and execute BOTH the original
(unshared) tree and the substituted tree fresh, and require the
answers bit-equal — CSE-substituted ≡ unshared execution over real
traffic, error severity on any divergence.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr

#: Relative floor for the dynamic half — MV113's: both executions run
#: the SAME compile pipeline, so the comparison is exact by default;
#: the floor only applies under a non-default precision SLA whose
#: reduction order may legally differ between the two programs.
_REL_FLOOR = 2.0 ** -20

_FIX = ("re-run the batch through run_many so the hoist re-stamps "
        "against the freshly computed shared interior")


def check_cse_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    seen: set = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind == "leaf" and isinstance(n.attrs.get("cse"), dict):
            yield from _check_leaf(n, mesh)

    yield from walk(root)


def _check_leaf(n, mesh) -> Iterator[Diagnostic]:
    from matrel_tpu.parallel import planner
    rec = n.attrs["cse"]
    m = n.attrs.get("matrix")
    actual_dtype = str(np.dtype(getattr(m, "dtype", "float32")))
    actual_layout = planner._layout_of(n, mesh)
    stamped_layout = rec.get("layout")
    stamped_dtype = rec.get("dtype")
    if stamped_layout is not None and stamped_layout != actual_layout:
        yield Diagnostic(
            code="MV116", severity="warning", node=node_addr(n),
            message=(
                f"cse stamp claims layout {stamped_layout!r} but the "
                f"hoisted result lies {actual_layout!r} — the planner "
                f"credited a shared-interior reuse the hoist no "
                f"longer backs"),
            fix_hint=_FIX)
    if stamped_dtype is not None and stamped_dtype != actual_dtype:
        yield Diagnostic(
            code="MV116", severity="warning", node=node_addr(n),
            message=(
                f"cse stamp claims dtype {stamped_dtype!r} but the "
                f"hoisted result carries {actual_dtype!r} — autotune "
                f"consults and HBM gates keyed on the wrong itemsize"),
            fix_hint=_FIX)
    uses = rec.get("uses")
    if uses is not None and uses < 2:
        yield Diagnostic(
            code="MV116", severity="warning", node=node_addr(n),
            message=(
                f"cse stamp records uses={uses!r} — an interior used "
                f"once is not shared; the hoist added a dispatch "
                f"without removing one"),
            fix_hint=_FIX)


def verify_cse_executions(session, limit: Optional[int] = None
                          ) -> List[Diagnostic]:
    """The dynamic half: prove the recent CSE-substituted roots equal
    their unshared executions. Each remembered pair (original tree,
    substituted tree) compiles and runs fresh — the substituted tree's
    hoisted-leaf results enter as data, the original recomputes the
    interior from sources — and must agree bit-for-bit under the
    default SLA. Returns the (possibly empty) MV116 diagnostic list;
    empty means every surviving remembered substitution is proven.
    Runs real compiles/executes; the bench/soak/test harness surface,
    never the hot path."""
    from matrel_tpu import executor as executor_lib
    out: List[Diagnostic] = []
    st = getattr(session, "_mqo", None)
    pairs = list(st.recent) if st is not None else []
    if limit is not None:
        pairs = pairs[-limit:]
    exact = session.config.precision_sla == "default"
    for orig, subst in pairs:
        try:
            unshared = executor_lib.compile_expr(
                orig, session.mesh, session.config).run().to_numpy()
            shared = executor_lib.compile_expr(
                subst, session.mesh, session.config).run().to_numpy()
        except Exception as ex:
            out.append(Diagnostic(
                code="MV116", severity="error",
                node=node_addr(orig),
                message=(f"fresh execution of a remembered CSE pair "
                         f"failed: {ex!r}"),
                fix_hint=_FIX))
            continue
        scale = max(float(np.abs(unshared).max()), 1.0)
        err = float(np.abs(shared.astype(np.float64)
                           - unshared.astype(np.float64)).max()) / scale
        bad = (err != 0.0) if exact else (err > _REL_FLOOR)
        if bad:
            out.append(Diagnostic(
                code="MV116", severity="error",
                node=node_addr(orig),
                message=(f"CSE-substituted execution diverges from "
                         f"unshared execution: rel err {err:.3e} "
                         f"(sla={session.config.precision_sla!r}) — "
                         f"the hoist is not transparent"),
                fix_hint=_FIX))
    return out
