"""MV115 — answer-provenance stamps must cohere with the seams.

The answer provenance ledger (obs/provenance.py) threads each consumed
cache entry's lineage stamp onto its substitution leaf
(``attrs["provenance"]``) next to the MV107 ``result_cache`` stamp, and
each ledger record names the serve path its answer took. Both are
DESCRIPTIONS of the same mechanisms the engine already stamps —
delta-patched entries carry a ``delta`` stamp, replicated entries a
``fleet`` stamp, degraded compiles a ``degrade`` meta — so a lineage
claim the mechanism stamps don't back (or a mechanism stamp the
lineage doesn't admit) means the account of the answer is wrong in one
direction or the other. The classic shapes: a hand-built or replayed
plan carrying a stale provenance stamp past an invalidation, and a
record-path vocabulary drift between writer and reader versions.

Two halves, the MV113 pattern:

- STATIC (:func:`check_provenance_stamps`, the registered pass): walk
  the annotated tree; on every substitution leaf cross-check the
  ``provenance`` stamp against the ``result_cache`` stamp BOTH ways
  (key-hash agreement; ``ivm_patched`` ⇔ ``delta``; ``fleet_replica``
  backed by ``fleet``), and warn on unknown path vocabulary or schema.
- DYNAMIC (:func:`verify_ledger`): audit a live session's ledger
  records for internal coherence — path ⇔ section agreement inside
  each summary (``degraded`` ⇔ ``degrade``, ``stale`` ⇔ grant,
  fleet paths ⇔ ``fleet`` hop). The numeric re-proof of the answers
  themselves is :func:`obs.provenance.audit`'s job.

Warning severity throughout (the MV102/MV106/MV107 class): execution
reads the real matrices either way — what is wrong is the plan's (or
the ledger's) description of itself.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr
from matrel_tpu.obs import provenance as provenance_lib

_FIX = ("re-run the query through the session so substitution "
        "re-stamps lineage against the live cache entry")

#: Paths whose leaf stamp a ``fleet`` mechanism stamp may back — a
#: replica entry later delta-patched restamps ``ivm_patched`` while
#: keeping its fleet ancestry.
_FLEET_OK = ("fleet_replica", "ivm_patched")


def check_provenance_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    seen: set = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind == "leaf" and n.attrs.get("provenance") is not None:
            yield from _check_leaf(n)

    yield from walk(root)


def _check_leaf(n) -> Iterator[Diagnostic]:
    pv = n.attrs["provenance"]
    if not isinstance(pv, dict):
        yield Diagnostic(
            code="MV115", severity="warning", node=node_addr(n),
            message=(f"provenance stamp is {type(pv).__name__!r}, "
                     f"not a lineage record — only the ledger's "
                     f"stamp writers may produce it (ML015)"),
            fix_hint=_FIX)
        return
    schema = pv.get("schema")
    if schema != provenance_lib.SCHEMA_VERSION:
        yield Diagnostic(
            code="MV115", severity="warning", node=node_addr(n),
            message=(f"provenance stamp schema {schema!r} != "
                     f"{provenance_lib.SCHEMA_VERSION} — written by a "
                     f"different ledger version; lineage readers may "
                     f"misrender it"),
            fix_hint=_FIX)
    path = pv.get("path")
    if path not in provenance_lib.PATHS:
        # unknown provenance KIND: warn, never error — a newer writer
        # must not brick an older verifier (the schema discipline)
        yield Diagnostic(
            code="MV115", severity="warning", node=node_addr(n),
            message=(f"provenance stamp claims unknown serve path "
                     f"{path!r} (known: "
                     f"{', '.join(provenance_lib.PATHS)})"),
            fix_hint=_FIX)
    rc = n.attrs.get("result_cache")
    if not isinstance(rc, dict):
        yield Diagnostic(
            code="MV115", severity="warning", node=node_addr(n),
            message=("provenance stamp without a result_cache stamp — "
                     "lineage claims a cache ancestry the plan itself "
                     "does not record (stale stamp past an "
                     "invalidation?)"),
            fix_hint=_FIX)
        return
    pk, rk = pv.get("key_hash"), rc.get("key_hash")
    if pk is not None and rk is not None and pk != rk:
        yield Diagnostic(
            code="MV115", severity="warning", node=node_addr(n),
            message=(f"provenance stamp names entry {pk!r} but the "
                     f"result_cache stamp names {rk!r} — the lineage "
                     f"and the substitution disagree about which "
                     f"entry answered"),
            fix_hint=_FIX)
    has_delta = isinstance(rc.get("delta"), dict)
    if path == "ivm_patched" and not has_delta:
        yield Diagnostic(
            code="MV115", severity="warning", node=node_addr(n),
            message=("provenance claims an IVM-patched ancestry but "
                     "the entry carries no delta stamp — the lineage "
                     "promises a patch chain the cache never applied"),
            fix_hint=_FIX)
    if has_delta and path != "ivm_patched":
        yield Diagnostic(
            code="MV115", severity="warning", node=node_addr(n),
            message=(f"entry carries delta stamp (gen "
                     f"{rc['delta'].get('gen')}) but provenance "
                     f"claims path {path!r} — a patched value served "
                     f"under a fresh-execution lineage hides its "
                     f"composed err_bound from the audit"),
            fix_hint=_FIX)
    if path == "fleet_replica" and not isinstance(rc.get("fleet"),
                                                  dict):
        yield Diagnostic(
            code="MV115", severity="warning", node=node_addr(n),
            message=("provenance claims a fleet-replica ancestry but "
                     "the entry carries no fleet stamp — no owning "
                     "slice to audit the hop against"),
            fix_hint=_FIX)
    if isinstance(rc.get("fleet"), dict) and path not in _FLEET_OK:
        yield Diagnostic(
            code="MV115", severity="warning", node=node_addr(n),
            message=(f"entry was replicated from slice "
                     f"{rc['fleet'].get('owner')!r} but provenance "
                     f"claims path {path!r} — the lineage omits the "
                     f"inter-slice hop"),
            fix_hint=_FIX)


# -- dynamic half: ledger-record coherence ------------------------------

def verify_ledger(session, limit: Optional[int] = None
                  ) -> List[Diagnostic]:
    """Check a live session's ledger records for internal coherence —
    each summary's path must admit exactly the sections it carries.
    Empty list when the ledger is off (nothing to check is not a
    finding). ``limit`` bounds the check to the newest N records."""
    led = getattr(session, "_prov", None)
    if led is None:
        return []
    out: List[Diagnostic] = []
    recs = led.records()
    if limit:
        recs = recs[-limit:]
    for rec in recs:
        out.extend(_check_record(rec))
    out.sort(key=lambda d: (d.severity != "error", d.code))
    return out


def _check_record(rec) -> Iterator[Diagnostic]:
    s = rec.summary
    addr = f"ledger:{rec.query_id}"
    if rec.path not in provenance_lib.PATHS:
        yield Diagnostic(
            code="MV115", severity="warning", node=addr,
            message=(f"ledger record claims unknown serve path "
                     f"{rec.path!r}"),
            fix_hint="bump the reader or fix the capture site")
    if s.get("schema") != provenance_lib.SCHEMA_VERSION:
        yield Diagnostic(
            code="MV115", severity="warning", node=addr,
            message=(f"ledger record schema {s.get('schema')!r} != "
                     f"{provenance_lib.SCHEMA_VERSION}"),
            fix_hint="bump the reader or fix the capture site")
    ivm = (s.get("cache") or {}).get("ivm")
    if rec.path == "ivm_patched" and not ivm:
        yield Diagnostic(
            code="MV115", severity="warning", node=addr,
            message=("record claims ivm_patched but carries no patch "
                     "chain — nothing for the audit to compose the "
                     "err_bound from"),
            fix_hint="capture via the delta plane's apply_patch seam")
    if ivm and rec.path != "ivm_patched":
        yield Diagnostic(
            code="MV115", severity="warning", node=addr,
            message=(f"record carries a patch chain but claims path "
                     f"{rec.path!r}"),
            fix_hint="capture via the delta plane's apply_patch seam")
    if rec.path in ("fleet_directory", "fleet_replica") \
            and not s.get("fleet"):
        yield Diagnostic(
            code="MV115", severity="warning", node=addr,
            message=(f"record claims {rec.path} but carries no fleet "
                     f"hop (owner -> serving slice)"),
            fix_hint="capture via the directory-answer seam")
    if rec.path == "degraded" and not s.get("degrade"):
        yield Diagnostic(
            code="MV115", severity="warning", node=addr,
            message=("record claims a degraded serve but carries no "
                     "rung stamp"),
            fix_hint="capture with the attempt's rung")
    if s.get("degrade") and not rec.rung:
        yield Diagnostic(
            code="MV115", severity="warning", node=addr,
            message=("record carries a degrade stamp but rung 0 — "
                     "the lineage claims a ladder step that never "
                     "escalated"),
            fix_hint="capture with the attempt's rung")
    if rec.path == "stale" and not s.get("stale"):
        yield Diagnostic(
            code="MV115", severity="warning", node=addr,
            message=("record claims a stale serve but carries no "
                     "staleness grant"),
            fix_hint="capture via the pipeline's stale-probe seam")
