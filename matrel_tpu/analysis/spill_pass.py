"""MV117 — spill-thaw provenance stamps must cohere with the tiers.

A result-cache leaf whose entry was served from a LOWER tier of the
spill hierarchy (docs/DURABILITY.md) carries the promotion's
provenance inside its ``result_cache`` stamp (``stamp["spill"]``: the
serving tier, the staged transfer legs, the coefficient-priced bill,
and whether the device transient fit the peak-HBM budget). The plan
was admitted on exactly that story — so a stamp whose legs are not
the legs :func:`reshard.spill_plan` stages from the claimed tier, or
whose ``fits`` verdict disagrees with the entry's own byte count
against the live budget, describes a promotion that never happened
that way (a hand-built plan, a replay across a config change, or a
spill-manager regression).

Warning severity, the MV107 class: the matrix on the leaf is the real
thawed value, so execution is numerically correct either way — what
is wrong is the plan's description of how the value got there (and
therefore every obs record and cost consult built on it).
"""

from __future__ import annotations

from typing import Iterator

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr

_FIX = ("re-run the query through the session so the thaw re-stamps "
        "against the live spill hierarchy and budget")


def check_spill_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    seen: set = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        rc = n.attrs.get("result_cache")
        if (n.kind == "leaf" and isinstance(rc, dict)
                and isinstance(rc.get("spill"), dict)):
            yield from _check_leaf(n, rc["spill"], config)

    yield from walk(root)


def _check_leaf(n, sp, config) -> Iterator[Diagnostic]:
    from matrel_tpu.parallel import coeffs, reshard
    tier = sp.get("tier")
    if tier not in ("host", "disk", "restored"):
        yield Diagnostic(
            code="MV117", severity="warning", node=node_addr(n),
            message=(
                f"spill stamp claims serving tier {tier!r} but only "
                f"host/disk/restored entries thaw — an HBM hit never "
                f"stamps spill provenance"),
            fix_hint=_FIX)
        return
    legs = sp.get("legs") or ()
    unknown = [l for l in legs if l not in coeffs.SPILL_LEGS]
    if unknown:
        yield Diagnostic(
            code="MV117", severity="warning", node=node_addr(n),
            message=(
                f"spill stamp carries leg(s) {unknown!r} outside the "
                f"reshard transfer vocabulary {coeffs.SPILL_LEGS!r} — "
                f"no coefficient row can ever price them"),
            fix_hint=_FIX)
        return
    # the legs a promotion from the claimed tier actually stages
    # (restored entries ARE disk-tier entries under a name key)
    m = n.attrs.get("matrix")
    nbytes = int(getattr(getattr(m, "data", None), "nbytes", 0) or 0)
    plan = reshard.spill_plan(
        "disk" if tier == "restored" else tier, "hbm", nbytes)
    expect = [reshard.spill_leg(s) for s in plan.steps]
    if list(legs) != expect:
        yield Diagnostic(
            code="MV117", severity="warning", node=node_addr(n),
            message=(
                f"spill stamp claims legs {list(legs)!r} but a "
                f"promotion from tier {tier!r} stages {expect!r} — "
                f"the plan was priced on transfers that did not run"),
            fix_hint=_FIX)
    if "fits" in sp and nbytes:
        actual = plan.fits(float(config.reshard_peak_budget_bytes))
        if bool(sp["fits"]) != actual:
            yield Diagnostic(
                code="MV117", severity="warning", node=node_addr(n),
                message=(
                    f"spill stamp claims fits={sp['fits']!r} but the "
                    f"entry's {nbytes} device-transient bytes "
                    f"{'respect' if actual else 'exceed'} the live "
                    f"reshard_peak_budget_bytes — the budget story "
                    f"the admission told is stale"),
                fix_hint=_FIX)
    cost = sp.get("cost")
    if cost not in ("measured", "analytic"):
        yield Diagnostic(
            code="MV117", severity="warning", node=node_addr(n),
            message=(
                f"spill stamp provenance {cost!r} is neither "
                f"'measured' nor 'analytic' — the coefficient-loop "
                f"audit cannot classify this promotion"),
            fix_hint=_FIX)
