"""Sharding-flow check (MV102): every layout the cost model CLAIMS for
a matmul's output must be one its lowering actually PINS.

``planner.infer_layout`` hands out co-partitioning credits ("this bmm
output is row-sharded, the consumer reads it free") that change
strategy rankings and join schemes. The executor only honours those
claims where the lowering hard-codes an out_spec — the exact bug class
ADVICE r5 found by hand: sparse_leaf matmuls run the SpMM path and
wide/refused COO matmuls run hard-coded xla, both IGNORING the stamped
strategy, so consulting STRATEGY_OUT_LAYOUT there claimed a "row"/"col"
the executor never produces (an unearned free-consume credit). This
pass re-derives the pinned layout from the executor's own dispatch
predicates and out_spec contracts and diffs it against the claim, so
that fix can never silently regress and no new dispatch can earn a
credit without pinning it.

Severity is "warning": a false claim mis-COSTS the plan (a worse
strategy may win, an extra reshard is unpriced) but the computed
numbers stay correct — GSPMD inserts the resharding the model forgot.
"""

from __future__ import annotations

from typing import Iterator

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr
from matrel_tpu.config import pallas_enabled
from matrel_tpu.parallel import planner


def pinned_matmul_layout(node, mesh, config) -> str:
    """Output layout the EXECUTOR's matmul lowering actually pins for
    this node, mirrored from Lowerer._matmul's dispatch order via the
    executor's single-source-of-truth predicates. "2d" doubles as
    "no claim" — the conservative answer for paths whose output
    sharding GSPMD decides."""
    from matrel_tpu import executor as exec_lib
    # branch order mirrors Lowerer._matmul: spgemm, then coo_leaf on
    # EITHER side, then sparse_leaf (review r6 — a mixed coo×sparse
    # matmul runs the COO path, and its compact lowering pins "rep")
    if exec_lib._spgemm_dispatch(node, config):
        return "2d"         # apply_dense scatters to the canonical layout
    if any(c.kind == "coo_leaf" for c in node.children):
        if exec_lib._coo_dispatch_plan(node) is None:
            return "2d"     # densify path: hard-coded xla
        # compact Pallas path pins out_specs=P() (replicated); the
        # expanded XLA path leaves sharding to GSPMD. With autotune on,
        # a measured "expanded" winner can reroute at compile time, so
        # "rep" may only be claimed when the compact path is guaranteed.
        if mesh.size == 1 or (pallas_enabled(config)
                              and not config.autotune):
            return "rep"
        return "2d"
    if any(c.kind == "sparse_leaf" for c in node.children):
        return "2d"         # SpMM path ignores the stamp
    return planner.STRATEGY_OUT_LAYOUT.get(node.attrs.get("strategy"),
                                           "2d")


def check_layout_claims(root, mesh, config) -> Iterator[Diagnostic]:
    """MV102 on every matmul node: planner.infer_layout's claim must
    equal the pinned layout. Non-matmul nodes propagate claims
    structurally (transpose swaps, elemwise agrees, …) — the matmul
    rule is where claims are MINTED, so that is what gets verified."""
    seen = set()
    lmemo: dict = {}

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind != "matmul":
            return
        claimed = planner.infer_layout(n, mesh, lmemo, config)
        pinned = pinned_matmul_layout(n, mesh, config)
        if claimed != pinned:
            yield Diagnostic(
                code="MV102", severity="warning", node=node_addr(n),
                message=f"cost model claims output layout {claimed!r} "
                        f"but the lowering pins {pinned!r} — a "
                        "co-partitioning credit the executor never "
                        "earns (or a free consume it never reports)",
                fix_hint="teach planner.infer_layout's matmul rule the "
                         "dispatch this node takes, or re-plan under "
                         "the executing config")

    yield from walk(root)
