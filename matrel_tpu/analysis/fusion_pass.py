"""Fusion-stamp consistency pass: MV111.

The fusion pass (ir/fusion.py) stamps each fusable region on its root
node; the executor lowers EXACTLY the stamped member set under one
dispatch frame, with the chain above the anchor pushed into the
producing kernel's epilogue slot. A stamp that disagrees with the
grammar's own derivation under the verifying config is the MV104/MV110
class of plan bug: the obs decision records (``fused_region``, member
census, est saved dispatches/HBM) describe a program that never
executes, a member outside the fusable vocabulary would lower through
a path the region evaluator cannot instrument, and a stamp present
with ``config.fusion_enable`` OFF means the bit-identity contract is
already broken — the default path must stamp (and construct) nothing.

Checked per stamp, both directions (the MV104 re-check discipline):

* fusion off ⇒ NO stamp anywhere (error).
* every stamped member uid resolves to a reachable region node, is a
  fusable kind or the single anchor matmul, and the anchor uid names a
  matmul member (errors).
* the grammar's re-derivation at this root yields EXACTLY the stamped
  member set — a wider or narrower boundary means the plan was
  annotated under a different config/operand statistics (error).
* the stamped census/signature, precision tier (``fused_tier`` must
  equal the anchor's CURRENT ``precision_tier`` — fused regions
  preserve the stamped tier) and re-mask census (``fused_remask`` —
  the zero-padding invariant is restored at exactly the staged path's
  breaker set) all match re-derivation (errors).
* backward: a region the grammar WOULD form whose root carries no
  stamp. Error with autotune off; with ``config.autotune`` on only a
  warning — a measured ``fuse|…`` "staged" winner legitimately
  suppresses a stamp, and the verifier never re-measures.
"""

from __future__ import annotations

from typing import Iterator

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr
from matrel_tpu.ir import fusion as fusion_lib


def check_fusion_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    """MV111 (see module docstring)."""
    stamps = fusion_lib.collect_stamps(root)
    enabled = bool(config is not None and config.fusion_enable)
    if not enabled:
        for n in stamps:
            yield Diagnostic(
                code="MV111", severity="error", node=node_addr(n),
                message="fused_region stamp present with "
                        "config.fusion_enable OFF — the default path "
                        "must stamp nothing (bit-identity contract); "
                        "the executor would lower per-op while obs "
                        "records a fused region",
                fix_hint="re-plan under the executing config, or drop "
                         "the hand-set fused_* attrs")
        return
    derived = {r.root_uid: r
               for r in fusion_lib.segment(root, config, mesh=mesh)}
    stamped_roots = set()
    for n in stamps:
        stamped_roots.add(n.uid)
        yield from _check_one(n, derived.get(n.uid), config)
    for uid, r in derived.items():
        if uid in stamped_roots:
            continue
        sev = "warning" if config.autotune else "error"
        node = fusion_lib._find_uid(root, uid)
        yield Diagnostic(
            code="MV111", severity=sev,
            node=node_addr(node) if node is not None else f"#{uid}",
            message=f"the fusion grammar derives a region "
                    f"({r.sig}) here but no stamp is present — the "
                    "executor will lower it per-op while the planner's "
                    "boundary says it should fuse"
                    + (" (a measured fuse| 'staged' winner may have "
                       "suppressed it)" if config.autotune else ""),
            fix_hint="re-plan under the executing config "
                     "(annotate_fusion runs inside compile when "
                     "fusion_enable is on)")


def _check_one(n, r, config) -> Iterator[Diagnostic]:
    members = fusion_lib.region_nodes(n)
    stamped = set(n.attrs.get("fused_members") or ())
    missing = stamped - (set(members) - {n.uid})
    if missing:
        yield Diagnostic(
            code="MV111", severity="error", node=node_addr(n),
            message=f"stamped member uid(s) {sorted(missing)} do not "
                    "resolve to reachable region nodes — the executor "
                    "would lower a different member set than the "
                    "stamp records",
            fix_hint="re-plan; member uids are remapped by "
                     "annotate_fusion, never hand-set")
        return
    anchor_uid = n.attrs.get("fused_anchor")
    mms = [m for m in members.values() if m.kind == "matmul"]
    if len(mms) > 1:
        yield Diagnostic(
            code="MV111", severity="error", node=node_addr(n),
            message=f"{len(mms)} matmul members in one region — the "
                    "epilogue-hook contract allows at most ONE "
                    "producer anchor per region",
            fix_hint="re-plan under the executing config")
        return
    anchor = members.get(anchor_uid) if anchor_uid is not None else None
    if anchor_uid is not None and (anchor is None
                                   or anchor.kind != "matmul"):
        yield Diagnostic(
            code="MV111", severity="error", node=node_addr(n),
            message=f"fused_anchor {anchor_uid} is not a matmul "
                    "member of this region",
            fix_hint="re-plan under the executing config")
        return
    for m in members.values():
        if m.uid == anchor_uid or m.uid == n.uid:
            continue
        if m.kind not in fusion_lib.FUSABLE_KINDS:
            yield Diagnostic(
                code="MV111", severity="error", node=node_addr(m),
                message=f"member kind {m.kind!r} is outside the "
                        f"fusable vocabulary "
                        f"{fusion_lib.FUSABLE_KINDS} — the region "
                        "evaluator has no single-frame lowering for "
                        "it",
                fix_hint="re-plan under the executing config")
            return
    if r is None:
        yield Diagnostic(
            code="MV111", severity="error", node=node_addr(n),
            message="fused_region stamped but the grammar derives NO "
                    "region at this root under the verifying config — "
                    "the boundary was drawn under different operand "
                    "statistics or a different fusion grammar",
            fix_hint="re-plan under the executing config")
        return
    if set(r.member_uids) != stamped:
        yield Diagnostic(
            code="MV111", severity="error", node=node_addr(n),
            message=f"stamped member set {sorted(stamped)} != the "
                    f"grammar's derivation {sorted(r.member_uids)} — "
                    "the stamp does not cover exactly the region the "
                    "executor lowers",
            fix_hint="re-plan under the executing config")
        return
    census = n.attrs.get("fused_census") or {}
    if census != r.census or n.attrs.get("fused_region") != r.sig:
        yield Diagnostic(
            code="MV111", severity="error", node=node_addr(n),
            message=f"stamped census/signature "
                    f"({n.attrs.get('fused_region')!r}, {census}) "
                    f"disagree with re-derivation ({r.sig!r}, "
                    f"{r.census}) — obs records (and fuse| autotune "
                    "keys) would describe a different region",
            fix_hint="re-plan under the executing config")
        return
    if int(n.attrs.get("fused_remask") or 0) != r.n_remask:
        yield Diagnostic(
            code="MV111", severity="error", node=node_addr(n),
            message=f"stamped re-mask census "
                    f"{n.attrs.get('fused_remask')} != derived "
                    f"{r.n_remask} — the fused lowering would restore "
                    "the zero-padding invariant at a different "
                    "breaker set than the staged path",
            fix_hint="re-plan under the executing config")
        return
    if anchor is not None:
        tier = anchor.attrs.get("precision_tier")
        if n.attrs.get("fused_tier") != tier:
            yield Diagnostic(
                code="MV111", severity="error", node=node_addr(n),
                message=f"stamped fused_tier "
                        f"{n.attrs.get('fused_tier')!r} != the "
                        f"anchor's precision_tier {tier!r} — fusing "
                        "must preserve the stamped tier's numerics",
                fix_hint="re-plan so the fusion stamp sees the "
                         "anchor's current tier")
