"""Axis-traffic pass: MV106 (slow-axis collective smell).

On a topology-weighted mesh (core/mesh.MeshTopology — non-uniform
per-axis inverse-bandwidth weights, the hierarchical ICI/DCN fabric),
a plan whose dominant collective rides the EXPENSIVE axis while an
admissible alternative moves far fewer weighted bytes is almost always
a stale or hand-stamped plan: the planner itself minimises the weighted
bill (choose_strategy_ex), so a fresh annotation cannot produce the
smell outside the tiebreak band. The classic instance is a
reduce-scatter over the cross-slice DCN axis when a broadcast that
stays on ICI is available — exactly the plan bug a flat byte model
ships silently, caught here statically before anything traces
(the arXiv:2112.01075 discipline, extended to the fabric dimension).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr
from matrel_tpu.analysis.strategy_pass import _dispatch_kind
from matrel_tpu.core import mesh as mesh_lib, padding
from matrel_tpu.parallel import planner

#: An alternative must move at least this factor fewer weighted bytes
#: before MV106 fires — the planner's own tiebreak band (10%), consumer
#: hints and root-context differences can legitimately leave a stamped
#: pick somewhat off the verifier's argmin; a 2× gap cannot be any of
#: those.
MV106_MARGIN = 2.0

#: Strategies MV106 compares — the real shard_map recipes. xla is
#: excluded (GSPMD picks its own decomposition; the model's rmm proxy
#: is a pricing stand-in, not a recipe to second-guess), spgemm is a
#: dispatch, not a choice.
_CANDIDATES = ("bmm_right", "bmm_left", "cpmm", "rmm", "summa")


def _root_exposures(root) -> dict:
    """uid -> (scale, transposed) of each matmul's exposure to the
    plan-ROOT canonical-output reshard, mirroring the planner's own
    threading (annotate_strategies walks _child_root_scale the same
    way) so MV106 prices alternatives in the context the planner did.
    Shared DAG nodes keep their maximum exposure (conservative: the
    bigger root charge makes alternatives look worse, never better)."""
    out: dict = {}

    def walk(n, scale: float, swap: bool):
        if n.kind == "matmul":
            prev = out.get(n.uid, (0.0, False))
            if scale >= prev[0]:
                out[n.uid] = (scale, swap)
        nxt_swap = swap != (n.kind == "transpose")
        for i, c in enumerate(n.children):
            walk(c, planner._child_root_scale(n, i, scale), nxt_swap)

    walk(root, 1.0, False)
    return out


def check_axis_traffic(root, mesh, config) -> Iterator[Diagnostic]:
    """MV106: on a non-uniform mesh, warn when a stamped strategy's
    dominant collective rides the expensive axis while an admissible
    alternative moves ≥ MV106_MARGIN× fewer weighted bytes (both priced
    with the same α steps and root-reshard context the planner uses).
    Uniform meshes have no slow axis — the pass is free there."""
    topo = mesh_lib.mesh_topology(mesh, config)
    if topo.uniform:
        return
    wts = topo.axis_weights
    wx, wy = wts
    slow = 0 if wx > wy else 1
    slow_name = mesh.axis_names[slow]
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    exposures = _root_exposures(root)
    lmemo: dict = {}
    dmemo: dict = {}
    seen = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind != "matmul" or "strategy" not in n.attrs:
            return
        strat = n.attrs["strategy"]
        if strat not in _CANDIDATES:
            return               # xla/spgemm/unknown: MV101's domain
        if n.attrs.get("strategy_source") == "measured":
            # an autotune wall-clock winner legitimately disagrees with
            # the byte model — that is the POINT of measuring (the
            # probes time the real fabric, weights and all); flagging
            # it would warn on every fresh annotation of an
            # autotune-enabled weighted session
            return
        if n.attrs.get("cost_model") == "measured":
            # same exemption, coefficient-ranked decisions (round 19,
            # parallel/coeffs.py; docs/COST_MODEL.md): a drift-
            # calibrated ms ranking legitimately disagrees with the
            # raw byte model — measured reality overriding the closed
            # forms is the closed loop WORKING, not a smell this pass
            # (which re-prices by exactly those closed forms) can judge
            return
        if _dispatch_kind(n, config) is not None:
            return               # fast-path dispatch: no collectives run
        a, b = n.children
        nn, kk = a.shape
        mm = b.shape[1]
        la = planner.infer_layout(a, mesh, lmemo, config)
        lb = planner.infer_layout(b, mesh, lmemo, config)
        da, db = a.density, b.density
        ax = planner.comm_cost_axes(strat, nn, kk, mm, da, db, gx, gy,
                                    a_layout=la, b_layout=lb,
                                    weights=wts)
        if ax[slow] <= 0.0 or ax[slow] <= ax[1 - slow]:
            return               # dominant traffic already off the slow axis
        scale, swap = exposures.get(n.uid, (0.0, False))
        al = config.comm_alpha_bytes

        def priced(s: str) -> float:
            return (planner.comm_cost(s, nn, kk, mm, da, db, gx, gy,
                                      a_layout=la, b_layout=lb,
                                      alpha_bytes=al, weights=wts)
                    + planner._root_reshard_cost(s, nn, mm, gx, gy, swap,
                                                 weights=wts) * scale)

        stamped_cost = priced(strat)
        pn, pk = padding.padded_shape((nn, kk), mesh)
        _, pm = padding.padded_shape((kk, mm), mesh)
        dt = planner.infer_dtype(n, config, dmemo)
        isz = np.dtype(dt).itemsize if dt is not None else 4
        a_bytes = planner._bytes((nn, kk), da)
        b_bytes = planner._bytes((kk, mm), db)
        thr = config.broadcast_threshold_bytes
        best_alt, best_cost = None, None
        for s in _CANDIDATES:
            if s == strat:
                continue
            if s == "bmm_right" and b_bytes > thr:
                continue
            if s == "bmm_left" and a_bytes > thr:
                continue
            if s == "summa" and (gx != gy or gx <= 1):
                continue
            if not planner.admissible(s, pn, pk, pm, gx, gy,
                                      itemsize=isz,
                                      hbm_budget_bytes=
                                      config.hbm_budget_bytes):
                continue
            c = priced(s)
            if best_cost is None or c < best_cost:
                best_alt, best_cost = s, c
        if (best_cost is not None
                and best_cost * MV106_MARGIN <= stamped_cost):
            yield Diagnostic(
                code="MV106", severity="warning", node=node_addr(n),
                message=f"stamped {strat!r} moves most of its bytes "
                        f"over the expensive {slow_name!r} axis "
                        f"(weight {wts[slow]:g}; ~{ax[slow]:.3g} B vs "
                        f"{ax[1 - slow]:.3g} B) while admissible "
                        f"{best_alt!r} costs {best_cost:.3g} weighted "
                        f"vs {stamped_cost:.3g} — the slow-axis "
                        "collective smell",
                fix_hint="re-plan on this mesh (annotate_strategies "
                         "prices axis weights) or calibrate "
                         "config.axis_cost_weights if the fabric "
                         "really is flat")

    yield from walk(root)
