"""Typed diagnostics for the static plan verifier.

One vocabulary shared by every pass (analysis/__init__.py registry):
a pass walks an ANNOTATED plan (post ``planner.annotate_strategies``)
and yields :class:`Diagnostic` records — it never mutates the tree and
never raises on a bad plan. Escalation is the caller's policy
(``config.verify_plans``): the executor raises
:class:`VerificationError` at "error", logs at "warn";
``session.verify``/``explain`` just hand the records back.

Code space (stable — tests and suppressions key on them):

  MV101  stamped strategy inadmissible / unknown       (error)
  MV102  layout claim not pinned by the lowering       (warning)
  MV103  zero-padding invariant broken without re-mask (error)
  MV104  SpGEMM stamp inconsistent with the dispatch   (error)
  MV105  per-device HBM working set over budget        (error)
  MV106  dominant collective rides the slow mesh axis  (warning)
  MV107  result-cache stamp disagrees with the cache   (warning)
  MV108  precision tier violates the query's accuracy
         SLA, or int tier on unprovable operands       (error)
  MV109  staged reshard peak over reshard_peak_budget_
         bytes, or a stamped reshard record that
         understates its recompiled peak               (error)
  MV110  SpGEMM kernel stamp unknown / inadmissible for
         the stamped structure class                   (error)
  MV112  brownout stamp disagrees with the rung that
         claims it (tier/staleness/controller-off)     (warning)
"""

from __future__ import annotations

import dataclasses
from typing import List

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One verifier finding, addressed to a plan node.

    code: stable "MVxxx" identifier (module docstring catalogue).
    severity: "error" (the lowering would run something the plan
      misdescribes, or could not run at all) or "warning" (the plan
      executes correctly but was COSTED on a false premise).
    node: human-readable node address — ``kind#uid shape`` — enough to
      find the node in ``pretty()`` output; plans are DAGs, so a uid is
      the only stable name.
    message: what invariant failed, with the observed values.
    fix_hint: the action that clears it (the reference's analyzer
      errors carry the same "did you mean" affordance).
    """

    code: str
    severity: str
    node: str
    message: str
    fix_hint: str = ""

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, "
                             f"got {self.severity!r}")

    def render(self) -> str:
        line = f"{self.code} [{self.severity}] {self.node}: {self.message}"
        if self.fix_hint:
            line += f" (fix: {self.fix_hint})"
        return line

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def node_addr(node) -> str:
    """The ``kind#uid shape`` address diagnostics carry."""
    return f"{node.kind}#{node.uid} {node.shape}"


class VerificationError(RuntimeError):
    """Raised by the compile path at ``verify_plans="error"`` when any
    error-severity diagnostic fires — BEFORE tracing, so nothing
    reaches the chip. Carries the full diagnostic list (not just the
    errors) so the failure message shows the whole picture."""

    def __init__(self, diagnostics: List[Diagnostic]):
        self.diagnostics = list(diagnostics)
        errors = [d for d in self.diagnostics if d.severity == "error"]
        lines = "\n  ".join(d.render() for d in self.diagnostics)
        super().__init__(
            f"plan verification failed with {len(errors)} error(s) "
            f"({len(self.diagnostics)} diagnostic(s) total):\n  {lines}")
