"""MV112 — brownout stamps must agree with the rung that claims them.

When the adaptive brownout controller (resilience/brownout.py;
docs/OVERLOAD.md) downshifts a default-SLA query, the serve worker
STAMPS the expr root (``attrs["brownout"] = {rung, sla[,
staleness_ms]}``) before compiling it under the downshifted precision
config — the stamp is the plan's own record of WHY it runs at reduced
fidelity, and it rides the plan key so a browned-out plan never shares
a cache slot with a full-fidelity one. This pass proves the stamp and
the plan still agree:

- the stamped rung must be a real brownout rung (1..3);
- a tier-downshift claim (``sla``) must match the precision SLA the
  plan actually compiles under — a stamp claiming "fast" on a plan
  compiled at "default" means the caller got full-price latency
  labelled as browned-out (or, worse, the reverse: a silently
  downgraded result with no rung to justify it);
- a ``staleness_ms`` claim requires rung >= 2 (STALE_RUNG) — stale
  serving below the rung that authorizes it is a contract violation;
- any stamp at all under a config with brownout OFF is a replayed /
  hand-built plan claiming a controller that does not exist.

Warning severity, the MV102/MV106/MV107 class: the lowering runs the
stamped tier correctly either way — what is wrong is the plan's
description of WHY. Fresh annotations are provably quiet: default
queries carry no stamp, and the worker stamps exactly the rung/sla it
compiles under.

Coverage note: the stamp rides expr attrs, and a rewrite rule that
RECONSTRUCTS the root node (e.g. a bare matmul root the chain pass
rebuilds) drops them with it — the pass verifies surviving stamps, it
cannot resurrect dropped ones. That is the conservative direction
(a dropped stamp makes no claim to be wrong about), and the
plan/result-cache ISOLATION is unaffected either way: cache keys are
computed over the pre-optimize expression, where the stamp always
lives.
"""

from __future__ import annotations

from typing import Iterator

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr
from matrel_tpu.resilience.brownout import (MAX_RUNG, STALE_RUNG,
                                            TIER_RUNG)

_FIX = ("let the serve worker stamp brownout downshifts (the stamp "
        "records the rung/sla the plan compiles under) — do not "
        "hand-stamp or replay browned-out plans across configs")


def check_brownout_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    seen: set = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        stamp = n.attrs.get("brownout") if n.attrs else None
        if isinstance(stamp, dict):
            yield from _check_stamp(n, stamp, config)

    yield from walk(root)


def _check_stamp(n, stamp: dict, config) -> Iterator[Diagnostic]:
    rung = stamp.get("rung")
    if not isinstance(rung, int) or not (TIER_RUNG <= rung <= MAX_RUNG):
        yield Diagnostic(
            code="MV112", severity="warning", node=node_addr(n),
            message=(f"brownout stamp carries rung {rung!r} — not a "
                     f"brownout rung ({TIER_RUNG}..{MAX_RUNG})"),
            fix_hint=_FIX)
        return
    if not getattr(config, "brownout_enable", False):
        yield Diagnostic(
            code="MV112", severity="warning", node=node_addr(n),
            message=("brownout stamp on a plan whose config has "
                     "brownout OFF — a replayed/hand-built plan "
                     "claims a controller that does not exist"),
            fix_hint=_FIX)
    claimed = stamp.get("sla")
    actual = getattr(config, "precision_sla", "default")
    if claimed is not None and claimed != actual:
        yield Diagnostic(
            code="MV112", severity="warning", node=node_addr(n),
            message=(f"brownout rung {rung} claims a downshift to "
                     f"{claimed!r} but the plan compiles under "
                     f"precision SLA {actual!r} — the stamp and the "
                     f"tier the lowering runs disagree"),
            fix_hint=_FIX)
    stale_claim = stamp.get("stale_ok") or (
        stamp.get("staleness_ms") is not None)
    if stale_claim and rung < STALE_RUNG:
        yield Diagnostic(
            code="MV112", severity="warning", node=node_addr(n),
            message=(f"brownout stamp declares a staleness tolerance "
                     f"at rung {rung} — stale serving is authorized "
                     f"only at rung >= {STALE_RUNG}"),
            fix_hint=_FIX)
