"""Reshard peak-memory feasibility (MV109).

MV105 proves a strategy's RESIDENT working set fits the chip; this
pass proves the MOVES do. A layout change lowered one-shot can
materialise a full gather of the array as a transient — the footprint
that makes near-HBM-limit operands unmovable — and the staged reshard
planner (parallel/reshard.py; arXiv:2112.01075) exists to bound it.
MV109 checks, for every stamped dense matmul (and the plan root's
canonical re-lay), that the staged ReshardPlan the lowering will run
has a peak per-device footprint within ``reshard_peak_budget_bytes``;
a move with NO bounded decomposition is an error before anything
traces. Hand-stamped ``attrs["reshard"]`` records (the cached/foreign-
plan surface, MV105's re-check discipline) are additionally recompiled
and flagged when they understate the real peak or exceed the verifying
config's budget.

The move derivation is ``reshard.staged_matmul_moves`` — the SAME
helper the executor stages with and matmul_decisions records from, so
the verifier can never disagree with the lowering about which moves
run. Budget 0 disables the derived checks (the legacy one-shot path
has no staged plans to prove); stamped records are still validated
against their own recompilation.
"""

from __future__ import annotations

from typing import Iterator

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr
from matrel_tpu.core import mesh as mesh_lib
from matrel_tpu.parallel import reshard as reshard_lib


def _check_stamp(n, gx: int, gy: int, wts, budget: float
                 ) -> Iterator[Diagnostic]:
    """Validate a hand-stamped attrs['reshard'] record by recompiling
    the move it claims."""
    stamp = n.attrs.get("reshard")
    if not isinstance(stamp, dict):
        return
    nbytes = stamp.get("nbytes")
    if not isinstance(nbytes, (int, float)) or nbytes <= 0:
        # a missing/zero size would recompile as a 0-byte move whose
        # peak is trivially fine — the exact bypass the re-check
        # exists to prevent (review r9): flag it like bad vocabulary
        yield Diagnostic(
            code="MV109", severity="error", node=node_addr(n),
            message=f"stamped reshard record {stamp!r} carries no "
                    "positive 'nbytes' — its peak cannot be verified",
            fix_hint="stamp ReshardPlan.to_dict() output (parallel/"
                     "reshard.py), which always records the move's "
                     "full padded-array bytes")
        return
    try:
        plan = reshard_lib.compile_reshard(
            str(stamp.get("src")), str(stamp.get("dst")),
            float(nbytes), gx, gy, wts, peak_budget=budget)
    except (ValueError, TypeError):
        yield Diagnostic(
            code="MV109", severity="error", node=node_addr(n),
            message=f"stamped reshard record {stamp!r} names endpoints "
                    "outside the plan compiler's vocabulary",
            fix_hint="stamp ReshardPlan.to_dict() output (parallel/"
                     "reshard.py), or drop the stamp and let the "
                     "lowering derive its own moves")
        return
    claimed = stamp.get("peak_bytes")
    if isinstance(claimed, (int, float)) \
            and claimed + 1.0 < plan.peak_bytes:
        yield Diagnostic(
            code="MV109", severity="error", node=node_addr(n),
            message=f"stamped reshard peak {claimed / 2**20:.2f} MiB "
                    f"understates the move's real bounded-decomposition "
                    f"peak {plan.peak_bytes / 2**20:.2f} MiB "
                    f"({stamp.get('src')}->{stamp.get('dst')}, "
                    f"{gx}x{gy} grid)",
            fix_hint="re-stamp from compile_reshard under this config "
                     "— an understated peak would admit a move the "
                     "chip cannot hold")
    if budget > 0 and not plan.fits(budget):
        yield Diagnostic(
            code="MV109", severity="error", node=node_addr(n),
            message=f"stamped reshard {stamp.get('src')}->"
                    f"{stamp.get('dst')} has no decomposition under "
                    f"{budget / 2**20:.2f} MiB peak: the bounded plan "
                    f"still peaks at {plan.peak_bytes / 2**20:.2f} MiB "
                    "per device",
            fix_hint="raise reshard_peak_budget_bytes (replication "
                     "moves cannot peak below the replicated array), "
                     "or re-plan so the consumer reads the existing "
                     "layout")


def check_reshard_peaks(root, mesh, config) -> Iterator[Diagnostic]:
    """MV109 over an annotated plan: every staged reshard's peak fits
    the budget, and every hand-stamped reshard record survives
    recompilation."""
    budget = float(config.reshard_peak_budget_bytes)
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    wts = mesh_lib.axis_weights(mesh, config)
    seen = set()
    lmemo: dict = {}
    dmemo: dict = {}

    def _over_peak(n, what: str, plan) -> Diagnostic:
        return Diagnostic(
            code="MV109", severity="error", node=node_addr(n),
            message=f"{what} {plan.src}->{plan.dst} has no "
                    f"decomposition under the {budget / 2**20:.2f} "
                    f"MiB reshard peak budget (best staged plan peaks "
                    f"at {plan.peak_bytes / 2**20:.2f} MiB per "
                    f"device, steps {list(plan.step_kinds)})",
            fix_hint="raise reshard_peak_budget_bytes, or re-plan "
                     "toward a strategy that consumes the operand's "
                     "existing layout (docs/RESHARD.md)")

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind != "matmul":
            return
        yield from _check_stamp(n, gx, gy, wts, budget)
        if budget <= 0:
            return
        for i, plan in reshard_lib.staged_matmul_moves(
                n, mesh, config, lmemo, dmemo):
            if not plan.fits(budget):
                yield _over_peak(n, f"operand {i} re-lay", plan)

    yield from walk(root)
    if budget > 0:
        # the plan ROOT's canonical re-lay stages too (executor.
        # _stage_root_relay — same shared derivation), so its peak is
        # proven like any operand move
        rplan = reshard_lib.root_relay_plan(root, mesh, config, lmemo,
                                            dmemo)
        if rplan is not None and not rplan.fits(budget):
            yield _over_peak(root, "root canonical re-lay", rplan)
