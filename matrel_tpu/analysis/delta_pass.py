"""MV113 — delta-patched results must be provably maintained
(docs/IVM.md; the MV108/MV110 verify-against-fresh-execution
precedent applied to the IVM plane).

Two halves, one code:

STATIC (registered in analysis.PASSES — ``check_delta_stamps``): a
plan consuming a result-cache entry that was delta-PATCHED carries
the delta provenance on its substitution stamp
(``attrs["result_cache"]["delta"]``: generation, rule, composed error
bound). The pass proves the stamp is COHERENT — the rule is in the
delta algebra's vocabulary (ir/delta.DELTA_RULES), the generation is
a positive integer, the bound is a finite non-negative float — so a
hand-built or tampered stamp cannot smuggle an unverifiable patch
past the obs surfaces that trust it. Error severity: an incoherent
provenance stamp means nobody can say what bound the consumed value
satisfies.

DYNAMIC (``verify_patched_entries`` — the bench --stream / soak
stream / test harness surface): every live patched entry's recorded
expression is RE-EXECUTED fresh (straight through the executor,
bypassing the result cache) and the patched value is proven equal
within the entry's composed error bound — exactly equal when the
bound is zero (the integer-exact graph-count patches). This is the
MV108 discipline — the stamped tier's documented bound IS the
asserted bound — pushed onto maintained state.
"""

from __future__ import annotations

import math
from typing import Iterator, List, Optional

import numpy as np

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr

_FIX = ("re-run the query through the session so substitution "
        "re-stamps from the live entry, or re-register the delta so "
        "the plane re-patches (docs/IVM.md)")

#: Relative floor for the dynamic check: a zero composed bound means
#: EXACT (integer paths); a nonzero bound is asserted as-is but never
#: below one f32 ulp-scale unit (measurement noise on reductions).
_REL_FLOOR = 2.0 ** -20


def check_delta_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    """The static half (see module docstring) — a read of the
    annotated tree, no device work, O(nodes)."""
    from matrel_tpu.ir import delta as delta_lib
    seen: set = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        rc = n.attrs.get("result_cache")
        if n.kind == "leaf" and isinstance(rc, dict) \
                and rc.get("delta") is not None:
            yield from _check_stamp(n, rc["delta"], delta_lib)

    yield from walk(root)


def _check_stamp(n, d, delta_lib) -> Iterator[Diagnostic]:
    if not isinstance(d, dict):
        yield Diagnostic(
            code="MV113", severity="error", node=node_addr(n),
            message=(f"delta provenance stamp is {type(d).__name__}, "
                     f"not a record — the consumed value's "
                     f"maintenance history is unreadable"),
            fix_hint=_FIX)
        return
    gen = d.get("gen")
    if not isinstance(gen, int) or gen < 1:
        yield Diagnostic(
            code="MV113", severity="error", node=node_addr(n),
            message=(f"delta stamp claims generation {gen!r} — "
                     f"patched entries exist only at generation >= 1 "
                     f"(0 means fresh execution, which must carry NO "
                     f"delta stamp)"),
            fix_hint=_FIX)
    rule = d.get("rule")
    if rule not in delta_lib.DELTA_RULES:
        yield Diagnostic(
            code="MV113", severity="error", node=node_addr(n),
            message=(f"delta stamp claims rule {rule!r}, not in the "
                     f"delta algebra's vocabulary "
                     f"{delta_lib.DELTA_RULES} — no documented error "
                     f"bound exists for it"),
            fix_hint=_FIX)
    bound = d.get("err_bound")
    if not isinstance(bound, (int, float)) or bound < 0 \
            or not math.isfinite(float(bound)):
        yield Diagnostic(
            code="MV113", severity="error", node=node_addr(n),
            message=(f"delta stamp carries err_bound {bound!r} — the "
                     f"composed bound must be a finite float >= 0 "
                     f"(0 = exact, the integer paths)"),
            fix_hint=_FIX)


def verify_patched_entries(session, limit: Optional[int] = None
                           ) -> List[Diagnostic]:
    """The dynamic half: prove every live delta-patched result-cache
    entry against FRESH execution of its recorded expression, within
    its composed error bound (exactly, when the bound is 0). Returns
    the (possibly empty) MV113 diagnostic list — empty means every
    surviving patched entry is proven. Runs real compiles/executes;
    the bench/soak/test harness surface, never the hot path."""
    from matrel_tpu import executor as executor_lib
    out: List[Diagnostic] = []
    checked = 0
    for key, ent in session._result_cache.items_snapshot():
        if not ent.delta_gen:
            continue
        if limit is not None and checked >= limit:
            break
        checked += 1
        if ent.expr is None:
            out.append(Diagnostic(
                code="MV113", severity="error",
                node=f"entry:{ent.key_hash}",
                message=("patched entry lost its expression — "
                         "nothing to verify against"),
                fix_hint=_FIX))
            continue
        try:
            plan = executor_lib.compile_expr(ent.expr, session.mesh,
                                             session.config)
            fresh = plan.run().to_numpy()
        except Exception as ex:
            out.append(Diagnostic(
                code="MV113", severity="error",
                node=f"entry:{ent.key_hash}",
                message=(f"fresh execution of the patched entry's "
                         f"expression failed: {ex!r}"),
                fix_hint=_FIX))
            continue
        got = ent.result.to_numpy()
        exact = (ent.err_bound or 0.0) <= 0.0
        scale = max(float(np.abs(fresh).max()), 1.0)
        err = float(np.abs(got.astype(np.float64)
                           - fresh.astype(np.float64)).max()) / scale
        tol = 0.0 if exact else max(float(ent.err_bound), _REL_FLOOR)
        bad = (err != 0.0) if exact else (err > tol)
        if bad:
            out.append(Diagnostic(
                code="MV113", severity="error",
                node=f"entry:{ent.key_hash}",
                message=(f"patched entry (gen {ent.delta_gen}, rule "
                         f"{ent.delta_rule}) diverges from fresh "
                         f"execution: rel err {err:.3e} vs stamped "
                         f"bound {'exact' if exact else ent.err_bound}"
                         ),
                fix_hint=_FIX))
    return out
