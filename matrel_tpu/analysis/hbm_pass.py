"""Per-device HBM feasibility (MV105).

Per-chip memory is the binding constraint for distributed linear
algebra on TPUs (arXiv:2112.09017): RMM replicates A along y and B
along x, BMM replicates one operand EVERYWHERE — on shapes where the
ICI byte model still ranks them cheapest, the replicated operands may
simply not fit a 16 GB v5e chip (VERDICT r5 Weak #3). The planner's
``admissible`` now drops such plans before costing (Next #6, closed in
this layer); this pass re-checks the STAMPED plan against the verifying
config's budget, so a plan annotated under a different budget (cached,
hand-stamped, or produced by an older planner) is still caught before
execution.

The closed forms live in ``planner.strategy_hbm_bytes`` — ONE source
shared by the gate and the verifier, so the two cannot disagree about
what fits.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr
from matrel_tpu.analysis.strategy_pass import _dispatch_kind
from matrel_tpu.core import mesh as mesh_lib, padding
from matrel_tpu.parallel import planner


def check_hbm_feasibility(root, mesh, config) -> Iterator[Diagnostic]:
    """MV105 on every matmul stamped with a shard_map strategy: its
    per-device working set (operand shards × replication factor +
    accumulator, padded dims, inferred itemsize) must fit
    ``config.hbm_budget_bytes``. xla/spgemm stamps and fast-path
    dispatches are exempt — GSPMD decomposes the former itself and the
    latter's working set is the sparse pair list, not a dense
    replication factor. Budget 0 disables the pass."""
    budget = config.hbm_budget_bytes
    if budget <= 0:
        return
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    seen = set()
    dmemo: dict = {}

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind != "matmul":
            return
        strat = n.attrs.get("strategy")
        if strat in (None, "xla", "spgemm"):
            return
        if _dispatch_kind(n, config) is not None:
            return          # fast path: the stamp's specs never run
        a, b = n.children
        nn, kk = a.shape
        mm = b.shape[1]
        pn, pk = padding.padded_shape((nn, kk), mesh)
        _, pm = padding.padded_shape((kk, mm), mesh)
        dt = planner.infer_dtype(n, config, dmemo)
        isz = np.dtype(dt).itemsize if dt is not None else 4
        need = planner.strategy_hbm_bytes(strat, pn, pk, pm, gx, gy,
                                          isz)
        if need > budget:
            hint = ("re-plan on this config (admissible() now "
                    "drops this strategy; cpmm/summa keep the "
                    "working set O(N^2/P)), or raise "
                    "hbm_budget_bytes if the chip really has "
                    "more HBM")
            # when a NON-replicating alternative fits the budget, the
            # operands can still move: a peak-bounded staged reshard
            # (parallel/reshard.py) re-lays them to that strategy's
            # layout without the full-gather transient the one-shot
            # move risks — name the knob instead of leaving a hard
            # refusal (the "can't reshard it at all" wall, ROADMAP 2)
            alts = [s for s in ("cpmm", "summa")
                    if planner.admissible(s, pn, pk, pm, gx, gy,
                                          itemsize=isz,
                                          hbm_budget_bytes=budget)]
            if alts:
                hint += (f"; a staged reshard would make {alts[0]!r} "
                         "feasible here — set config."
                         "reshard_peak_budget_bytes > 0 so the "
                         "re-lays run as peak-bounded step sequences "
                         "(docs/RESHARD.md, MV109)")
            yield Diagnostic(
                code="MV105", severity="error", node=node_addr(n),
                message=f"strategy {strat!r} needs "
                        f"{need / 2**30:.2f} GiB per device "
                        f"(dims ({pn}, {pk}, {pm}), itemsize {isz}, "
                        f"{gx}x{gy} grid) but hbm_budget_bytes is "
                        f"{budget / 2**30:.2f} GiB — the replicated "
                        "operands cannot exist on the chip",
                fix_hint=hint)

    yield from walk(root)
