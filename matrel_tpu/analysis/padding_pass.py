"""Zero-padding invariant flow (MV103).

The system-wide invariant (core/padding.py): every lowered intermediate
is EXACTLY 0 outside its logical region, so matmul/add/elementwise-
multiply compose without masks. Ops whose math breaks that (scalar-add,
pow<=0, broadcasted add/sub, non-zero select fills, black-box join
merges — 0 op 0 != 0) must re-mask, and the executor does; but the
contract lives only in executor code and scattered tests. This pass
makes it DATA: :data:`PADDING_CONTRACT` mirrors each lowering's effect
on the invariant, and the checker walks the plan against it:

  * a node whose lowering breaks the invariant without a re-mask is an
    MV103 error (today that means the contract table was edited to
    match a lowering change that dropped a mask — the tripwire this
    pass exists for);
  * a node KIND the table does not know is an MV103 warning: a new op
    was added to the executor without declaring its padding behaviour,
    so the invariant can no longer be proven for any plan containing
    it.

One diagnostic per root cause, not a cascade per consumer: the report
points at the node that broke the invariant, not at the matmul three
levels up that would compute garbage from it.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr

#: Effect vocabulary: "clean" — preserves the invariant given clean
#: children; "remask" — the op breaks it but the lowering re-masks the
#: result to the logical region; "breaks" — breaks it with NO re-mask
#: (never emitted by the real contract below; the value exists so a
#: contract edit that mirrors a lost mask trips MV103 loudly).
CLEAN, REMASK, BREAKS = "clean", "remask", "breaks"


def _scalar_effect(node) -> str:
    op, v = node.attrs["op"], node.attrs["value"]
    if op == "mul":
        return CLEAN                       # 0 * v == 0
    if op == "add":
        return REMASK if v != 0.0 else CLEAN
    if op == "pow":
        return REMASK if v <= 0 else CLEAN  # 0**0 == 1, 0**-1 == inf
    return BREAKS                          # unknown scalar op: no proof


def _elemwise_effect(node) -> str:
    l, r = node.children
    if l.shape != r.shape and node.attrs["op"] != "mul":
        # broadcast writes real values into the padded region of the
        # size-1 operand's axis; executor re-masks all ops but mul
        # (0 * anything == 0 needs none)
        return REMASK
    return CLEAN  # 0 op 0 == 0 for add/sub/mul/min/max; div masks b==0


def _select_value_effect(node) -> str:
    # where(pred(x), x, fill): padding holds x == 0, so a non-zero fill
    # lands wherever pred(0) is False — executor re-masks exactly then
    return REMASK if node.attrs["fill"] != 0.0 else CLEAN


#: kind -> effect(node). The mirror of executor.Lowerer._eval's masking
#: behaviour — update BOTH together (tests/test_analysis.py seeds a
#: broken entry to prove the checker fires; the executor's own masking
#: is proven dynamically by test_executor/test_fuzz oracles).
PADDING_CONTRACT: Dict[str, Callable] = {
    "leaf": lambda n: CLEAN,          # constructors zero-pad
    "sparse_leaf": lambda n: CLEAN,   # to_dense scatters into zeros
    "coo_leaf": lambda n: CLEAN,      # to_block likewise
    "transpose": lambda n: CLEAN,
    "matmul": lambda n: CLEAN,        # 0-rows x 0-cols stay 0; SpGEMM/
                                      # SpMV paths pad their outputs
    "solve": lambda n: CLEAN,         # computes on logical slice, pads
    "inverse": lambda n: CLEAN,
    "elemwise": _elemwise_effect,
    "scalar": _scalar_effect,
    "agg": lambda n: REMASK,          # _mask_to_logical on every axis
    "vec": lambda n: CLEAN,           # logical slice, zero pad
    "rank1": lambda n: CLEAN,         # a + u.vT of clean operands
    "select_value": _select_value_effect,
    "select_index": lambda n: CLEAN,  # where(keep, x, 0) over x == 0
    "select_block": lambda n: CLEAN,
    "join_index": lambda n: REMASK,   # black-box merge: 0 op 0 != 0
    "join_value": lambda n: CLEAN,    # built from logical entries
    "join_rows": lambda n: CLEAN,     # merge on logical slices, pads
    "join_cols": lambda n: CLEAN,
}


def check_padding_flow(root, mesh, config,
                       contract: Dict[str, Callable] = None
                       ) -> Iterator[Diagnostic]:
    """Flow the invariant through the plan against ``contract``
    (default :data:`PADDING_CONTRACT`; injectable for fixture tests)."""
    rules = PADDING_CONTRACT if contract is None else contract
    seen: set = set()
    # the diagnostic fires AT the node that breaks/unknowns the
    # invariant — one report per root cause, no per-consumer cascade —
    # so the walk tracks only visited-ness, not a propagated dirty bit
    # (a carried bit would be dead state here, and wrong for re-mask
    # nodes, whose mask restores the region regardless of the child)

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        rule = rules.get(n.kind)
        if rule is None:
            yield Diagnostic(
                code="MV103", severity="warning", node=node_addr(n),
                message=f"node kind {n.kind!r} has no entry in the "
                        "padding contract — the zero-padding invariant "
                        "cannot be proven for this plan",
                fix_hint="declare the new lowering's effect in "
                         "analysis/padding_pass.PADDING_CONTRACT "
                         "(and re-mask in the executor if it breaks "
                         "the invariant)")
            return
        if rule(n) == BREAKS:
            yield Diagnostic(
                code="MV103", severity="error", node=node_addr(n),
                message=f"lowering of {n.kind!r} "
                        f"(attrs {_attr_summary(n)}) breaks the "
                        "zero-padding invariant and is not followed by "
                        "a re-mask — downstream matmuls/aggregates "
                        "would read garbage from the padded region",
                fix_hint="re-mask the result (_mask_to_logical) in the "
                         "executor, then mark the contract entry "
                         "'remask'")

    yield from walk(root)


def _attr_summary(n) -> str:
    keys = ("op", "value", "fill", "agg", "axis")
    got = {k: n.attrs[k] for k in keys if k in n.attrs}
    return repr(got) if got else "{}"
