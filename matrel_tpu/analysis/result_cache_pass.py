"""MV107 — result-cache consumption stamps must match the cache.

A plan that consumes a materialized-result-cache entry carries the
substitution stamp the session wrote (``attrs["result_cache"]``: the
layout and dtype the cache RECORDED at insertion, plus the entry's key
hash). The planner credited the reuse on exactly that recorded
layout/dtype — so a stamp that no longer agrees with the leaf's ACTUAL
matrix means the plan was costed (and will be reported by obs) on a
premise the cache no longer backs. The classic shape is a stamp kept
alive across an invalidation: a catalog rebind dropped the entry, and
a replayed or hand-built plan still claims it.

Warning severity, the MV102/MV106 class: the lowering reads the REAL
matrix on the leaf, so execution is numerically correct either way —
what is wrong is the plan's description of itself.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr

_FIX = ("re-run the query through the session so substitution "
        "re-stamps against the live cache entry")


def check_result_cache_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    seen: set = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        if n.kind == "leaf" and isinstance(
                n.attrs.get("result_cache"), dict):
            yield from _check_leaf(n, mesh)

    yield from walk(root)


def _check_leaf(n, mesh) -> Iterator[Diagnostic]:
    from matrel_tpu.parallel import planner
    rec = n.attrs["result_cache"]
    m = n.attrs.get("matrix")
    actual_dtype = str(np.dtype(getattr(m, "dtype", "float32")))
    actual_layout = planner._layout_of(n, mesh)
    stamped_layout = rec.get("layout")
    stamped_dtype = rec.get("dtype")
    if stamped_layout is not None and stamped_layout != actual_layout:
        yield Diagnostic(
            code="MV107", severity="warning", node=node_addr(n),
            message=(
                f"result-cache stamp claims layout {stamped_layout!r} "
                f"but the leaf's matrix lies {actual_layout!r} — the "
                f"planner credited a reuse the cache no longer backs "
                f"(stale stamp after invalidation?)"),
            fix_hint=_FIX)
    if stamped_dtype is not None and stamped_dtype != actual_dtype:
        yield Diagnostic(
            code="MV107", severity="warning", node=node_addr(n),
            message=(
                f"result-cache stamp claims dtype {stamped_dtype!r} "
                f"but the leaf's matrix carries {actual_dtype!r} — "
                f"autotune consults and HBM gates keyed on the wrong "
                f"itemsize"),
            fix_hint=_FIX)
