"""MV114 — fleet placement stamps must match the topology they
claim to be priced on (docs/FLEET.md).

Two hazard shapes, both the MV107 stale-stamp class:

1. **Span stamp vs topology.** A query the fleet placed as
   slice-SPANNING carries ``attrs["placement"]`` on the plan root
   with the weights it was priced under and the effective DCN weight
   its dominant collective was billed at
   (``serve/placement.effective_dcn_weight`` — the ONE helper the
   placer itself used). A stamp whose weights no longer match the
   verifying mesh's — or whose recorded DCN bill disagrees with what
   the shared helper derives from them — means the span/slice trade
   was decided on a topology this plan is not running on (a replayed
   stamp after re-calibration, or a hand-built plan smuggling a
   placement claim).

2. **Directory-hit substitution vs owning slice.** A result-cache
   leaf whose stamp carries ``fleet`` provenance was REPLICATED from
   another slice's cache; the owning slice's recorded layout/dtype
   rides the stamp. A replica whose own claims diverge from what the
   owner recorded is a migration that silently changed the value's
   shape-class — MV107 already proves stamp-vs-matrix, this proves
   stamp-vs-origin.

Warning severity (the MV102/MV106/MV107 class): execution reads the
real operands either way — what is wrong is the plan's description of
how it was priced. Free when no fleet stamps exist: plans without
them walk and yield nothing.
"""

from __future__ import annotations

from typing import Iterator

from matrel_tpu.analysis.diagnostics import Diagnostic, node_addr

_FIX = ("re-submit through the fleet so placement re-stamps against "
        "the live topology (serve/fleet.py)")
_FIX_REPL = ("drop and re-replicate the entry through the fleet API "
             "so the directory and the replica agree")


def check_placement_stamps(root, mesh, config) -> Iterator[Diagnostic]:
    stamp = root.attrs.get("placement") if hasattr(root, "attrs") \
        else None
    if isinstance(stamp, dict) and stamp.get("mode") == "span":
        yield from _check_span(root, stamp, mesh, config)
    seen: set = set()

    def walk(n) -> Iterator[Diagnostic]:
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            yield from walk(c)
        rc = n.attrs.get("result_cache")
        if (n.kind == "leaf" and isinstance(rc, dict)
                and isinstance(rc.get("fleet"), dict)):
            yield from _check_replica(n, rc)

    yield from walk(root)


def _check_span(root, stamp: dict, mesh, config) -> Iterator[Diagnostic]:
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.serve import placement as placement_lib
    live = mesh_lib.axis_weights(mesh, config)
    stamped_w = tuple(float(v) for v in (stamp.get("weights") or ())
                      if isinstance(v, (int, float)))
    if len(stamped_w) != 2:
        yield Diagnostic(
            code="MV114", severity="warning", node=node_addr(root),
            message=("span placement stamp carries no usable "
                     "topology weights — the span/slice trade "
                     "cannot be re-checked"),
            fix_hint=_FIX)
        return
    if stamped_w != tuple(live):
        yield Diagnostic(
            code="MV114", severity="warning", node=node_addr(root),
            message=(
                f"span placement stamp was priced under axis weights "
                f"{stamped_w} but this mesh resolves {tuple(live)} — "
                f"the DCN-crossing trade was decided on a topology "
                f"this plan is not running on (stale stamp after "
                f"re-calibration?)"),
            fix_hint=_FIX)
    expect = placement_lib.effective_dcn_weight(stamped_w)
    got = stamp.get("dcn_weight")
    if isinstance(got, (int, float)) and float(got) != expect:
        yield Diagnostic(
            code="MV114", severity="warning", node=node_addr(root),
            message=(
                f"span placement stamp bills the cut at weight "
                f"{got:g} but its own weights {stamped_w} derive "
                f"{expect:g} — the dominant collective was not "
                f"priced on the DCN axis weight"),
            fix_hint=_FIX)


def _check_replica(n, rc: dict) -> Iterator[Diagnostic]:
    fl = rc["fleet"]
    own_layout, own_dtype = rc.get("layout"), rc.get("dtype")
    rec_layout, rec_dtype = fl.get("layout"), fl.get("dtype")
    if (rec_dtype is not None and own_dtype is not None
            and rec_dtype != own_dtype):
        yield Diagnostic(
            code="MV114", severity="warning", node=node_addr(n),
            message=(
                f"replicated cache entry claims dtype {own_dtype!r} "
                f"but the owning slice recorded {rec_dtype!r} — the "
                f"migration changed the value's dtype class"),
            fix_hint=_FIX_REPL)
    if (rec_layout is not None and own_layout is not None
            and rec_layout not in (own_layout, "rep")
            and own_layout != "rep"):
        # replication legitimately re-lays the value (a gather to
        # replicated form is the staged move); only a claim of a
        # THIRD sharded layout neither side ever held is incoherent
        yield Diagnostic(
            code="MV114", severity="warning", node=node_addr(n),
            message=(
                f"replicated cache entry claims layout {own_layout!r} "
                f"but the owning slice recorded {rec_layout!r} and "
                f"neither side is replicated — the directory and the "
                f"replica disagree about the value's layout"),
            fix_hint=_FIX_REPL)
