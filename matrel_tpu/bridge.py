"""Execution bridge — the py4j-analogue entry point (BASELINE.json:5
north star: "the Scala DSL and SQL entrypoints stay intact behind a
py4j→JAX execution bridge"; SURVEY.md §7.8).

A newline-delimited JSON-RPC server over TCP, so a JVM-side (or any
non-Python) DSL shim can drive this framework the way the reference's Scala
DSL drives Spark: create/upload matrices, submit DSL/SQL queries, fetch
results. The protocol is deliberately tiny and language-neutral — py4j
itself is JVM-side tooling that cannot live in this image.

Protocol (one JSON object per line):
  {"id": 1, "method": "create_random", "params": {"name": "A", "shape": [64, 64], "seed": 0}}
  {"id": 2, "method": "upload",        "params": {"name": "X", "shape": [2, 2], "data": [[1, 2], [3, 4]]}}
  {"id": 3, "method": "sql",           "params": {"query": "rowsum(A * A)", "store": "R"}}
  {"id": 4, "method": "fetch",         "params": {"name": "R"}}
  {"id": 5, "method": "explain",       "params": {"query": "A * A"}}
  {"id": 6, "method": "tables"} | {"method": "shutdown"}
Responses: {"id": N, "result": ...} or {"id": N, "error": "..."}.
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import threading
from typing import Any, Dict, Optional

import numpy as np

from matrel_tpu.session import MatrelSession
from matrel_tpu.utils import lockdep

log = logging.getLogger("matrel_tpu.bridge")


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        server: "BridgeServer" = self.server  # type: ignore[assignment]
        for raw in self.rfile:
            line = raw.decode("utf-8").strip()
            if not line:
                continue
            try:
                req = json.loads(line)
                result = server.dispatch(req.get("method"), req.get("params") or {})
                resp = {"id": req.get("id"), "result": result}
            except _Shutdown:
                self.wfile.write(json.dumps(
                    {"id": req.get("id"), "result": "bye"}).encode() + b"\n")
                self.wfile.flush()
                threading.Thread(target=server.shutdown, daemon=True).start()
                return
            except Exception as e:  # noqa: BLE001 — protocol boundary
                resp = {"id": req.get("id") if isinstance(req, dict) else None,
                        "error": f"{type(e).__name__}: {e}"}
            self.wfile.write(json.dumps(resp).encode("utf-8") + b"\n")
            self.wfile.flush()


class _Shutdown(Exception):
    pass


class BridgeServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, session: Optional[MatrelSession] = None,
                 host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _Handler)
        self.session = session or MatrelSession.builder().get_or_create()
        self._lock = lockdep.make_lock("bridge.server")

    @property
    def port(self) -> int:
        return self.server_address[1]

    def serve_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    # -- RPC methods --------------------------------------------------------

    def dispatch(self, method: str, params: Dict[str, Any]) -> Any:
        with self._lock:  # session/catalog is not thread-safe
            if method == "create_random":
                m = self.session.random(tuple(params["shape"]),
                                        seed=int(params.get("seed", 0)))
                self.session.register(params["name"], m)
                return {"shape": list(m.shape)}
            if method == "upload":
                arr = np.asarray(params["data"], dtype=np.float32)
                if "shape" in params:
                    arr = arr.reshape(params["shape"])
                m = self.session.from_numpy(arr)
                self.session.register(params["name"], m)
                return {"shape": list(m.shape)}
            if method == "sql":
                e = self.session.sql(params["query"])
                out = self.session.compute(e)
                if params.get("store"):
                    self.session.register(params["store"], out)
                    return {"stored": params["store"], "shape": list(out.shape)}
                return {"data": out.to_numpy().tolist(),  # lockcheck: disable=LK102 bridge.server IS the RPC serializer: the session is not thread-safe, so each RPC (including result materialization) runs under it by design; no other thread ever waits on this lock for latency
                        "shape": list(out.shape)}
            if method == "fetch":
                m = self.session.table(params["name"])
                return {"data": m.to_numpy().tolist(),  # lockcheck: disable=LK102 same RPC-serializer design as "sql" above: fetch materializes under bridge.server deliberately
                        "shape": list(m.shape)}
            if method == "explain":
                return {"plan": self.session.explain(
                    self.session.sql(params["query"]))}
            if method == "tables":
                return {"tables": {n: list(m.shape)
                                   for n, m in self.session.catalog.items()}}
            if method == "shutdown":
                raise _Shutdown()
            raise ValueError(f"unknown method {method!r}")


class BridgeClient:
    """Minimal client for tests/other processes (the JVM shim's contract)."""

    def __init__(self, host: str, port: int):
        self.sock = socket.create_connection((host, port))
        self.f = self.sock.makefile("rwb")
        self._id = 0

    def call(self, method: str, **params) -> Any:
        self._id += 1
        req = {"id": self._id, "method": method, "params": params}
        self.f.write(json.dumps(req).encode() + b"\n")
        self.f.flush()
        resp = json.loads(self.f.readline())
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp["result"]

    def close(self):
        try:
            self.f.close()
        finally:
            self.sock.close()
