"""SQL-ish entry point over registered matrix tables.

The reference exposes matrix queries through SQL extensions on Spark SQL
(SURVEY.md §2 "SQL entry point" — syntax unverifiable from the empty mount,
confidence LOW, so this module defines a documented surface rather than
guessing the exact grammar): an expression language over the session
catalog, compiled to the same MatExpr IR as the DSL, hence optimized and
executed identically.

Grammar (Python-expression syntax, parsed via ``ast`` — no eval):
    SELECT <expr>
        [FROM t1, t2, ...]        -- restricts AND validates the visible
                                     tables against the session catalog
        [WHERE <pred over v>]     -- sugar for select(<expr>, "<pred>")
        [PRECISION '<sla>']       -- per-query accuracy SLA ("exact"/
                                     "high"/"fast"/explicit dtype) for
                                     precision-tiered execution
                                     (docs/PRECISION.md)
    <expr> :=
        A * B            matrix multiply        A + B | A - B  elementwise
        A .* B | A % B   element multiply       A / B          elementwise
        elemmin(A, B) | elemmax(A, B)           elementwise min/max
        2 * A | A * 2    scalar multiply        A + 2          scalar add
        transpose(A) | t(A)
        rowsum(e) colsum(e) sum(e) trace(e) vec(e)
        rowmax/rowmin/colmax/colmin/rowcount/rowavg/colcount/colavg(e)
        max/min/count/avg(e)                       global aggregates
        diagsum/diagmax/diagmin/diagcount/diagavg(e)   diagonal aggregates
        power(e, p)  norm(e [, "fro"|"l1"|"max"])
        rankone(a, u, v)   A + u·vᵀ (optimizer pushes through multiplies)
        select(e, "v > 0" [, fill])     σ on entry values
        selectrows(e, "i % 2 == 0")     σ on row index
        selectcols(e, "j < 4")          σ on col index
        selectblocks(e, "bi == bj", block_size)   σ on block index
        joinindex(a, b, "x * y")        ⋈ on index with merge expr
        joinrows(a, b, "x + y")         ⋈ on row index (pairwise cols)
        joincols(a, b, "x - y")         ⋈ on col index (pairwise rows)
            — index-join merges also accept the structured keywords
            ("left"/"right"/"add"/"mul"), which let the planner infer
            output dtypes (autotune reaches consuming multiplies)
        joinvalue(a, b, <merge>, <pred>)   ⋈ on values; merge/pred are
            either structured keywords ("left"/"right"/"add"/"mul" and
            "eq"/"lt"/"le"/"gt"/"ge" — these stream under aggregates)
            or expression strings over (x, y)

Predicate / merge strings are tiny lambdas over (v) / (i) / (j) /
(bi, bj) / (x, y), parsed with the same restricted-ast machinery.
``A .* B`` is lexed (quote-aware) to ``A % B`` before parsing.
Malformed input of any kind raises SqlError.
"""

from __future__ import annotations

import ast
import hashlib
from typing import Any, Callable, Dict

import jax.numpy as jnp

from matrel_tpu.ir import expr as E

_BINOPS = {
    ast.Add: "add", ast.Sub: "sub", ast.Div: "div",
}

_AGG_FNS = {
    "rowsum": ("sum", "row"), "colsum": ("sum", "col"),
    "sum": ("sum", "all"), "trace": ("sum", "diag"),
    "rowmax": ("max", "row"), "rowmin": ("min", "row"),
    "colmax": ("max", "col"), "colmin": ("min", "col"),
    "rowcount": ("count", "row"), "colcount": ("count", "col"),
    "rowavg": ("avg", "row"), "colavg": ("avg", "col"),
    # global + diagonal spellings — every executor kind×axis is reachable
    # from SQL (reference γ surface: sum/count/avg/max/min over
    # row/col/all/diag; SURVEY.md §2 "Physical: relational execs")
    "max": ("max", "all"), "min": ("min", "all"),
    "count": ("count", "all"), "avg": ("avg", "all"),
    "diagsum": ("sum", "diag"),
    "diagmax": ("max", "diag"), "diagmin": ("min", "diag"),
    "diagcount": ("count", "diag"), "diagavg": ("avg", "diag"),
}


class SqlError(ValueError):
    pass


def _parse_eval(src: str, what: str) -> ast.Expression:
    """ast.parse(mode='eval') with SyntaxError mapped into SqlError."""
    try:
        return ast.parse(src, mode="eval")
    except SyntaxError as e:
        raise SqlError(f"malformed {what}: {src!r} ({e.msg})") from e


def _compile_lambda(src: str, argnames: tuple) -> Callable:
    """Compile a restricted arithmetic/comparison expression into a fn over
    jnp arrays. Only names in ``argnames``, literals, arithmetic,
    comparisons, and boolean ops are allowed."""
    tree = _parse_eval(src, "predicate/merge expression")

    allowed = (ast.Expression, ast.BinOp, ast.UnaryOp, ast.Compare,
               ast.BoolOp, ast.Name, ast.Constant, ast.Load,
               ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Mod, ast.Pow,
               ast.USub, ast.UAdd, ast.Not,
               ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
               ast.And, ast.Or)
    for node in ast.walk(tree):
        if not isinstance(node, allowed):
            raise SqlError(f"disallowed syntax in predicate: "
                           f"{type(node).__name__} in {src!r}")
        if isinstance(node, ast.Name) and node.id not in argnames:
            raise SqlError(f"unknown name {node.id!r} in predicate {src!r}; "
                           f"allowed: {argnames}")

    def fn(*args):
        env = dict(zip(argnames, args))

        def ev(n):
            if isinstance(n, ast.Expression):
                return ev(n.body)
            if isinstance(n, ast.Constant):
                return n.value
            if isinstance(n, ast.Name):
                return env[n.id]
            if isinstance(n, ast.UnaryOp):
                v = ev(n.operand)
                if isinstance(n.op, ast.USub):
                    return -v
                if isinstance(n.op, ast.UAdd):
                    return +v
                return jnp.logical_not(v)
            if isinstance(n, ast.BinOp):
                l, r = ev(n.left), ev(n.right)
                return {ast.Add: lambda: l + r, ast.Sub: lambda: l - r,
                        ast.Mult: lambda: l * r, ast.Div: lambda: l / r,
                        ast.Mod: lambda: l % r, ast.Pow: lambda: l ** r,
                        }[type(n.op)]()
            if isinstance(n, ast.Compare):
                l = ev(n.left)
                out = None
                for op, cmp in zip(n.ops, n.comparators):
                    r = ev(cmp)
                    res = {ast.Eq: lambda: l == r, ast.NotEq: lambda: l != r,
                           ast.Lt: lambda: l < r, ast.LtE: lambda: l <= r,
                           ast.Gt: lambda: l > r, ast.GtE: lambda: l >= r,
                           }[type(op)]()
                    out = res if out is None else jnp.logical_and(out, res)
                    l = r
                return out
            if isinstance(n, ast.BoolOp):
                vals = [ev(v) for v in n.values]
                acc = vals[0]
                for v in vals[1:]:
                    acc = (jnp.logical_and(acc, v)
                           if isinstance(n.op, ast.And)
                           else jnp.logical_or(acc, v))
                return acc
            raise SqlError(f"unhandled node {type(n).__name__}")

        return ev(tree)

    # the session plan cache keys callables by this tag: identical query
    # text compiles to a fresh fn each parse, but must HIT the cache,
    # while different predicate text must MISS it (ADVICE r2 high)
    fn.__matrel_key__ = f"sql({','.join(argnames)}):{src}"
    return fn


class _Compiler(ast.NodeVisitor):
    def __init__(self, catalog: Dict[str, Any]):
        self.catalog = catalog

    def compile(self, src: str) -> E.MatExpr:
        tree = _parse_eval(src, "query expression")
        return self._expr(tree.body)

    def _expr(self, n: ast.AST):
        if isinstance(n, ast.Name):
            if n.id not in self.catalog:
                raise SqlError(f"unknown table {n.id!r}")
            return E.as_expr(self.catalog[n.id])
        if isinstance(n, ast.Constant) and isinstance(n.value, (int, float)):
            return float(n.value)
        if isinstance(n, ast.BinOp):
            return self._binop(n)
        if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.USub):
            v = self._expr(n.operand)
            if isinstance(v, float):
                return -v
            return v.multiply_scalar(-1.0)
        if isinstance(n, ast.Call):
            return self._call(n)
        raise SqlError(f"unsupported syntax: {type(n).__name__}")

    def _binop(self, n: ast.BinOp):
        l, r = self._expr(n.left), self._expr(n.right)
        scalar_l, scalar_r = isinstance(l, float), isinstance(r, float)
        if isinstance(n.op, ast.Mult):
            if scalar_l and scalar_r:
                return l * r
            if scalar_l:
                return r.multiply_scalar(l)
            if scalar_r:
                return l.multiply_scalar(r)
            return l.multiply(r)          # '*' between matrices = matmul
        if isinstance(n.op, ast.MatMult):
            return l.multiply(r)
        if isinstance(n.op, ast.Mod):
            # 'A .* B' lexes to 'A % B': element-wise multiply
            if scalar_l or scalar_r:
                raise SqlError(".* / % is matrix element-multiply; use "
                               "* for scalar multiply")
            return l.elem_multiply(r)
        if type(n.op) in _BINOPS:
            op = _BINOPS[type(n.op)]
            if scalar_r and op == "add":
                return l.add_scalar(r)
            if scalar_r and op == "sub":
                return l.add_scalar(-r)
            if scalar_r and op == "div":
                return l.multiply_scalar(1.0 / r)
            if scalar_l:
                raise SqlError("scalar on the left only supported for *")
            return E.elemwise(op, l, r)
        raise SqlError(f"unsupported operator {type(n.op).__name__}")

    def _call(self, n: ast.Call):
        name = n.func.id.lower() if isinstance(n.func, ast.Name) else None
        args = n.args
        if name in ("transpose", "t"):
            return self._expr(args[0]).t()
        if name in ("elemmult", "elemmul"):
            return self._expr(args[0]).elem_multiply(self._expr(args[1]))
        if name == "elemmin":
            return self._expr(args[0]).elem_min(self._expr(args[1]))
        if name == "elemmax":
            return self._expr(args[0]).elem_max(self._expr(args[1]))
        if name == "multiply":
            return self._expr(args[0]).multiply(self._expr(args[1]))
        if name == "add":
            return self._expr(args[0]).add(self._expr(args[1]))
        if name == "power":
            return self._expr(args[0]).power(self._lit(args[1]))
        if name == "vec":
            return self._expr(args[0]).vec()
        if name == "norm":
            kind = (self._str(args[1]) if len(args) > 1 else "fro")
            return self._expr(args[0]).norm(kind)
        if name in ("inverse", "inv"):
            return self._expr(args[0]).inverse()
        if name in ("rankone", "rankoneupdate"):
            return self._expr(args[0]).rank_one_update(
                self._expr(args[1]), self._expr(args[2]))
        if name == "solve":
            return self._expr(args[0]).solve(self._expr(args[1]))
        if name in _AGG_FNS:
            kind, axis = _AGG_FNS[name]
            return E.agg(self._expr(args[0]), kind, axis)
        if name == "select":
            pred = _compile_lambda(self._str(args[1]), ("v",))
            fill = self._lit(args[2]) if len(args) > 2 else 0.0
            return self._expr(args[0]).select_value(pred, fill=fill)
        if name == "selectrows":
            pred = _compile_lambda(self._str(args[1]), ("i",))
            return self._expr(args[0]).select_index(rows=pred)
        if name == "selectcols":
            pred = _compile_lambda(self._str(args[1]), ("j",))
            return self._expr(args[0]).select_index(cols=pred)
        if name == "joinindex":
            merge = self._merge_or_pred(args[2], E.JOIN_MERGES)
            return self._expr(args[0]).join_on_index(self._expr(args[1]), merge)
        if name in ("joinrows", "joincols"):
            from matrel_tpu.relational import ops as R
            merge = self._merge_or_pred(args[2], E.JOIN_MERGES)
            join = (R.join_on_rows if name == "joinrows"
                    else R.join_on_cols)
            return join(self._expr(args[0]), self._expr(args[1]), merge)
        if name == "joinvalue":
            merge = self._merge_or_pred(args[2], E.JOIN_MERGES)
            pred = (self._merge_or_pred(args[3], E.JOIN_PREDS)
                    if len(args) > 3 else None)
            return self._expr(args[0]).join_on_value(
                self._expr(args[1]), merge, pred)
        if name == "selectblocks":
            from matrel_tpu.relational import ops as R
            pred = _compile_lambda(self._str(args[1]), ("bi", "bj"))
            bs = int(self._lit(args[2])) if len(args) > 2 else None
            return R.select_blocks(self._expr(args[0]), pred,
                                   block_size=bs)
        raise SqlError(f"unknown function {name!r}")

    def _merge_or_pred(self, node, keywords):
        """Merge/predicate argument of ANY join function (joinvalue's
        merge+pred, and the merges of joinindex/joinrows/joincols): a
        structured keyword string (streams under aggregates; gives the
        planner dtype inference) or an (x, y) expression string."""
        s = self._str(node)
        if s in keywords:
            return s
        return _compile_lambda(s, ("x", "y"))

    @staticmethod
    def _str(node) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        raise SqlError("expected a string literal")

    @staticmethod
    def _lit(node) -> float:
        if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
            return float(node.value)
        if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
                and isinstance(node.operand, ast.Constant)):
            return -float(node.operand.value)
        raise SqlError("expected a numeric literal")


def _float_dot(q: str, i: int) -> bool:
    """Is the dot at q[i] part of a float literal (``2.*A`` = 2.0 * A)?
    Only when the preceding digit run is a NUMBER, not the tail of an
    identifier: ``t1.*t2`` is table t1 elem-multiplied by t2."""
    j = i
    while j > 0 and q[j - 1].isdigit():
        j -= 1
    if j == i:            # no digits before the dot
        return False
    return j == 0 or not (q[j - 1].isalpha() or q[j - 1] == "_")


def _lex_elemmul(q: str) -> str:
    """Replace the documented ``.*`` element-multiply token with ``%``
    outside string literals (quote-aware; string predicates keep their
    characters untouched)."""
    out = []
    quote = None
    i = 0
    while i < len(q):
        ch = q[i]
        if quote:
            out.append(ch)
            if ch == quote:
                quote = None
        elif ch in "'\"":
            quote = ch
            out.append(ch)
        elif (ch == "." and i + 1 < len(q) and q[i + 1] == "*"
                and not _float_dot(q, i)):
            out.append(" % ")
            i += 1
        else:
            out.append(ch)
        i += 1
    return "".join(out)


def _find_keyword(q: str, kw: str) -> int:
    """Start index of a word-boundary keyword OUTSIDE string literals,
    or -1. Quoted predicates containing the word are skipped."""
    quote = None
    n, k = len(q), len(kw)
    for i, ch in enumerate(q):
        if quote:
            if ch == quote:
                quote = None
            continue
        if ch in "'\"":
            quote = ch
            continue
        if (q[i:i + k].lower() == kw
                and (i == 0 or not (q[i - 1].isalnum()
                                    or q[i - 1] == "_"))
                and (i + k >= n or not (q[i + k].isalnum()
                                        or q[i + k] == "_"))):
            return i
    return -1


def parse_sql(query: str, session) -> E.MatExpr:
    """Compile a SQL-ish query against the session catalog into a
    MatExpr. FROM names are validated against the catalog AND restrict
    the tables visible to the body; WHERE is sugar for a value
    selection on the result."""
    q = query.strip()
    while q.endswith(";"):
        q = q[:-1].rstrip()
    # the SELECT keyword needs trailing whitespace — 'select(...)' (no
    # space) is the σ FUNCTION, not the keyword
    if q[:6].lower() == "select" and len(q) > 6 and q[6].isspace():
        q = q[6:].strip()
    q = _lex_elemmul(q)
    # trailing PRECISION '<sla>' clause — the SQL face of the per-query
    # accuracy SLA (session.run's precision= argument; docs/
    # PRECISION.md): stripped FIRST since it follows WHERE in the
    # statement. Quoted or bare spellings both accepted.
    prec_sla = None
    pi = _find_keyword(q, "precision")
    if pi >= 0:
        prec_src = q[pi + len("precision"):].strip()
        if prec_src[:1] in "'\"" and prec_src[:1] == prec_src[-1:] \
                and len(prec_src) >= 2:
            prec_src = prec_src[1:-1].strip()
        if not prec_src:
            raise SqlError("PRECISION requires an SLA value "
                           "('exact'/'high'/'fast'/explicit dtype)")
        from matrel_tpu.config import normalize_sla
        try:
            prec_sla = normalize_sla(prec_src)
        except ValueError as ex:
            raise SqlError(str(ex)) from ex
        q = q[:pi]
    where_src = None
    wi = _find_keyword(q, "where")
    if wi >= 0:
        where_src = q[wi + 5:].strip()
        if not where_src:
            raise SqlError("WHERE requires a predicate over v")
        q = q[:wi]
    fi = _find_keyword(q, "from")
    catalog = dict(session.catalog)
    if fi >= 0:
        names = [t.strip() for t in q[fi + 4:].split(",") if t.strip()]
        q = q[:fi]
        if not names:
            raise SqlError("FROM requires at least one table name")
        for t in names:
            if not t.isidentifier():
                raise SqlError(f"bad table name in FROM: {t!r}")
        unknown = sorted(t for t in names if t not in catalog)
        if unknown:
            raise SqlError(
                f"unknown table(s) in FROM: {unknown}; the session "
                f"catalog has {sorted(catalog)}")
        catalog = {t: catalog[t] for t in names}
    expr = _Compiler(catalog).compile(q.strip())
    if where_src is not None:
        expr = expr.select_value(_compile_lambda(where_src, ("v",)))
    # stamp the query-text fingerprint for the obs/ event log (the
    # session's query records carry source="sql" + this hash, so the
    # history CLI can group runs of the same statement). Out-of-band on
    # purpose: an attrs entry would flow into the plan-cache key and
    # split the cache between SQL- and DSL-built identical plans.
    # Scalar-only queries ("2 * 3") legitimately compile to a plain
    # number — nothing to stamp there.
    if isinstance(expr, E.MatExpr):
        object.__setattr__(
            expr, "_sql_hash",
            hashlib.sha1(query.strip().encode()).hexdigest()[:16])
        if prec_sla is not None:
            # out-of-band like _sql_hash: session._resolve_sla reads it
            # (an explicit run(precision=...) argument still wins) and
            # applies the tier-isolating cache prefix — an attrs entry
            # would redundantly split the plan cache a second way
            object.__setattr__(expr, "_sql_precision", prec_sla)
    return expr
