"""SQL-ish entry point over registered matrix tables.

The reference exposes matrix queries through SQL extensions on Spark SQL
(SURVEY.md §2 "SQL entry point" — syntax unverifiable from the empty mount,
confidence LOW, so this module defines a documented surface rather than
guessing the exact grammar): an expression language over the session
catalog, compiled to the same MatExpr IR as the DSL, hence optimized and
executed identically.

Grammar (Python-expression syntax, parsed via ``ast`` — no eval):
    SELECT <expr> [FROM t1, t2, ...]     -- FROM optional; names resolve
                                            against the session catalog
    <expr> :=
        A * B            matrix multiply        A + B | A - B  elementwise
        A .* B  → elemmul(A, B)                 A / B          elementwise
        2 * A | A * 2    scalar multiply        A + 2          scalar add
        transpose(A) | t(A)
        rowsum(e) colsum(e) sum(e) trace(e) vec(e)
        rowmax/rowmin/colmax/colmin/rowcount/rowavg/colcount/colavg(e)
        power(e, p)  norm(e [, "fro"|"l1"|"max"])
        select(e, "v > 0" [, fill])     σ on entry values
        selectrows(e, "i % 2 == 0")     σ on row index
        selectcols(e, "j < 4")          σ on col index
        joinindex(a, b, "x * y")        ⋈ on index with merge expr

Predicate / merge strings are tiny lambdas over (v) / (i) / (j) / (x, y),
parsed with the same restricted-ast machinery.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Callable, Dict

import jax.numpy as jnp

from matrel_tpu.ir import expr as E

_BINOPS = {
    ast.Add: "add", ast.Sub: "sub", ast.Div: "div",
}

_AGG_FNS = {
    "rowsum": ("sum", "row"), "colsum": ("sum", "col"),
    "sum": ("sum", "all"), "trace": ("sum", "diag"),
    "rowmax": ("max", "row"), "rowmin": ("min", "row"),
    "colmax": ("max", "col"), "colmin": ("min", "col"),
    "rowcount": ("count", "row"), "colcount": ("count", "col"),
    "rowavg": ("avg", "row"), "colavg": ("avg", "col"),
}


class SqlError(ValueError):
    pass


def _compile_lambda(src: str, argnames: tuple) -> Callable:
    """Compile a restricted arithmetic/comparison expression into a fn over
    jnp arrays. Only names in ``argnames``, literals, arithmetic,
    comparisons, and boolean ops are allowed."""
    tree = ast.parse(src, mode="eval")

    allowed = (ast.Expression, ast.BinOp, ast.UnaryOp, ast.Compare,
               ast.BoolOp, ast.Name, ast.Constant, ast.Load,
               ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Mod, ast.Pow,
               ast.USub, ast.UAdd, ast.Not,
               ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
               ast.And, ast.Or)
    for node in ast.walk(tree):
        if not isinstance(node, allowed):
            raise SqlError(f"disallowed syntax in predicate: "
                           f"{type(node).__name__} in {src!r}")
        if isinstance(node, ast.Name) and node.id not in argnames:
            raise SqlError(f"unknown name {node.id!r} in predicate {src!r}; "
                           f"allowed: {argnames}")

    def fn(*args):
        env = dict(zip(argnames, args))

        def ev(n):
            if isinstance(n, ast.Expression):
                return ev(n.body)
            if isinstance(n, ast.Constant):
                return n.value
            if isinstance(n, ast.Name):
                return env[n.id]
            if isinstance(n, ast.UnaryOp):
                v = ev(n.operand)
                if isinstance(n.op, ast.USub):
                    return -v
                if isinstance(n.op, ast.UAdd):
                    return +v
                return jnp.logical_not(v)
            if isinstance(n, ast.BinOp):
                l, r = ev(n.left), ev(n.right)
                return {ast.Add: lambda: l + r, ast.Sub: lambda: l - r,
                        ast.Mult: lambda: l * r, ast.Div: lambda: l / r,
                        ast.Mod: lambda: l % r, ast.Pow: lambda: l ** r,
                        }[type(n.op)]()
            if isinstance(n, ast.Compare):
                l = ev(n.left)
                out = None
                for op, cmp in zip(n.ops, n.comparators):
                    r = ev(cmp)
                    res = {ast.Eq: lambda: l == r, ast.NotEq: lambda: l != r,
                           ast.Lt: lambda: l < r, ast.LtE: lambda: l <= r,
                           ast.Gt: lambda: l > r, ast.GtE: lambda: l >= r,
                           }[type(op)]()
                    out = res if out is None else jnp.logical_and(out, res)
                    l = r
                return out
            if isinstance(n, ast.BoolOp):
                vals = [ev(v) for v in n.values]
                acc = vals[0]
                for v in vals[1:]:
                    acc = (jnp.logical_and(acc, v)
                           if isinstance(n.op, ast.And)
                           else jnp.logical_or(acc, v))
                return acc
            raise SqlError(f"unhandled node {type(n).__name__}")

        return ev(tree)

    return fn


class _Compiler(ast.NodeVisitor):
    def __init__(self, catalog: Dict[str, Any]):
        self.catalog = catalog

    def compile(self, src: str) -> E.MatExpr:
        tree = ast.parse(src, mode="eval")
        return self._expr(tree.body)

    def _expr(self, n: ast.AST):
        if isinstance(n, ast.Name):
            if n.id not in self.catalog:
                raise SqlError(f"unknown table {n.id!r}")
            return E.as_expr(self.catalog[n.id])
        if isinstance(n, ast.Constant) and isinstance(n.value, (int, float)):
            return float(n.value)
        if isinstance(n, ast.BinOp):
            return self._binop(n)
        if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.USub):
            v = self._expr(n.operand)
            if isinstance(v, float):
                return -v
            return v.multiply_scalar(-1.0)
        if isinstance(n, ast.Call):
            return self._call(n)
        raise SqlError(f"unsupported syntax: {type(n).__name__}")

    def _binop(self, n: ast.BinOp):
        l, r = self._expr(n.left), self._expr(n.right)
        scalar_l, scalar_r = isinstance(l, float), isinstance(r, float)
        if isinstance(n.op, ast.Mult):
            if scalar_l and scalar_r:
                return l * r
            if scalar_l:
                return r.multiply_scalar(l)
            if scalar_r:
                return l.multiply_scalar(r)
            return l.multiply(r)          # '*' between matrices = matmul
        if isinstance(n.op, ast.MatMult):
            return l.multiply(r)
        if type(n.op) in _BINOPS:
            op = _BINOPS[type(n.op)]
            if scalar_r and op == "add":
                return l.add_scalar(r)
            if scalar_r and op == "sub":
                return l.add_scalar(-r)
            if scalar_r and op == "div":
                return l.multiply_scalar(1.0 / r)
            if scalar_l:
                raise SqlError("scalar on the left only supported for *")
            return E.elemwise(op, l, r)
        raise SqlError(f"unsupported operator {type(n.op).__name__}")

    def _call(self, n: ast.Call):
        name = n.func.id.lower() if isinstance(n.func, ast.Name) else None
        args = n.args
        if name in ("transpose", "t"):
            return self._expr(args[0]).t()
        if name in ("elemmult", "elemmul"):
            return self._expr(args[0]).elem_multiply(self._expr(args[1]))
        if name == "multiply":
            return self._expr(args[0]).multiply(self._expr(args[1]))
        if name == "add":
            return self._expr(args[0]).add(self._expr(args[1]))
        if name == "power":
            return self._expr(args[0]).power(self._lit(args[1]))
        if name == "vec":
            return self._expr(args[0]).vec()
        if name == "norm":
            kind = (self._str(args[1]) if len(args) > 1 else "fro")
            return self._expr(args[0]).norm(kind)
        if name in ("inverse", "inv"):
            return self._expr(args[0]).inverse()
        if name == "solve":
            return self._expr(args[0]).solve(self._expr(args[1]))
        if name in _AGG_FNS:
            kind, axis = _AGG_FNS[name]
            return E.agg(self._expr(args[0]), kind, axis)
        if name == "select":
            pred = _compile_lambda(self._str(args[1]), ("v",))
            fill = self._lit(args[2]) if len(args) > 2 else 0.0
            return self._expr(args[0]).select_value(pred, fill=fill)
        if name == "selectrows":
            pred = _compile_lambda(self._str(args[1]), ("i",))
            return self._expr(args[0]).select_index(rows=pred)
        if name == "selectcols":
            pred = _compile_lambda(self._str(args[1]), ("j",))
            return self._expr(args[0]).select_index(cols=pred)
        if name == "joinindex":
            merge = _compile_lambda(self._str(args[2]), ("x", "y"))
            return self._expr(args[0]).join_on_index(self._expr(args[1]), merge)
        raise SqlError(f"unknown function {name!r}")

    @staticmethod
    def _str(node) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        raise SqlError("expected a string literal")

    @staticmethod
    def _lit(node) -> float:
        if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
            return float(node.value)
        if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
                and isinstance(node.operand, ast.Constant)):
            return -float(node.operand.value)
        raise SqlError("expected a numeric literal")


_SELECT_RE = re.compile(r"^\s*select\s+(.*?)(\s+from\s+[\w\s,]+)?\s*;?\s*$",
                        re.IGNORECASE | re.DOTALL)


def parse_sql(query: str, session) -> E.MatExpr:
    """Compile a SQL-ish query against the session catalog into a MatExpr."""
    m = _SELECT_RE.match(query)
    body = m.group(1) if m else query
    return _Compiler(session.catalog).compile(body.strip())
